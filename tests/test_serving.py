"""Continuous-batching serving engine over the stacked KV ring cache.

Contracts under test:
  * token-for-token greedy parity: a request stream pushed through the
    engine's churning slots must produce EXACTLY the tokens sequential
    FusedDecoder.generate() calls produce (per-slot positions, masked
    in-slot prefill, and per-slot logit controls must all be invisible);
  * zero-recompile churn: slot free/re-admit is pure data — the engine's
    trace-count spy must not move after warmup;
  * the full-cache guard in the decode_attention write kernels (the
    eviction invariant the engine relies on): a row at cache_lens ==
    Smax drops the write instead of corrupting neighbouring blocks.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import jax.numpy as jnp

from paddle_tpu.incubate.nn import FusedMultiTransformer
from paddle_tpu.inference.generation import FusedDecoder
from paddle_tpu.inference.serving import ServingEngine
from paddle_tpu.nn.layer.common import Embedding, Linear

V, E, H, FF, L = 97, 32, 4, 64, 2


def _model(seed=3):
    paddle.seed(seed)
    embed = Embedding(V, E)
    fmt = FusedMultiTransformer(E, H, FF, num_layers=L,
                                normalize_before=True)
    head = Linear(E, V, bias_attr=False)
    fmt.eval()
    return fmt, embed, head


def _prompt(rng, n):
    return rng.randint(1, V, (n,)).astype(np.int32)


def _oracle(fmt, embed, head, prompt, use_rotary=False, **kw):
    dec = FusedDecoder(fmt, embed, head, max_seq_len=128,
                       use_rotary=use_rotary)
    out = dec.generate(paddle.to_tensor(prompt[None]), **kw)
    return np.asarray(out._data)[0, prompt.size:]


class TestServingParity:
    @pytest.mark.parametrize("bulk,rotary", [
        ("1", False), ("0", False), ("1", True), ("0", True)])
    def test_greedy_tokens_match_sequential_decode(self, monkeypatch,
                                                   bulk, rotary):
        """5 mixed-length requests churned through 2 slots == 5
        sequential FusedDecoder.generate() calls, token for token —
        for BOTH in-slot prefill flavors (bulk flash / masked scan) and,
        with rotary on, the vector-t rope branch (each slot's rope at
        its OWN per-row position)."""
        monkeypatch.setenv("PADDLE_TPU_SERVE_BULK", bulk)
        fmt, embed, head = _model()
        rng = np.random.RandomState(0)
        reqs = [(_prompt(rng, s), m)
                for s, m in [(5, 6), (3, 4), (7, 8), (4, 5), (6, 3)]]
        eng = ServingEngine(fmt, embed, head, num_slots=2,
                            max_seq_len=128, decode_chunk=2,
                            use_rotary=rotary)
        rids = [eng.submit(p, max_new_tokens=m) for p, m in reqs]
        eng.run()
        for (p, m), rid in zip(reqs, rids):
            want = _oracle(fmt, embed, head, p, use_rotary=rotary,
                           max_new_tokens=m)
            np.testing.assert_array_equal(
                eng.results[rid]["tokens"], want)

    def test_per_slot_logit_controls_match_sequential(self):
        """eos / min_length / repetition_penalty are PER-SLOT data (no
        retrace): concurrent requests with different controls must each
        match their own sequential run."""
        fmt, embed, head = _model()
        rng = np.random.RandomState(1)
        reqs = [
            (_prompt(rng, 5), dict(max_new_tokens=10, eos_token_id=7,
                                   min_length=3)),
            (_prompt(rng, 4), dict(max_new_tokens=8, eos_token_id=2,
                                   repetition_penalty=1.5)),
            (_prompt(rng, 6), dict(max_new_tokens=6)),
            (_prompt(rng, 5), dict(max_new_tokens=12, eos_token_id=43)),
        ]
        eng = ServingEngine(fmt, embed, head, num_slots=2,
                            max_seq_len=128, decode_chunk=2,
                            enable_repetition_penalty=True)
        rids = [eng.submit(p, **kw) for p, kw in reqs]
        eng.run()
        for (p, kw), rid in zip(reqs, rids):
            want = _oracle(fmt, embed, head, p, **kw)
            np.testing.assert_array_equal(
                eng.results[rid]["tokens"], want)

    def test_int8_cache_mode_parity(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_DECODE_INT8_CACHE", "1")
        fmt, embed, head = _model()
        rng = np.random.RandomState(2)
        reqs = [(_prompt(rng, s), m) for s, m in [(5, 6), (3, 5)]]
        eng = ServingEngine(fmt, embed, head, num_slots=2,
                            max_seq_len=128, decode_chunk=2)
        rids = [eng.submit(p, max_new_tokens=m) for p, m in reqs]
        eng.run()
        for (p, m), rid in zip(reqs, rids):
            want = _oracle(fmt, embed, head, p, max_new_tokens=m)
            np.testing.assert_array_equal(
                eng.results[rid]["tokens"], want)


class TestServingChurn:
    def test_slot_reuse_without_retrace(self):
        """The zero-recompile contract: after the warmup requests have
        exercised the engine's (bounded) executable set, 3 x num_slots
        more requests churning through freed slots must not trace
        anything new — admission/eviction is data, not structure."""
        fmt, embed, head = _model(seed=11)
        rng = np.random.RandomState(3)
        eng = ServingEngine(fmt, embed, head, num_slots=2,
                            max_seq_len=128, decode_chunk=2)
        # warmup: same shape-buckets the churn phase will use
        for _ in range(2):
            eng.submit(_prompt(rng, 5), max_new_tokens=6,
                       eos_token_id=7)
        eng.run()
        warm_traces = eng.metrics()["traces"]
        assert warm_traces > 0

        for _ in range(6):                    # 3 x num_slots
            eng.submit(_prompt(rng, 5), max_new_tokens=6,
                       eos_token_id=7)
        eng.run()
        m = eng.metrics()
        assert m["requests_admitted"] == 8
        assert m["requests_finished"] == 8
        assert m["traces"] == warm_traces, (
            f"slot churn retraced: {warm_traces} -> {m['traces']}")

    def test_submit_enforces_ring_capacity_invariant(self):
        """prompt + max_new_tokens > Smax could push cache_lens to Smax
        (the write kernels' documented invariant) — must refuse at
        submit, not corrupt at decode."""
        fmt, embed, head = _model(seed=12)
        eng = ServingEngine(fmt, embed, head, num_slots=1,
                            max_seq_len=128)
        with pytest.raises(ValueError, match="Smax"):
            eng.submit(np.ones(100, np.int32), max_new_tokens=29)
        # exactly at capacity is fine (cache_lens peaks at Smax - 1)
        rid = eng.submit(np.ones(4, np.int32), max_new_tokens=124)
        assert rid == 0

    def test_tokens_per_sec_zero_elapsed_guard(self):
        """A frozen clock leaves busy_s == 0.0 with tokens already
        emitted (e.g. a metrics() call after the first step under a
        coarse virtual clock): tokens_per_sec must read 0.0 — never a
        ZeroDivisionError, and never None once tokens exist."""
        fmt, embed, head = _model(seed=15)
        rng = np.random.RandomState(6)
        eng = ServingEngine(fmt, embed, head, num_slots=1,
                            max_seq_len=128, decode_chunk=2,
                            clock=lambda: 0.0)
        eng.submit(_prompt(rng, 4), max_new_tokens=3)
        # chunked admission may spend the first step(s) purely on
        # prefill — step until the first token lands (still under the
        # frozen clock, which is what the guard is about)
        while not eng.metrics()["tokens_emitted"]:
            eng.step()
        m = eng.metrics()
        assert m["tokens_emitted"] > 0
        assert m["busy_s"] == 0.0
        assert m["tokens_per_sec"] == 0.0
        # a truly idle engine still reports None (nothing to rate)
        fresh = ServingEngine(fmt, embed, head, num_slots=1,
                              max_seq_len=128)
        assert fresh.metrics()["tokens_per_sec"] is None

    def test_metrics_surface(self):
        fmt, embed, head = _model(seed=13)
        rng = np.random.RandomState(4)
        eng = ServingEngine(fmt, embed, head, num_slots=2,
                            max_seq_len=128, decode_chunk=2)
        eng.submit(_prompt(rng, 5), max_new_tokens=4)
        eng.submit(_prompt(rng, 3), max_new_tokens=6)
        eng.run()
        m = eng.metrics()
        assert m["tokens_emitted"] == 10
        assert m["requests_finished"] == 2
        assert m["tokens_per_sec"] > 0
        assert m["ttft_p50_s"] is not None and m["ttft_p50_s"] >= 0
        assert m["latency_p99_s"] >= m["ttft_p50_s"]
        # per-chunk records: occupancy/queue/step latency emitted every
        # chunk boundary
        assert eng.chunk_log
        rec = eng.chunk_log[0]
        for k in ("step_s", "new_tokens", "occupancy", "queue_depth",
                  "traces"):
            assert k in rec


class TestFullCacheGuard:
    """The decode_attention write kernels' cache_lens < Smax invariant:
    a full row must DROP the write (clamped to the last block), leaving
    the cache byte-identical — not address one block past the grid."""

    def test_fp_write_full_row_drops(self):
        from paddle_tpu.ops.pallas import decode_attention as da
        rng = np.random.RandomState(0)
        Lk, B, Hd, D, S = 2, 2, 4, 32, 128
        caches = jnp.asarray(rng.randn(Lk, 2, B, Hd, S, D), jnp.float32)
        q = jnp.asarray(rng.randn(B, Hd, 1, D), jnp.float32)
        kv = jnp.asarray(rng.randn(2, B, Hd, 1, D), jnp.float32)
        lens = jnp.asarray([S, 5], jnp.int32)      # row 0 is FULL
        c2, o = da.decode_attention_stacked_write(q, kv, caches, 0, lens)
        assert bool(jnp.isfinite(o).all())
        np.testing.assert_array_equal(np.asarray(c2[0, :, 0]),
                                      np.asarray(caches[0, :, 0]))
        # the non-full row still lands its write at position 5
        np.testing.assert_allclose(np.asarray(c2[0, 0, 1, :, 5, :]),
                                   np.asarray(kv[0, 1, :, 0, :]),
                                   rtol=1e-6)

    def test_i8_write_full_row_drops(self):
        from paddle_tpu.ops.pallas import decode_attention as da
        rng = np.random.RandomState(1)
        Lk, B, Hd, D, S = 2, 2, 4, 32, 128
        ci8 = jnp.ones((Lk, 2, B, Hd, S, D), jnp.int8)
        sc = jnp.ones((Lk, 2, B, Hd, 1, S), jnp.float32)
        q = jnp.asarray(rng.randn(B, Hd, 1, D), jnp.float32)
        kv = jnp.asarray(rng.randn(2, B, Hd, 1, D), jnp.float32)
        lens = jnp.asarray([S, 5], jnp.int32)
        c2, s2, o = da.decode_attention_stacked_i8_write(
            q, kv, ci8, sc, 0, lens)
        assert bool(jnp.isfinite(o).all())
        np.testing.assert_array_equal(np.asarray(c2[0, :, 0]),
                                      np.asarray(ci8[0, :, 0]))
        np.testing.assert_array_equal(np.asarray(s2[0, :, 0]),
                                      np.asarray(sc[0, :, 0]))
        # non-full row's int8 write landed
        assert not bool((c2[0, 0, 1, :, 5, :] ==
                         ci8[0, 0, 1, :, 5, :]).all())

    def test_engine_request_at_exact_capacity(self):
        """A request sized so its final write lands at Smax - 1 (the
        invariant's boundary) must complete cleanly."""
        fmt, embed, head = _model(seed=14)
        rng = np.random.RandomState(5)
        eng = ServingEngine(fmt, embed, head, num_slots=1,
                            max_seq_len=128, decode_chunk=2)
        p = _prompt(rng, 120)
        rid = eng.submit(p, max_new_tokens=8)
        eng.run()
        assert eng.results[rid]["tokens"].size == 8
        assert int(eng._lens[0]) == 127      # peaked at Smax - 1


class TestOverloadShedding:
    """Robustness satellites (ISSUE 3): bounded admission queue + per-
    request deadlines over the existing eviction machinery."""

    def test_max_pending_rejects_cleanly_then_drains(self):
        from paddle_tpu.inference.serving import AdmissionFull
        fmt, embed, head = _model(seed=21)
        rng = np.random.RandomState(0)
        eng = ServingEngine(fmt, embed, head, num_slots=2,
                            max_seq_len=128, decode_chunk=2,
                            max_pending=3)
        for _ in range(3):
            eng.submit(_prompt(rng, 4), max_new_tokens=3)
        with pytest.raises(AdmissionFull):
            eng.submit(_prompt(rng, 4), max_new_tokens=3)
        assert eng.metrics()["requests_rejected"] == 1
        eng.run()                        # shed != broken: queue drains
        assert eng.metrics()["requests_finished"] == 3
        # capacity freed -> admission works again
        rid = eng.submit(_prompt(rng, 4), max_new_tokens=2)
        eng.run()
        assert eng.results[rid]["tokens"].size == 2

    def test_deadline_evicts_queued_and_running(self):
        fmt, embed, head = _model(seed=22)
        rng = np.random.RandomState(1)
        clk = [0.0]
        eng = ServingEngine(fmt, embed, head, num_slots=1,
                            max_seq_len=128, decode_chunk=2,
                            clock=lambda: clk[0])
        rid_run = eng.submit(_prompt(rng, 4), max_new_tokens=60,
                             deadline_s=5.0)
        rid_q = eng.submit(_prompt(rng, 4), max_new_tokens=4,
                           deadline_s=1.0)
        eng.step()                       # admits rid_run; rid_q queued
        assert eng.results == {}
        clk[0] = 2.0
        eng.step()                       # rid_q shed from the queue
        assert eng.results[rid_q]["expired"] is True
        assert eng.results[rid_q]["tokens"].size == 0
        clk[0] = 6.0
        eng.step()                       # rid_run evicted mid-decode
        assert eng.results[rid_run]["expired"] is True
        assert not eng._active.any()
        assert eng.metrics()["requests_expired"] == 2
        # the evicted slot is reusable: a fresh request completes
        rid3 = eng.submit(_prompt(rng, 5), max_new_tokens=3)
        eng.run()
        assert eng.results[rid3]["expired"] is False
        assert eng.results[rid3]["tokens"].size == 3
        # expired requests are shed, not finished: they stay out of the
        # finished count and the latency percentiles
        m = eng.metrics()
        assert m["requests_finished"] == 1
        assert m["requests_expired"] == 2

    def test_reset_metrics_zeroes_shed_counters(self):
        """reset_metrics() must zero rejected/expired alongside admitted,
        or a post-warmup shed-rate computed from one metrics() snapshot
        mixes windows."""
        from paddle_tpu.inference.serving import AdmissionFull
        fmt, embed, head = _model(seed=24)
        rng = np.random.RandomState(3)
        eng = ServingEngine(fmt, embed, head, num_slots=2,
                            max_seq_len=128, decode_chunk=2,
                            max_pending=1)
        eng.submit(_prompt(rng, 4), max_new_tokens=2)
        with pytest.raises(AdmissionFull):
            eng.submit(_prompt(rng, 4), max_new_tokens=2)
        eng.run()
        assert eng.metrics()["requests_rejected"] == 1
        eng.reset_metrics()
        m = eng.metrics()
        assert m["requests_admitted"] == 0
        assert m["requests_rejected"] == 0
        assert m["requests_expired"] == 0

    def test_no_deadline_is_unbounded(self):
        fmt, embed, head = _model(seed=23)
        rng = np.random.RandomState(2)
        clk = [0.0]
        eng = ServingEngine(fmt, embed, head, num_slots=1,
                            max_seq_len=128, decode_chunk=2,
                            clock=lambda: clk[0])
        rid = eng.submit(_prompt(rng, 4), max_new_tokens=4)
        clk[0] = 1e6                     # ancient request, no deadline
        eng.run()
        assert eng.results[rid]["expired"] is False
        assert eng.results[rid]["tokens"].size == 4


class TestPrefixCacheServing:
    """Prefix-cache KV reuse inside the engine (ISSUE 4): deterministic
    on/off parity across admission/eviction churn, zero retraces after
    warmup with caching enabled, the one-knob prefill/block ladder, and
    the full-counter metrics reset."""

    def _shared_reqs(self, rng, n=12, n_prefixes=3):
        prefixes = [_prompt(rng, 8) for _ in range(n_prefixes)]
        # lead with an exactly-block-aligned prompt twice: the repeat is
        # a FULLY-cached prompt, whose final block must be dropped so
        # the first-token sample still has a suffix token — and its
        # 1-block adopt ladder bucket compiles up front (warmup must
        # exercise every K bucket the churn phase will reuse)
        reqs = [(prefixes[0].copy(), 3), (prefixes[0].copy(), 3)]
        for i in range(n):
            sfx = _prompt(rng, 2 + i % 5)
            reqs.append((np.concatenate([prefixes[i % n_prefixes], sfx]),
                         4))
        return reqs

    @pytest.mark.parametrize("sample", [False, True])
    def test_on_off_parity_across_eviction_churn(self, sample,
                                                 serving_metrics_ok):
        """Enabling the prefix cache must never change sampled outputs —
        even with a pool so small (3 blocks vs 2-block prefixes) that
        admission constantly evicts and republishes blocks."""
        fmt, embed, head = _model(seed=31)
        rng = np.random.RandomState(5)
        reqs = self._shared_reqs(rng)

        def run(blocks):
            paddle.seed(0)               # identical sampling key stream
            eng = ServingEngine(fmt, embed, head, num_slots=2,
                                max_seq_len=128, decode_chunk=2,
                                prefill_cap=4, prefix_cache_blocks=blocks,
                                do_sample=sample, top_k=5)
            rids = [eng.submit(p, max_new_tokens=m) for p, m in reqs]
            eng.run()
            return eng, [eng.results[r]["tokens"] for r in rids]

        eng_on, toks_on = run(3)
        eng_off, toks_off = run(0)
        for a, b in zip(toks_on, toks_off):
            np.testing.assert_array_equal(a, b)
        m = serving_metrics_ok(eng_on)
        serving_metrics_ok(eng_off)
        assert m["prefix_hits"] > 0                 # reuse really happened
        assert m["prefill_tokens_saved"] > 0
        assert m["prefix_store"]["evictions"] > 0   # ... under churn

    def test_zero_retraces_after_warmup_with_cache(self,
                                                   serving_metrics_ok):
        """The adopt/commit copy paths ride the same bounded pow-2
        executable ladders as prefill: once warmup has exercised the
        buckets, shared-prefix churn must not trace anything new."""
        fmt, embed, head = _model(seed=32)
        rng = np.random.RandomState(6)
        reqs = self._shared_reqs(rng)
        eng = ServingEngine(fmt, embed, head, num_slots=2,
                            max_seq_len=128, decode_chunk=2,
                            prefill_cap=4, prefix_cache_blocks=16)
        for p, m in reqs[:7]:
            eng.submit(p, max_new_tokens=m)
        eng.run()
        warm = eng.metrics()["traces"]
        assert warm > 0
        for p, m in reqs[7:]:
            eng.submit(p, max_new_tokens=m)
        eng.run()
        m = serving_metrics_ok(eng)
        assert m["traces"] == warm, (
            f"prefix-cache churn retraced: {warm} -> {m['traces']}")
        assert m["prefix_hits"] > 0

    def test_prefill_cap_knob_and_validation(self, monkeypatch):
        """prefill_cap is the ONE knob for the prefill chunk ladder and
        the prefix block size: constructor arg, env default, pow-2
        validated."""
        fmt, embed, head = _model(seed=33)
        with pytest.raises(ValueError, match="power of two"):
            ServingEngine(fmt, embed, head, num_slots=1, max_seq_len=128,
                          prefill_cap=24)
        monkeypatch.setenv("PADDLE_SERVING_PREFILL_CAP", "12")
        with pytest.raises(ValueError, match="power of two"):
            ServingEngine(fmt, embed, head, num_slots=1, max_seq_len=128)
        monkeypatch.setenv("PADDLE_SERVING_PREFILL_CAP", "8")
        eng = ServingEngine(fmt, embed, head, num_slots=1,
                            max_seq_len=128, prefix_cache_blocks=4)
        assert eng.prefill_cap == 8
        assert eng.prefix_cache.block_tokens == 8       # ladders aligned
        assert eng._prefill_chunks(20) == [8, 8, 4]
        # explicit arg wins over env
        eng2 = ServingEngine(fmt, embed, head, num_slots=1,
                             max_seq_len=128, prefill_cap=16)
        assert eng2.prefill_cap == 16

    def test_reset_metrics_zeroes_every_counter(self):
        """PR 3 missed requests_rejected/expired on the first pass; this
        pins the FULL surface: after reset_metrics(keep_results=False),
        every metrics() key except the trace spy (documented: never
        reset) and the store-lifetime prefix_store stats must read
        exactly like a fresh engine's."""
        fmt, embed, head = _model(seed=34)
        rng = np.random.RandomState(7)
        eng = ServingEngine(fmt, embed, head, num_slots=2,
                            max_seq_len=128, decode_chunk=2,
                            prefill_cap=4, prefix_cache_blocks=8)
        fresh = eng.metrics()
        for _ in range(3):
            eng.submit(_prompt(rng, 9), max_new_tokens=3)
        eng.run()
        m = eng.metrics()
        moved = [k for k in fresh
                 if k != "prefix_store" and m[k] != fresh[k]]
        assert "prefix_hits" in moved or "prefix_misses" in moved
        assert "prefill_tokens_computed" in moved
        eng.reset_metrics(keep_results=False)
        after = eng.metrics()
        for k in fresh:
            if k in ("traces", "prefix_store", "kv_blocks_total",
                     "kv_blocks_used", "kv_blocks_free"):
                # allocator STATE, not window counters: published
                # prefix blocks legitimately stay resident across a
                # metrics reset (like the trace spy and store stats)
                continue
            assert after[k] == fresh[k], (
                f"reset_metrics missed {k}: {after[k]!r} != fresh "
                f"{fresh[k]!r}")


@pytest.mark.slow
class TestServingBench:
    def test_bench_serving_poisson_sweep(self, monkeypatch, capsys,
                                         tmp_path):
        """The Poisson workload sweep (continuous vs static batching on
        the same compiled step). Slow-marked: tier-1 covers the engine
        through the unit tests above; this drives the full bench."""
        import json
        import bench_serving
        # the bench writes BENCH_serving.json next to its own file —
        # point it at tmp so the committed record isn't clobbered by CI
        monkeypatch.setattr(bench_serving, "__file__",
                            str(tmp_path / "bench_serving.py"))
        monkeypatch.setenv("BENCH_SERVE_REQUESTS", "12")
        monkeypatch.setenv("BENCH_SERVE_WARMUP", "4")
        monkeypatch.setenv("BENCH_SLOTS", "4")
        rc = bench_serving.main()
        assert rc == 0
        rec = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert rec["retraces_after_warmup"] == 0
        # timing-dependent: assert with margin below the 1.5x the full
        # fixed-seed bench shows (12 requests here, CI jitter)
        assert rec["speedup_vs_static"] > 1.1

    def test_bench_shared_prompt_prefix_cache_sweep(self, monkeypatch,
                                                    capsys, tmp_path):
        """The Poisson shared-prompt sweep (prefix cache on vs off at
        equal compiled shape). Slow-marked like the classic sweep: tier-1
        covers the cache through the unit/parity tests; this drives the
        full A/B bench and its acceptance gates (hit-rate, no retraces,
        TTFT not worse)."""
        import json
        import bench_serving
        monkeypatch.setattr(bench_serving, "__file__",
                            str(tmp_path / "bench_serving.py"))
        monkeypatch.setenv("BENCH_SERVE_REQUESTS", "12")
        monkeypatch.setenv("BENCH_PREFIX_TEMPLATES", "3")
        rc = bench_serving.main(["--shared-prompts"])
        assert rc == 0
        rec = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert rec["retraces_after_warmup"] == 0
        assert rec["prefix_hit_rate"] > 0.5
        assert rec["prefill_tokens_saved"] > \
            rec["prefill_tokens_computed"]
        # timing-dependent with margin (the full fixed-seed bench shows
        # ~1.4x tokens/s and ~2x better TTFT p50; 12 requests here)
        assert rec["value"] > 1.1
        assert rec["ttft_p50_ms_on"] < rec["ttft_p50_ms_off"]

    def test_bench_paged_kv_sweep(self, monkeypatch, capsys, tmp_path):
        """The paged-KV capacity A/B (equal KV memory, 4x slots; plus
        the equal-slot per-step-cost check and the exact token-parity
        gate). Slow-marked like the other sweeps: tier-1 covers the
        paged layout through tests/test_paged_kv.py; this drives the
        full bench. Output redirects to tmp so CI can't clobber the
        committed record."""
        import json
        import bench_serving
        monkeypatch.setattr(bench_serving, "__file__",
                            str(tmp_path / "bench_serving.py"))
        monkeypatch.setenv("BENCH_SERVE_REQUESTS", "12")
        rc = bench_serving.main(["--paged"])
        assert rc == 0
        rec = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert rec["parity_ok"] is True
        assert rec["retraces_after_warmup"] == 0
        assert rec["retraces_after_warmup_dense"] == 0
        # the capacity win: strictly more concurrent slots than the
        # dense engine can physically hold at the same KV bytes
        assert rec["value"] >= 1.5
        # per-step cost at equal shape: margin below the ~0.97 the
        # full fixed-seed bench shows (12 requests here, CI jitter)
        assert rec["tokens_per_sec_ratio_equal_slots"] > 0.8

    def test_bench_chunked_prefill_sweep(self, monkeypatch, capsys,
                                         tmp_path):
        """The token-budget overload A/B (chunked vs phase prefill at
        equal compiled shape, SAME arrivals, engine-owned TTFT
        percentiles). Slow-marked like the other sweeps: tier-1 covers
        the scheduler through tests/test_budget_scheduler.py; this
        drives the full bench and its acceptance gates (TTFT flatness,
        token parity, no retraces). Output redirects to tmp so CI can't
        clobber the committed record."""
        import json
        import bench_serving
        monkeypatch.setattr(bench_serving, "__file__",
                            str(tmp_path / "bench_serving.py"))
        monkeypatch.setenv("BENCH_SERVE_REQUESTS", "12")
        rc = bench_serving.main(["--chunked"])
        assert rc == 0
        rec = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert rec["parity_ok"] is True
        assert rec["retraces_after_warmup"] == 0
        assert rec["retraces_after_warmup_phase"] == 0
        assert rec["budget_steps"] > 0
        # the flatness gate, with margin for 12-request CI jitter (the
        # full fixed-seed bench pins <= 1.3 in the committed record)
        assert rec["value"] <= 2.0
        assert rec["tokens_per_sec_ratio"] > 0.8

    def test_bench_spec_decode_sweep(self, monkeypatch, capsys,
                                     tmp_path):
        """The speculative-decoding A/B (n-gram drafter + verify step
        on vs off at equal compiled shape, SAME arrivals). Slow-marked
        like the other sweeps: tier-1 covers spec decoding through
        tests/test_spec_decode.py; this drives the full bench and its
        acceptance gates (speedup, acceptance rate, no retraces). The
        output redirects to tmp so CI can't clobber the committed
        record."""
        import json
        import bench_serving
        monkeypatch.setattr(bench_serving, "__file__",
                            str(tmp_path / "bench_serving.py"))
        monkeypatch.setenv("BENCH_SERVE_REQUESTS", "12")
        rc = bench_serving.main(["--spec"])
        assert rc == 0
        rec = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert rec["retraces_after_warmup"] == 0
        assert rec["retraces_after_warmup_off"] == 0
        assert rec["draft_accepted"] > 0
        assert rec["acceptance_rate"] > 0.5
        assert rec["tokens_per_step"] > 1.2
        # timing-dependent with margin below the >= 1.2x the full
        # fixed-seed bench shows (12 requests here, CI jitter)
        assert rec["value"] > 1.05
