"""End-to-end quantized serving (ISSUE 20): flat-path int8 KV kernel
plus int4 packed weights with fused dequant-matmul.

Contracts under test:
  * knob surface — ``weight_quant=``/``kv_quant=`` ctor args win over
    the PADDLE_TPU_DECODE_* env, unknown modes and the int4-unpackable
    axes fail fast at construction, the explicit int4 + dense-ring
    pairing is refused, and ``init_serving_mesh`` rejects packed
    contracted axes whose HALF length does not divide mp;
  * the flat i8 Pallas kernel (decode_attention_paged_flat_i8) is
    numerically the dequantized masked-softmax reference, its support
    predicate holds the int8 sublane line (Bt >= 32), and under
    FLAT_BUDGET=1 + INT8_CACHE the engine really dispatches it
    (path-spy pinned) with EXACT token parity against the
    flat_gather_view fallback oracle and the row-aligned engine;
  * per-flavor greedy AND sampled self-parity: the SAME stream through
    the flat [T] and row [B, C] layouts is token-identical under every
    quant flavor, across prefix-cache churn and spec decode;
  * distribution closeness: int4 sampled outputs stay statistically
    near fp on the same seed stream (quantization shifts logits, so
    cross-flavor parity is NOT exact by design — the gate is overlap);
  * memory truth: the int8 pool (+ scale mirrors) holds <= 1/2 the fp
    pool bytes, int8 weights <= 1/2 and int4 weights <= 1/4 of the fp
    stack, and the telemetry snapshot reports both modes;
  * zero retraces after warmup in every flavor: quant is stacking-time
    + kernel-flavor structure, never per-step trace structure.
"""
import numpy as np
import pytest

import paddle_tpu as paddle

from paddle_tpu.incubate.nn import FusedMultiTransformer
from paddle_tpu.inference.serving import ServingEngine
from paddle_tpu.nn.layer.common import Embedding, Linear

V, E, H, FF, L = 97, 32, 4, 64, 2


def _model(seed=3, e=E, h=H, ff=FF, v=V):
    paddle.seed(seed)
    embed = Embedding(v, e)
    fmt = FusedMultiTransformer(e, h, ff, num_layers=L,
                                normalize_before=True)
    head = Linear(e, v, bias_attr=False)
    fmt.eval()
    return fmt, embed, head


def _prompt(rng, n):
    return rng.randint(1, V, (n,)).astype(np.int32)


def _reqs(rng, n=6):
    reqs = [(_prompt(rng, 8 + i % 5), 4) for i in range(n - 1)]
    reqs.append((_prompt(rng, 40), 6))
    return reqs


def _ran_flat(eng):
    return any(k[0] == "flat_budget" for k in eng._jit_cache)


def _pool_bytes(eng):
    tot = int(eng._caches["kv"].nbytes)
    if "sc" in eng._caches:
        tot += int(eng._caches["sc"].nbytes)
    return tot


def _stack_bytes(eng):
    return sum(int(np.prod(a.shape)) * a.dtype.itemsize
               for a in eng.dec._stacked().values())


# flavor -> ctor kwargs (None entries defer to env/default)
FLAVORS = {
    "int8kv": dict(kv_quant="int8"),
    "int8w": dict(weight_quant="int8"),
    "int4w": dict(weight_quant="int4"),
}


def _engine(fmt, embed, head, flat, prefill_cap=4, **kw):
    paddle.seed(0)
    eng = ServingEngine(fmt, embed, head, num_slots=2, max_seq_len=128,
                        decode_chunk=2, prefill_cap=prefill_cap,
                        flat_budget=flat, **kw)
    return eng


def _drive(eng, reqs):
    rids = [eng.submit(p, max_new_tokens=m) for p, m in reqs]
    eng.run()
    return [eng.results[r]["tokens"] for r in rids]


class TestQuantKnobs:
    def test_unknown_modes_fail_fast(self):
        fmt, embed, head = _model(seed=20)
        with pytest.raises(ValueError, match="weight_quant"):
            ServingEngine(fmt, embed, head, num_slots=2,
                          max_seq_len=64, weight_quant="int2")
        with pytest.raises(ValueError, match="kv_quant"):
            ServingEngine(fmt, embed, head, num_slots=2,
                          max_seq_len=64, kv_quant="fp8")
        # int4 KV is refused by design (per-row absmax at 4 bits clips
        # decode tails), not silently mapped to int8
        with pytest.raises(ValueError, match="kv_quant"):
            ServingEngine(fmt, embed, head, num_slots=2,
                          max_seq_len=64, kv_quant="int4")

    def test_int4_dense_ring_refused(self):
        fmt, embed, head = _model(seed=21)
        with pytest.raises(ValueError, match="dense"):
            ServingEngine(fmt, embed, head, num_slots=2,
                          max_seq_len=64, paged=False,
                          weight_quant="int4")

    def test_int4_odd_axes_fail_at_ctor(self):
        # E = 33 (H = 3 heads x head_dim 11): every int4-packed
        # contracted axis is odd -> the ctor names the offenders
        fmt, embed, head = _model(seed=22, e=33, h=3, ff=64)
        with pytest.raises(ValueError, match="even"):
            ServingEngine(fmt, embed, head, num_slots=2,
                          max_seq_len=64, weight_quant="int4")

    def test_ctor_wins_over_env(self, monkeypatch):
        fmt, embed, head = _model(seed=23)
        monkeypatch.setenv("PADDLE_TPU_DECODE_INT4_WEIGHTS", "1")
        monkeypatch.setenv("PADDLE_TPU_DECODE_INT8_CACHE", "1")
        eng = ServingEngine(fmt, embed, head, num_slots=2,
                            max_seq_len=64, weight_quant="none",
                            kv_quant="none")
        assert eng.dec._weight_quant_mode() == "none"
        assert not eng.dec._int8_cache()
        # env alone engages; INT4 outranks INT8 when both leak on
        monkeypatch.setenv("PADDLE_TPU_DECODE_INT8_WEIGHTS", "1")
        eng = ServingEngine(fmt, embed, head, num_slots=2,
                            max_seq_len=64)
        assert eng.dec._weight_quant_mode() == "int4"
        assert eng.dec._int8_cache()
        # explicit int8 arg beats the int4 env
        eng = ServingEngine(fmt, embed, head, num_slots=2,
                            max_seq_len=64, weight_quant="int8")
        assert eng.dec._weight_quant_mode() == "int8"

    def test_mesh_validation_covers_packed_axes(self):
        from paddle_tpu.parallel import init_serving_mesh
        # ffn half = 1 does not divide mp=2 -> refused before any
        # fleet/topology state is touched
        with pytest.raises(ValueError, match="packed half"):
            init_serving_mesh(2, num_heads=4, head_dim=8, ffn_dim=2,
                              weight_quant="int4")
        # heads divide mp but the packed out-proj half (2*1/2 = 1)
        # does not -> the int4 check catches what the head check missed
        with pytest.raises(ValueError, match="packed half"):
            init_serving_mesh(2, num_heads=2, head_dim=1, ffn_dim=64,
                              weight_quant="int4")


class TestFlatI8Kernel:
    def test_matches_dequantized_masked_reference(self):
        """decode_attention_paged_flat_i8 vs the dequantize-then-
        masked-softmax reference over mixed chunks (mid-cache bases, a
        partial chunk, a pure-pad chunk) — same fixture family as the
        fp numerics test, at the int8 sublane Bt."""
        from paddle_tpu.ops.pallas.decode_attention import (
            FLAT_CHUNK, decode_attention_paged_flat_i8,
            paged_flat_i8_is_supported)
        import jax.numpy as jnp
        rng = np.random.RandomState(0)
        lnum, nb, h, bt, d = 2, 10, 4, 32, 16
        b, nblk = 3, 3                       # Smax = 96
        t = 4 * FLAT_CHUNK
        pool = rng.randint(-127, 128,
                           (lnum, 2, nb, h, bt, d)).astype(np.int8)
        scales = (0.01 + rng.rand(lnum, 2, nb, h, 1, bt)
                  .astype(np.float32) * 0.05)
        tbl = rng.permutation(nb)[:b * nblk].reshape(b, nblk).astype(
            np.int32)
        cslot = np.array([0, 1, 1, 2], np.int32)
        cbase = np.array([5, 0, 40, 70], np.int32)
        cn = np.array([8, 8, 3, 0], np.int32)    # partial + pad chunks
        q = rng.randn(t, h, d).astype(np.float32)
        assert paged_flat_i8_is_supported(t, h, d, pool.shape, q.dtype)
        lay = 1
        out = np.asarray(decode_attention_paged_flat_i8(
            jnp.asarray(q), jnp.asarray(pool), jnp.asarray(scales),
            jnp.asarray(tbl), jnp.asarray(cslot), jnp.asarray(cbase),
            jnp.asarray(cn), lay))
        assert out.dtype == np.float32
        smax = nblk * bt
        # dequantize the whole pool once; reference = fp masked softmax
        deq = pool.astype(np.float32) * np.swapaxes(
            scales, -1, -2)                     # [L,2,NB,H,Bt,D]
        for ci in range(4):
            for r in range(int(cn[ci])):
                tok = ci * FLAT_CHUNK + r
                s, pos = int(cslot[ci]), int(cbase[ci]) + r
                kv = deq[lay][:, tbl[s]].transpose(
                    0, 2, 1, 3, 4).reshape(2, h, smax, d)
                sc = np.einsum("hd,hsd->hs", q[tok], kv[0]) * (d ** -0.5)
                sc[:, pos + 1:] = -1e30
                p = np.exp(sc - sc.max(-1, keepdims=True))
                p /= p.sum(-1, keepdims=True)
                ref = np.einsum("hs,hsd->hd", p, kv[1])
                np.testing.assert_allclose(out[tok], ref, rtol=2e-5,
                                           atol=2e-5)

    def test_support_predicate_gates(self):
        from paddle_tpu.ops.pallas.decode_attention import (
            FLAT_CHUNK, paged_flat_i8_is_supported)
        good = (1, 2, 8, 4, 32, 16)
        assert paged_flat_i8_is_supported(FLAT_CHUNK, 4, 16, good,
                                          np.float32)
        # int8 sublane minimum: Bt must be a multiple of 32
        assert not paged_flat_i8_is_supported(
            FLAT_CHUNK, 4, 16, (1, 2, 8, 4, 8, 16), np.float32)
        assert not paged_flat_i8_is_supported(
            FLAT_CHUNK, 4, 16, (1, 2, 8, 4, 48, 16), np.float32)
        # stream alignment + shape rank
        assert not paged_flat_i8_is_supported(FLAT_CHUNK + 1, 4, 16,
                                              good, np.float32)
        assert not paged_flat_i8_is_supported(0, 4, 16, good,
                                              np.float32)
        assert not paged_flat_i8_is_supported(FLAT_CHUNK, 4, 16,
                                              good[1:], np.float32)

    def test_engine_dispatches_kernel_with_fallback_parity(
            self, monkeypatch):
        """FLAT_BUDGET + INT8 KV at Bt=32: the engine must really run
        the flat i8 Pallas kernel (spy on the module namespace the
        step core resolves at trace time), and its tokens must equal
        BOTH the gather-fallback oracle (predicate forced off) and the
        row-aligned engine bit-for-bit. Pool bytes halve."""
        import paddle_tpu.ops.pallas.decode_attention as da
        fmt, embed, head = _model(seed=24)
        rng = np.random.RandomState(11)
        reqs = _reqs(rng)

        calls = {"i8": 0}
        orig = da.decode_attention_paged_flat_i8

        def spy(*a, **k):
            calls["i8"] += 1
            return orig(*a, **k)

        monkeypatch.setattr(da, "decode_attention_paged_flat_i8", spy)
        # prefill_cap IS the pool Bt: 32 satisfies the i8 sublane rule
        eng_f = _engine(fmt, embed, head, True, prefill_cap=32,
                        kv_quant="int8")
        toks_f = _drive(eng_f, reqs)
        assert calls["i8"] > 0, "flat i8 Pallas kernel never dispatched"
        assert _ran_flat(eng_f) and "sc" in eng_f._caches

        # oracle 1: same flat layout, predicate forced off -> the
        # flat_gather_view dequant fallback
        calls["i8"] = 0
        monkeypatch.setattr(da, "paged_flat_i8_is_supported",
                            lambda *a, **k: False)
        eng_g = _engine(fmt, embed, head, True, prefill_cap=32,
                        kv_quant="int8")
        toks_g = _drive(eng_g, reqs)
        assert calls["i8"] == 0
        monkeypatch.undo()
        for a, b in zip(toks_f, toks_g):
            np.testing.assert_array_equal(a, b)

        # oracle 2: the row-aligned engine on the same quantized pool
        eng_r = _engine(fmt, embed, head, False, prefill_cap=32,
                        kv_quant="int8")
        toks_r = _drive(eng_r, reqs)
        for a, b in zip(toks_f, toks_r):
            np.testing.assert_array_equal(a, b)

        # memory truth: int8 pool + scale mirrors <= half the fp pool
        eng_fp = _engine(fmt, embed, head, True, prefill_cap=32)
        _drive(eng_fp, reqs)
        assert _pool_bytes(eng_f) <= _pool_bytes(eng_fp) / 2


class TestQuantSelfParity:
    """The layout must stay invisible under every quant flavor: the
    SAME stream through the flat [T] and row [B, C] engines is
    token-identical (quantization changes numerics, so the oracle is
    the OTHER LAYOUT in the SAME flavor — not fp)."""

    @pytest.mark.parametrize("flavor", sorted(FLAVORS))
    @pytest.mark.parametrize("prefix_blocks,spec", [(0, 0), (3, 4)])
    def test_greedy_flat_vs_row(self, flavor, prefix_blocks, spec,
                                serving_metrics_ok):
        fmt, embed, head = _model(seed=25)
        rng = np.random.RandomState(7)
        reqs = _reqs(rng)
        kw = dict(FLAVORS[flavor],
                  prefix_cache_blocks=prefix_blocks, spec_k=spec or None)
        eng_f = _engine(fmt, embed, head, True, **kw)
        toks_f = _drive(eng_f, reqs)
        eng_r = _engine(fmt, embed, head, False, **kw)
        toks_r = _drive(eng_r, reqs)
        assert _ran_flat(eng_f)
        for a, b in zip(toks_f, toks_r):
            np.testing.assert_array_equal(a, b)
        serving_metrics_ok(eng_f)
        serving_metrics_ok(eng_r)

    @pytest.mark.parametrize("flavor", sorted(FLAVORS))
    def test_sampled_flat_vs_row(self, flavor):
        """fold_in(seed, nt) sampling invariance must survive quant:
        sampled outputs are scheduling- and layout-independent."""
        fmt, embed, head = _model(seed=26)
        rng = np.random.RandomState(9)
        reqs = _reqs(rng)
        kw = dict(FLAVORS[flavor], do_sample=True, top_k=5)
        toks_f = _drive(_engine(fmt, embed, head, True, **kw), reqs)
        toks_r = _drive(_engine(fmt, embed, head, False, **kw), reqs)
        for a, b in zip(toks_f, toks_r):
            np.testing.assert_array_equal(a, b)


class TestQuantDistribution:
    def test_int4_sampled_distribution_near_fp(self):
        """Quantized logits shift, so token-level parity with fp is
        NOT a contract — distribution overlap is: first sampled tokens
        over a shared per-request seed stream must substantially
        overlap between fp and int4 (total variation well below
        disjoint)."""
        fmt, embed, head = _model(seed=27)
        rng = np.random.RandomState(13)
        prompt = _prompt(rng, 12)
        n = 24

        def first_tokens(**kw):
            eng = _engine(fmt, embed, head, True, do_sample=True,
                          top_k=8, temperature=1.5, **kw)
            rids = [eng.submit(prompt, max_new_tokens=1)
                    for _ in range(n)]
            eng.run()
            return [int(eng.results[r]["tokens"][0]) for r in rids]

        # paddle.seed(0) inside _engine pins the SAME per-request seed
        # stream for both flavors — differences are logits-only
        t_fp = first_tokens()
        t_i4 = first_tokens(weight_quant="int4")
        h_fp = np.bincount(t_fp, minlength=V) / n
        h_i4 = np.bincount(t_i4, minlength=V) / n
        tv = 0.5 * np.abs(h_fp - h_i4).sum()
        assert tv < 0.5, (
            f"int4 sampled distribution drifted from fp: TV={tv:.3f} "
            f"(fp tokens {sorted(set(t_fp))}, int4 {sorted(set(t_i4))})")


class TestQuantBytes:
    def test_weight_bytes_halve_and_quarter(self):
        fmt, embed, head = _model(seed=28)
        rng = np.random.RandomState(5)
        reqs = _reqs(rng, n=3)
        eng_fp = _engine(fmt, embed, head, True)
        _drive(eng_fp, reqs)
        b_fp = _stack_bytes(eng_fp)
        eng_8 = _engine(fmt, embed, head, True, weight_quant="int8")
        _drive(eng_8, reqs)
        eng_4 = _engine(fmt, embed, head, True, weight_quant="int4")
        _drive(eng_4, reqs)
        b_8, b_4 = _stack_bytes(eng_8), _stack_bytes(eng_4)
        assert b_8 <= b_fp / 2, f"int8 stack {b_8} vs fp {b_fp}"
        assert b_4 <= b_fp / 4, f"int4 stack {b_4} vs fp {b_fp}"
        # packed structure: every contracted axis halves in int8 bytes
        stk = eng_4.dec._stacked()
        assert stk["qkv_w"].dtype == np.int8
        assert stk["qkv_w"].shape[-1] * 2 == E
        assert stk["f2_w"].shape[1] * 2 == FF

    def test_snapshot_reports_quant_modes(self):
        from paddle_tpu.inference.telemetry import (
            snapshot as engine_snapshot)
        fmt, embed, head = _model(seed=29)
        rng = np.random.RandomState(5)
        eng = _engine(fmt, embed, head, True, weight_quant="int4",
                      kv_quant="int8")
        _drive(eng, _reqs(rng, n=3))
        w = engine_snapshot(eng)["weights"]
        assert w["weight_quant"] == "int4"
        assert w["kv_quant"] == "int8"
        eng2 = _engine(fmt, embed, head, True)
        _drive(eng2, _reqs(rng, n=2))
        w2 = engine_snapshot(eng2)["weights"]
        assert w2["weight_quant"] == "none"
        assert w2["kv_quant"] == "none"


class TestQuantZeroRetrace:
    @pytest.mark.parametrize("flavor", sorted(FLAVORS))
    def test_replay_retraces_nothing(self, flavor, serving_metrics_ok):
        """Quantization is stacking-time structure (weight dtype/shape)
        and kernel flavor — per-step metadata stays data, so an
        identical staggered replay builds zero new executables."""
        fmt, embed, head = _model(seed=30)
        rng = np.random.RandomState(3)
        reqs = _reqs(rng, n=6)

        def staggered(eng):
            for p, m in reqs[:3]:
                eng.submit(p, max_new_tokens=m)
            for _ in range(3):
                eng.step()
            for p, m in reqs[3:]:
                eng.submit(p, max_new_tokens=m)
            eng.run()

        eng = _engine(fmt, embed, head, True, **FLAVORS[flavor])
        staggered(eng)
        warm = eng.metrics()["traces"]
        assert warm > 0 and _ran_flat(eng)
        staggered(eng)
        m = serving_metrics_ok(eng)
        assert m["traces"] == warm, (
            f"{flavor} staggered replay retraced: {warm} -> "
            f"{m['traces']}")
