"""Fleet pipeline-parallel vs serial equivalence on the 8-device CPU mesh.

SURVEY §4 companion pattern (hybrid_parallel_pp_transformer.py): build the
same model twice (fixed seed), train one serially and one through
fleet.distributed_model(PipelineLayer) → PipelineParallel.train_batch
(compiled ppermute schedule), assert loss and updated params allclose.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import jax

from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.meta_parallel.pp_layers import (
    LayerDesc, PipelineLayer)
from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import (
    PipelineParallel, PipelineParallelWithInterleave)

needs8 = pytest.mark.skipif(len(jax.devices()) < 8,
                            reason="needs 8 virtual devices")

D = 16
NLAYERS = 8


class Block(paddle.nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = paddle.nn.Linear(D, D)

    def forward(self, x):
        return paddle.nn.functional.tanh(self.fc(x)) + x


class Head(paddle.nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = paddle.nn.Linear(D, 4)

    def forward(self, x):
        return self.fc(x)


class Stem(paddle.nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = paddle.nn.Linear(D, D)

    def forward(self, x):
        return self.fc(x)


def mse(out, label):
    return ((out - label) ** 2).mean()


def make_model(seed):
    paddle.seed(seed)
    descs = [LayerDesc(Stem)] + [LayerDesc(Block) for _ in range(NLAYERS)] \
        + [LayerDesc(Head)]
    return PipelineLayer(descs, num_stages=2, loss_fn=mse)


def serial_steps(model, opt, xs, ys, nsteps):
    losses = []
    for s in range(nsteps):
        x = paddle.to_tensor(xs[s])
        y = paddle.to_tensor(ys[s])
        loss = mse(model.forward(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss._data)))
    return losses


def fleet_pp(pp, virtual=None):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 1, "mp_degree": 1, "pp_degree": pp,
        "sharding_degree": 1,
        "pp_configs": {"accumulate_steps": 4},
    }
    fleet.init(is_collective=True, strategy=strategy)
    model = make_model(7)
    if virtual:
        model._num_virtual_pipeline_stages = virtual
    wrapped = fleet.distributed_model(model)
    return model, wrapped


@needs8
class TestFleetPipeline:
    def _data(self, nsteps=3, batch=8):
        rng = np.random.RandomState(0)
        xs = [rng.randn(batch, D).astype(np.float32) for _ in range(nsteps)]
        ys = [rng.randn(batch, 4).astype(np.float32) for _ in range(nsteps)]
        return xs, ys

    def _run_pp(self, wrapped, model, xs, ys, nsteps):
        opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
        losses = []
        for s in range(nsteps):
            loss = wrapped.train_batch(
                [paddle.to_tensor(xs[s]), paddle.to_tensor(ys[s])], opt)
            losses.append(float(np.asarray(loss._data)))
        return losses

    def _assert_matches_serial(self, wrapped, model, kind):
        xs, ys = self._data()
        assert isinstance(wrapped, kind)
        losses_pp = self._run_pp(wrapped, model, xs, ys, 3)

        ref = make_model(7)
        opt = paddle.optimizer.SGD(0.1, parameters=ref.parameters())
        losses_ref = serial_steps(ref, opt, xs, ys, 3)

        np.testing.assert_allclose(losses_pp, losses_ref, atol=1e-5,
                                   rtol=1e-5)
        for p_pp, p_ref in zip(model.parameters(), ref.parameters()):
            np.testing.assert_allclose(np.asarray(p_pp._data),
                                       np.asarray(p_ref._data),
                                       atol=1e-5, rtol=1e-5)

    def test_pp2_matches_serial(self):
        model, wrapped = fleet_pp(2)
        assert wrapped._mesh() is not None
        self._assert_matches_serial(wrapped, model, PipelineParallel)

    def test_pp2_interleave_matches_serial(self):
        model, wrapped = fleet_pp(2, virtual=2)
        assert isinstance(wrapped, PipelineParallelWithInterleave)
        assert wrapped.num_virtual == 2
        self._assert_matches_serial(wrapped, model,
                                    PipelineParallelWithInterleave)

    def test_partition_prologue_epilogue(self):
        model, wrapped = fleet_pp(2)
        pro, body, epi, period = wrapped._partition()
        assert len(body) == NLAYERS
        assert len(pro) == 1 and len(epi) == 1
        assert period == 1      # homogeneous stack

    def test_fallback_without_mesh_pp1(self):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": 1, "sharding_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
        model = make_model(3)
        wrapped = fleet.distributed_model(model)
        assert isinstance(wrapped, PipelineParallel)
        assert wrapped._mesh() is None      # sequential fallback path
        xs, ys = self._data(2)
        opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
        loss = wrapped.train_batch(
            [paddle.to_tensor(xs[0]), paddle.to_tensor(ys[0])], opt)
        assert np.isfinite(float(np.asarray(loss._data)))


@needs8
class TestFleetPipelineShared:
    """Tied weights via SharedLayerDesc must be jit arguments (not baked
    constants) and receive grad contributions from BOTH uses."""

    def _make(self, seed):
        from paddle_tpu.distributed.fleet.meta_parallel.pp_layers import (
            SharedLayerDesc)
        paddle.seed(seed)

        def head_fwd(layer, x):
            # second use of the tied weight: project with its transpose
            w = layer.fc.weight
            return paddle.matmul(x, w.t())

        descs = (
            [SharedLayerDesc("tied", Stem)]
            + [LayerDesc(Block) for _ in range(4)]
            + [SharedLayerDesc("tied", Stem, forward_func=head_fwd)]
        )
        return PipelineLayer(descs, num_stages=2, loss_fn=mse)

    def test_tied_weights_update_and_grads(self):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": 2, "sharding_degree": 1,
                                   "pp_configs": {"accumulate_steps": 2}}
        fleet.init(is_collective=True, strategy=strategy)
        model = self._make(11)
        wrapped = fleet.distributed_model(model)
        opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())

        rng = np.random.RandomState(5)
        xs = [rng.randn(4, D).astype(np.float32) for _ in range(2)]
        ys = [rng.randn(4, D).astype(np.float32) for _ in range(2)]
        losses_pp = [float(np.asarray(wrapped.train_batch(
            [paddle.to_tensor(x), paddle.to_tensor(y)], opt)._data))
            for x, y in zip(xs, ys)]
        assert not getattr(wrapped, "_pp_disabled", False), \
            "tied-weight model must use the compiled pipeline"

        ref = self._make(11)
        opt_ref = paddle.optimizer.SGD(0.1, parameters=ref.parameters())
        losses_ref = serial_steps(ref, opt_ref, xs, ys, 2)
        np.testing.assert_allclose(losses_pp, losses_ref, atol=1e-5,
                                   rtol=1e-5)
        for p_pp, p_ref in zip(model.parameters(), ref.parameters()):
            np.testing.assert_allclose(np.asarray(p_pp._data),
                                       np.asarray(p_ref._data),
                                       atol=1e-5, rtol=1e-5)


@needs8
class TestFleetPipelineFallback:
    def test_tuple_activation_falls_back(self):
        """Models with tuple inter-stage activations fall back to the
        sequential micro-batch loop instead of crashing."""

        class TupleBlock(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = paddle.nn.Linear(D, D)

            def forward(self, x, m=None):
                h = paddle.nn.functional.tanh(self.fc(x))
                return (h, m if m is not None else h)

        class Untuple(paddle.nn.Layer):
            def forward(self, x, m):
                return x + m

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": 2, "sharding_degree": 1,
                                   "pp_configs": {"accumulate_steps": 2}}
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(1)
        model = PipelineLayer(
            [LayerDesc(TupleBlock) for _ in range(4)] + [LayerDesc(Untuple)],
            num_stages=2, loss_fn=mse)
        wrapped = fleet.distributed_model(model)
        opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
        rng = np.random.RandomState(5)
        x = paddle.to_tensor(rng.randn(4, D).astype(np.float32))
        y = paddle.to_tensor(rng.randn(4, D).astype(np.float32))
        import warnings
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            loss = wrapped.train_batch([x, y], opt)
        assert np.isfinite(float(np.asarray(loss._data)))
        assert wrapped._pp_disabled


@needs8
class TestPeriodicBody:
    """Non-uniform (PERIODIC) stacks pipeline too: alternating block types
    with different parameter shapes — the reference's MoE-every-k /
    wide-narrow patterns — previously fell back to the sequential loop
    (VERDICT r2 weak-4)."""

    D = 16

    class Narrow(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = paddle.nn.Linear(TestPeriodicBody.D,
                                       TestPeriodicBody.D)

        def forward(self, x):
            return x + paddle.nn.functional.tanh(self.fc(x))

    class Wide(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            d = TestPeriodicBody.D
            self.up = paddle.nn.Linear(d, 4 * d)
            self.down = paddle.nn.Linear(4 * d, d)

        def forward(self, x):
            return x + self.down(paddle.nn.functional.gelu(self.up(x)))

    def _build(self, stages):
        from paddle_tpu.distributed.fleet.meta_parallel.pp_layers import (
            LayerDesc, PipelineLayer)
        descs = []
        for _ in range(4):                       # period-2 pattern × 4
            descs.append(LayerDesc(self.Narrow))
            descs.append(LayerDesc(self.Wide))
        return PipelineLayer(descs, num_stages=stages,
                             loss_fn=lambda o, l: ((o - l) ** 2).mean())

    def test_period2_compiled_matches_serial(self):
        rng = np.random.RandomState(0)
        x = rng.randn(8, self.D).astype(np.float32)
        y = rng.randn(8, self.D).astype(np.float32)

        paddle.seed(9)
        serial = self._build(stages=1)
        sd = {k: np.asarray(v._data).copy()
              for k, v in serial.state_dict().items()}
        opt_s = paddle.optimizer.SGD(0.1, parameters=serial.parameters())
        serial_losses = []
        for _ in range(3):
            loss = ((serial(paddle.to_tensor(x)) - paddle.to_tensor(y))
                    ** 2).mean()
            loss.backward()
            opt_s.step()
            opt_s.clear_grad()
            serial_losses.append(float(np.asarray(loss._data)))

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {
            "dp_degree": 4, "mp_degree": 1, "pp_degree": 2,
            "sharding_degree": 1,
            "pp_configs": {"accumulate_steps": 2}}
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(9)
        model = self._build(stages=2)
        model.set_state_dict({k: paddle.to_tensor(v)
                              for k, v in sd.items()})
        wrapped = fleet.distributed_model(model)
        assert wrapped._partition()[3] == 2      # period detected
        opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
        losses = []
        for _ in range(3):
            loss = wrapped.train_batch(
                [paddle.to_tensor(x), paddle.to_tensor(y)], opt)
            losses.append(float(np.asarray(loss._data)))
        assert wrapped._pp_cache.get("_ran"), "periodic body fell back"
        np.testing.assert_allclose(losses, serial_losses, rtol=2e-4,
                                   atol=2e-5)
        serial_sd = serial.state_dict()
        for k, v in model.state_dict().items():
            np.testing.assert_allclose(
                np.asarray(v._data), np.asarray(serial_sd[k]._data),
                rtol=5e-4, atol=5e-4, err_msg=k)


def test_paramless_layers_distinguished_in_period():
    """Two _FnLayers wrapping DIFFERENT callables must not be treated as
    the same pattern position (the template would silently replace the
    other's behavior)."""
    from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import (
        _param_sig)
    from paddle_tpu.distributed.fleet.meta_parallel.pp_layers import _FnLayer
    relu = paddle.nn.functional.relu
    silu = paddle.nn.functional.silu
    assert _param_sig(_FnLayer(relu)) == _param_sig(_FnLayer(relu))
    assert _param_sig(_FnLayer(relu)) != _param_sig(_FnLayer(silu))
    d1, d2 = paddle.nn.Dropout(0.1), paddle.nn.Dropout(0.5)
    assert _param_sig(d1) != _param_sig(d2)
    assert _param_sig(paddle.nn.Dropout(0.1)) == _param_sig(
        paddle.nn.Dropout(0.1))
