"""Optimizer + LR scheduler + AMP tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as optim


def _quadratic_setup():
    p = paddle.create_parameter([4], "float32")
    p.set_value(np.ones(4, np.float32) * 5.0)
    return p


def _step(opt, p, n=1):
    for _ in range(n):
        loss = (p * p).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()


class TestOptimizers:
    def test_sgd_descends(self):
        p = _quadratic_setup()
        opt = optim.SGD(learning_rate=0.1, parameters=[p])
        _step(opt, p, 20)
        assert np.abs(p.numpy()).max() < 1.0

    def test_adamw_descends(self):
        p = _quadratic_setup()
        opt = optim.AdamW(learning_rate=0.3, parameters=[p])
        _step(opt, p, 50)
        assert np.abs(p.numpy()).max() < 1.0

    def test_adamw_vs_reference_formula(self):
        # one step of AdamW against hand-computed update
        p = paddle.create_parameter([2], "float32")
        p.set_value(np.array([1.0, -2.0], np.float32))
        lr, b1, b2, eps, wd = 0.1, 0.9, 0.999, 1e-8, 0.01
        opt = optim.AdamW(learning_rate=lr, beta1=b1, beta2=b2, epsilon=eps,
                          weight_decay=wd, parameters=[p])
        w0 = p.numpy().copy()
        loss = (p * paddle.to_tensor([3.0, 4.0])).sum()
        loss.backward()
        g = np.array([3.0, 4.0], np.float32)
        opt.step()
        m = (1 - b1) * g
        v = (1 - b2) * g * g
        mhat = m / (1 - b1)
        vhat = v / (1 - b2)
        expect = w0 - lr * (mhat / (np.sqrt(vhat) + eps) + wd * w0)
        np.testing.assert_allclose(p.numpy(), expect, rtol=1e-5)

    def test_momentum(self):
        p = _quadratic_setup()
        opt = optim.Momentum(learning_rate=0.05, momentum=0.9,
                             parameters=[p])
        _step(opt, p, 30)
        assert np.abs(p.numpy()).max() < 2.0

    def test_grad_clip_global_norm(self):
        p = paddle.create_parameter([3], "float32")
        p.set_value(np.zeros(3, np.float32))
        clip = nn.ClipGradByGlobalNorm(1.0)
        opt = optim.SGD(learning_rate=1.0, parameters=[p], grad_clip=clip)
        (p * paddle.to_tensor([30.0, 40.0, 0.0])).sum().backward()
        opt.step()
        # grad norm 50 clipped to 1 → update = -[0.6,0.8,0]/50*... = -g/50
        np.testing.assert_allclose(p.numpy(), [-0.6, -0.8, 0.0], rtol=1e-5)

    def test_optimizer_state_dict_roundtrip(self):
        p = _quadratic_setup()
        opt = optim.AdamW(learning_rate=0.1, parameters=[p])
        _step(opt, p, 3)
        sd = opt.state_dict()
        p2 = paddle.create_parameter([4], "float32")
        p2.name = p.name
        opt2 = optim.AdamW(learning_rate=0.1, parameters=[p2])
        opt2.set_state_dict(sd)
        m1 = opt._acc("moment1", p).numpy()
        m2 = opt2._acc("moment1", p2).numpy()
        np.testing.assert_allclose(m1, m2)

    def test_multi_precision_master_weights(self):
        import jax.numpy as jnp
        p = paddle.create_parameter([4], "bfloat16")
        opt = optim.AdamW(learning_rate=0.01, parameters=[p],
                          multi_precision=True)
        (p * 2.0).sum().backward()
        opt.step()
        master = opt._master_weights[id(p)]
        assert master.dtype == jnp.float32
        assert p.dtype == jnp.bfloat16


class TestLRSchedulers:
    def test_cosine(self):
        s = optim.lr.CosineAnnealingDecay(0.1, T_max=10)
        vals = []
        for _ in range(10):
            vals.append(s())
            s.step()
        assert vals[0] == pytest.approx(0.1)
        assert vals[-1] < vals[0]

    def test_warmup(self):
        s = optim.lr.LinearWarmup(0.1, warmup_steps=5, start_lr=0.0,
                                  end_lr=0.1)
        first = s()
        for _ in range(6):
            s.step()
        assert first < 0.1
        assert s() == pytest.approx(0.1)

    def test_scheduler_in_optimizer(self):
        p = paddle.create_parameter([2], "float32")
        s = optim.lr.StepDecay(0.1, step_size=1, gamma=0.5)
        opt = optim.SGD(learning_rate=s, parameters=[p])
        assert opt.get_lr() == pytest.approx(0.1)
        s.step()
        assert opt.get_lr() == pytest.approx(0.05)

    def test_noam(self):
        s = optim.lr.NoamDecay(d_model=128, warmup_steps=10,
                               learning_rate=1.0)
        v0 = s()
        for _ in range(9):
            s.step()
        assert s() > v0


class TestAMP:
    def test_auto_cast_o1_bf16_matmul(self):
        import jax.numpy as jnp
        a = paddle.to_tensor(np.random.randn(4, 4).astype(np.float32))
        b = paddle.to_tensor(np.random.randn(4, 4).astype(np.float32))
        with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
            out = paddle.matmul(a, b)
        assert out.dtype == jnp.bfloat16
        out2 = paddle.matmul(a, b)
        assert out2.dtype == jnp.float32

    def test_decorate_o2(self):
        import jax.numpy as jnp
        model = nn.Linear(4, 4)
        opt = paddle.optimizer.AdamW(parameters=model.parameters())
        model, opt = paddle.amp.decorate(model, opt, level="O2",
                                         dtype="bfloat16")
        assert model.weight.dtype == jnp.bfloat16
        assert opt._multi_precision

    def test_grad_scaler_flow(self):
        model = nn.Linear(4, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)
        x = paddle.to_tensor(np.random.randn(8, 4).astype(np.float32))
        loss = model(x).sum()
        scaled = scaler.scale(loss)
        scaled.backward()
        w0 = model.weight.numpy().copy()
        scaler.step(opt)
        scaler.update()
        opt.clear_grad()
        assert not np.allclose(model.weight.numpy(), w0)

    def test_grad_scaler_skips_on_inf(self):
        model = nn.Linear(2, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=4.0)
        w0 = model.weight.numpy().copy()
        model.weight.grad = paddle.to_tensor(
            np.array([[np.inf], [1.0]], np.float32))
        scaler.unscale_(opt)
        scaler.step(opt)
        scaler.update()
        np.testing.assert_array_equal(model.weight.numpy(), w0)
        assert scaler.get_loss_scaling() == pytest.approx(2.0)


# ---- round-2 optimizer breadth: Adamax/NAdam/RAdam/ASGD/Rprop -------------

class TestOptimizerBreadth:
    def _fit_quadratic(self, opt_cls, steps=60, **kw):
        paddle.seed(0)
        target = np.array([3.0, -2.0], np.float32)
        w = paddle.to_tensor(np.zeros(2, np.float32), stop_gradient=False)
        opt = opt_cls(parameters=[w], **kw)
        for _ in range(steps):
            loss = ((w - paddle.to_tensor(target)) ** 2).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
        return np.asarray(w._data), float(np.asarray(loss._data))

    def test_adamax_converges(self):
        w, loss = self._fit_quadratic(paddle.optimizer.Adamax,
                                      learning_rate=0.3)
        np.testing.assert_allclose(w, [3.0, -2.0], atol=0.2)

    def test_nadam_converges(self):
        w, loss = self._fit_quadratic(paddle.optimizer.NAdam,
                                      learning_rate=0.3)
        np.testing.assert_allclose(w, [3.0, -2.0], atol=0.2)

    def test_radam_converges(self):
        w, loss = self._fit_quadratic(paddle.optimizer.RAdam,
                                      learning_rate=0.3, steps=100)
        np.testing.assert_allclose(w, [3.0, -2.0], atol=0.2)

    def test_asgd_converges_and_averages(self):
        paddle.seed(0)
        w = paddle.to_tensor(np.zeros(1, np.float32), stop_gradient=False)
        opt = paddle.optimizer.ASGD(learning_rate=0.1, parameters=[w])
        for _ in range(100):
            loss = ((w - 5.0) ** 2).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
        np.testing.assert_allclose(np.asarray(w._data), [5.0], atol=0.05)
        avg = np.asarray(opt.averaged_value(w)._data)
        assert 0.0 < avg[0] <= 5.01  # trailing average lags the iterate

    def test_rprop_converges(self):
        w, loss = self._fit_quadratic(paddle.optimizer.Rprop,
                                      learning_rate=0.1, steps=80)
        np.testing.assert_allclose(w, [3.0, -2.0], atol=0.1)

    def test_new_optimizers_state_dict_roundtrip(self):
        paddle.seed(1)
        w = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
        opt = paddle.optimizer.Adamax(learning_rate=0.1, parameters=[w])
        loss = (w ** 2).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
        sd = opt.state_dict()
        assert any("moment" in k for k in sd)
        opt2 = paddle.optimizer.Adamax(learning_rate=0.1, parameters=[w])
        opt2.set_state_dict(sd)
        assert opt2._step_count == opt._step_count

    def test_asgd_batch_num_smooths(self):
        """With batch_num=n and alternating gradients ±1 around a mean of
        g0, the d/ys recursion steps with the n-gradient mean."""
        paddle.seed(2)
        w = paddle.to_tensor(np.zeros(1, np.float32), stop_gradient=False)
        opt = paddle.optimizer.ASGD(learning_rate=1.0, batch_num=2,
                                    parameters=[w])
        # inject alternating gradients by hand: +2, 0, +2, 0 (mean 1)
        from paddle_tpu.tensor.tensor import Tensor
        import jax.numpy as jnp
        positions = []
        for i in range(4):
            w.grad = Tensor(jnp.asarray([2.0 if i % 2 == 0 else 0.0]))
            opt.step()
            positions.append(float(np.asarray(w._data)[0]))
        # steps 2..4 use the 2-grad mean (1.0): equal decrements of 1
        np.testing.assert_allclose(positions[2] - positions[1], -1.0,
                                   atol=1e-5)
        np.testing.assert_allclose(positions[3] - positions[2], -1.0,
                                   atol=1e-5)

    def test_inplace_binary_shape_guard(self):
        x = paddle.to_tensor(np.ones(1, np.float32))
        with pytest.raises(ValueError, match="shape/dtype"):
            x.pow_(paddle.to_tensor(np.ones(3, np.float32)))
        y = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
        with pytest.raises(RuntimeError, match="in-place"):
            y.zero_()


class TestFusedEagerStep:
    """Eager opt.step() compiles into ONE program per param-set (the
    reference's multi_tensor_adam capability, VERDICT r2 weak-6): same
    numbers as the per-param loop, grads+lr as arguments so LR-scheduler
    moves don't retrace."""

    def _train(self, fuse, steps=4):
        import os
        os.environ["PADDLE_TPU_FUSE_EAGER_STEP"] = "1" if fuse else "0"
        paddle.seed(11)
        m = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.Tanh(),
                                 paddle.nn.Linear(16, 8))
        sched = paddle.optimizer.lr.StepDecay(learning_rate=0.05,
                                              step_size=2, gamma=0.5)
        opt = paddle.optimizer.AdamW(learning_rate=sched, weight_decay=0.01,
                                     parameters=m.parameters())
        opt._fuse_eager = None          # re-read the env toggle
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
        y = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
        losses = []
        for _ in range(steps):
            loss = ((m(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            sched.step()
            losses.append(float(np.asarray(loss._data)))
        return losses, [np.asarray(p._data) for p in m.parameters()], opt

    def test_fused_matches_loop_and_engages(self):
        l_loop, p_loop, _ = self._train(False)
        l_fused, p_fused, opt = self._train(True)
        # compiled-vs-eager op fusion reorders float math slightly
        np.testing.assert_allclose(l_fused, l_loop, rtol=2e-5)
        for a, b in zip(p_fused, p_loop):
            np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)
        assert getattr(opt, "_fused_fn", None) is not None, \
            "fused path never engaged"
        # one trace signature despite the LR changing mid-run
        assert len(opt._fused_fn._cache) <= 2   # slot-creation + steady

    def test_cache_churn_warns_once(self):
        """r3 weak #8: per-step hyperparameter churn (e.g. mutating a
        param's lr scale every step) silently retraces every step — the
        9th distinct cache signature must warn once."""
        import os
        import warnings
        os.environ["PADDLE_TPU_FUSE_EAGER_STEP"] = "1"
        paddle.seed(12)
        m = paddle.nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        opt._fuse_eager = None
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            for i in range(10):
                for p in m.parameters():   # churn the hyper key each step
                    p.optimize_attr = {"learning_rate": 1.0 + i * 0.01}
                loss = (m(x) ** 2).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
        msgs = [w for w in rec
                if "hyperparameter churn" in str(w.message)]
        assert len(msgs) == 1


class TestAdafactor:
    """Factored second moment (the fix the 1B OOM analysis drives):
    converges, and its stats are ROW+COL sized, not full-matrix."""

    def test_converges_and_factored_state(self):
        paddle.seed(17)
        m = paddle.nn.Linear(16, 8)
        opt = paddle.optimizer.Adafactor(learning_rate=0.3,
                                         parameters=m.parameters())
        rng = np.random.RandomState(3)
        x = paddle.to_tensor(rng.randn(32, 16).astype(np.float32))
        w = rng.randn(16, 8).astype(np.float32)
        y = paddle.to_tensor((np.asarray(x._data) @ w).astype(np.float32))
        losses = []
        for _ in range(60):
            loss = ((m(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(np.asarray(loss._data)))
        assert losses[-1] < 0.2 * losses[0], losses[::6]

        slots = opt._accumulators
        vr = slots["vrow"][id(m.weight)]
        vc = slots["vcol"][id(m.weight)]
        assert tuple(vr._data.shape) == (16,)       # rows of [16, 8]
        assert tuple(vc._data.shape) == (8,)        # cols
        assert "moment2" not in slots or id(m.weight) not in slots.get(
            "moment2", {})  # matrix keeps NO full moment
        # bias (1-D) keeps a full (tiny) second moment
        assert id(m.bias) in slots["moment2"]

    def test_state_dict_roundtrip(self):
        paddle.seed(19)
        m = paddle.nn.Linear(8, 4)
        opt = paddle.optimizer.Adafactor(learning_rate=0.1,
                                         parameters=m.parameters())
        loss = (m(paddle.to_tensor(np.ones((2, 8), np.float32))) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        sd = opt.state_dict()
        assert any("vrow" in k for k in sd) and any("vcol" in k for k in sd)
        opt2 = paddle.optimizer.Adafactor(learning_rate=0.1,
                                          parameters=m.parameters())
        opt2.set_state_dict(sd)
        vr = opt._accumulators["vrow"][id(m.weight)]
        vr2 = opt2._accumulators["vrow"][id(m.weight)]
        np.testing.assert_allclose(np.asarray(vr2._data),
                                   np.asarray(vr._data))

    def test_beta1_and_to_static(self):
        paddle.seed(18)
        m = paddle.nn.Linear(8, 8)
        opt = paddle.optimizer.Adafactor(learning_rate=0.02, beta1=0.9,
                                         parameters=m.parameters())
        x = paddle.to_tensor(np.random.RandomState(4).randn(
            4, 8).astype(np.float32))

        def step(xb):
            loss = (m(xb) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss
        comp = paddle.jit.to_static(step)
        l0 = float(np.asarray(comp(x)._data))
        for _ in range(5):
            ln = float(np.asarray(comp(x)._data))
        assert ln < l0
