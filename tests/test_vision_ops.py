"""paddle.vision.ops parity tests — NumPy oracles.
Reference: python/paddle/vision/ops.py + detection CUDA kernels."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as V


def test_box_iou_pairwise():
    a = np.array([[0, 0, 2, 2], [1, 1, 3, 3]], np.float32)
    b = np.array([[0, 0, 2, 2], [2, 2, 4, 4]], np.float32)
    iou = np.asarray(V.box_iou(paddle.to_tensor(a),
                               paddle.to_tensor(b))._data)
    np.testing.assert_allclose(iou[0, 0], 1.0, rtol=1e-6)
    np.testing.assert_allclose(iou[0, 1], 0.0, atol=1e-7)
    np.testing.assert_allclose(iou[1, 0], 1 / 7, rtol=1e-5)
    np.testing.assert_allclose(iou[1, 1], 1 / 7, rtol=1e-5)


def test_nms_greedy_and_categories():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30],
                      [0, 0, 10, 10]], np.float32)
    scores = np.array([0.9, 0.8, 0.7, 0.95], np.float32)
    keep = np.asarray(V.nms(paddle.to_tensor(boxes), 0.5,
                            paddle.to_tensor(scores))._data)
    # box3 (same as box0, higher score) kept; boxes 0,1 suppressed; box2 kept
    assert keep.tolist() == [3, 2]
    # category-aware: same boxes in different categories both survive
    cats = np.array([0, 0, 0, 1])
    keep2 = np.asarray(V.nms(paddle.to_tensor(boxes), 0.5,
                             paddle.to_tensor(scores),
                             category_idxs=paddle.to_tensor(cats),
                             categories=[0, 1])._data)
    assert 3 in keep2.tolist() and 0 in keep2.tolist()
    keep3 = np.asarray(V.nms(paddle.to_tensor(boxes), 0.5,
                             paddle.to_tensor(scores), top_k=1)._data)
    assert keep3.tolist() == [3]


def test_roi_align_uniform_map():
    """On a constant feature map every aligned RoI returns that constant."""
    feat = np.full((1, 3, 8, 8), 2.5, np.float32)
    boxes = np.array([[0, 0, 4, 4], [2, 2, 7, 7]], np.float32)
    out = V.roi_align(paddle.to_tensor(feat), paddle.to_tensor(boxes),
                      paddle.to_tensor(np.array([2], np.int32)),
                      output_size=2)
    arr = np.asarray(out._data)
    assert arr.shape == (2, 3, 2, 2)
    np.testing.assert_allclose(arr, 2.5, rtol=1e-5)


def test_roi_align_linear_gradient_map():
    """Feature = x coordinate → aligned samples average to bin centers."""
    H = W = 8
    feat = np.tile(np.arange(W, dtype=np.float32), (H, 1))[None, None]
    boxes = np.array([[0, 0, 8, 8]], np.float32)
    out = V.roi_align(paddle.to_tensor(feat), paddle.to_tensor(boxes),
                      paddle.to_tensor(np.array([1], np.int32)),
                      output_size=4, aligned=False)
    arr = np.asarray(out._data)[0, 0]
    # interior bin centers step by 2 in x; the border bin clamps its
    # outside samples to the last column (reference border behavior)
    diffs = np.diff(arr[0])
    np.testing.assert_allclose(diffs[:-1], 2.0, atol=1e-4)
    assert 1.5 <= diffs[-1] <= 2.0


def test_roi_pool_max_semantics():
    feat = np.zeros((1, 1, 6, 6), np.float32)
    feat[0, 0, 1, 1] = 5.0
    feat[0, 0, 4, 4] = 7.0
    boxes = np.array([[0, 0, 5, 5]], np.float32)
    out = V.roi_pool(paddle.to_tensor(feat), paddle.to_tensor(boxes),
                     paddle.to_tensor(np.array([1], np.int32)),
                     output_size=2)
    arr = np.asarray(out._data)[0, 0]
    assert arr[0, 0] == 5.0 and arr[1, 1] == 7.0


def test_roi_align_grad_flows():
    feat = paddle.to_tensor(np.random.RandomState(0).randn(
        1, 2, 8, 8).astype(np.float32), stop_gradient=False)
    boxes = paddle.to_tensor(np.array([[1, 1, 6, 6]], np.float32))
    out = V.roi_align(feat, boxes,
                      paddle.to_tensor(np.array([1], np.int32)), 2)
    out.sum().backward()
    g = np.asarray(feat.grad._data)
    assert g.shape == feat._data.shape and np.abs(g).sum() > 0


def test_box_coder_encode_decode_roundtrip():
    priors = np.array([[0, 0, 10, 10], [5, 5, 15, 15]], np.float32)
    targets = np.array([[1, 1, 9, 9]], np.float32)
    enc = np.asarray(V.box_coder(
        paddle.to_tensor(priors), None, paddle.to_tensor(targets),
        code_type="encode_center_size")._data)
    assert enc.shape == (1, 2, 4)
    dec = np.asarray(V.box_coder(
        paddle.to_tensor(priors), None, paddle.to_tensor(
            enc[0][None].transpose(1, 0, 2)),
        code_type="decode_center_size")._data)
    # decoding the encodings against the same priors recovers the target
    np.testing.assert_allclose(dec[0, 0], targets[0], atol=1e-4)


def test_yolo_box_shapes_and_center():
    rng = np.random.RandomState(1)
    N, A, K, H, W = 1, 2, 3, 4, 4
    x = np.zeros((N, A * (5 + K), H, W), np.float32)
    img = np.array([[128, 128]], np.int32)
    boxes, scores = V.yolo_box(paddle.to_tensor(x), paddle.to_tensor(img),
                               anchors=[10, 14, 23, 27], class_num=K,
                               conf_thresh=0.0, downsample_ratio=32)
    b = np.asarray(boxes._data)
    s = np.asarray(scores._data)
    assert b.shape == (1, A * H * W, 4)
    assert s.shape == (1, A * H * W, K)
    # zero logits → sigmoid 0.5: first cell center = (0.5/4)*128 = 16
    cx = (b[0, 0, 0] + b[0, 0, 2]) / 2
    np.testing.assert_allclose(cx, 16.0, atol=1e-3)


def test_distribute_fpn_proposals():
    rois = np.array([[0, 0, 10, 10],       # small → low level
                     [0, 0, 300, 300]],    # large → high level
                    np.float32)
    outs, restore, nums = V.distribute_fpn_proposals(
        paddle.to_tensor(rois), 2, 5, 4, 224)
    sizes = [np.asarray(o._data).shape[0] for o in outs]
    assert sum(sizes) == 2
    assert np.asarray(outs[0]._data).shape[0] == 1   # level 2 got the small
    r = np.asarray(restore._data)
    cat = np.concatenate([np.asarray(o._data) for o in outs if
                          np.asarray(o._data).size])
    np.testing.assert_allclose(cat[r], rois, rtol=1e-6)


def test_deform_conv2d_zero_offset_matches_conv():
    """With zero offsets, deform_conv2d == standard convolution."""
    rng = np.random.RandomState(2)
    x = rng.randn(1, 2, 6, 6).astype(np.float32)
    w = rng.randn(3, 2, 3, 3).astype(np.float32)
    off = np.zeros((1, 2 * 9, 4, 4), np.float32)
    out = V.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                          paddle.to_tensor(w))
    arr = np.asarray(out._data)
    # oracle: direct correlation
    expect = np.zeros((1, 3, 4, 4), np.float32)
    for o in range(3):
        for i in range(4):
            for j in range(4):
                expect[0, o, i, j] = (x[0, :, i:i + 3, j:j + 3]
                                      * w[o]).sum()
    np.testing.assert_allclose(arr, expect, rtol=1e-3, atol=1e-4)


def test_deform_conv2d_mask_scales_contributions():
    rng = np.random.RandomState(3)
    x = rng.randn(1, 1, 5, 5).astype(np.float32)
    w = rng.randn(1, 1, 3, 3).astype(np.float32)
    off = np.zeros((1, 18, 3, 3), np.float32)
    mask0 = np.zeros((1, 9, 3, 3), np.float32)
    out0 = np.asarray(V.deform_conv2d(
        paddle.to_tensor(x), paddle.to_tensor(off), paddle.to_tensor(w),
        mask=paddle.to_tensor(mask0))._data)
    np.testing.assert_allclose(out0, 0.0, atol=1e-6)
    layer = V.DeformConv2D(1, 2, 3)
    out = layer(paddle.to_tensor(x), paddle.to_tensor(off))
    assert list(out.shape) == [1, 2, 3, 3]


def test_yolo_box_iou_aware_layout():
    N, A, K, H, W = 1, 2, 3, 2, 2
    x = np.zeros((N, A + A * (5 + K), H, W), np.float32)
    img = np.array([[64, 64]], np.int32)
    boxes, scores = V.yolo_box(paddle.to_tensor(x), paddle.to_tensor(img),
                               anchors=[10, 14, 23, 27], class_num=K,
                               conf_thresh=0.0, iou_aware=True,
                               iou_aware_factor=0.5)
    s = np.asarray(scores._data)
    # all-zero logits: conf = 0.5^0.5 * 0.5^0.5 = 0.5; cls = 0.5 → 0.25
    np.testing.assert_allclose(s, 0.25, rtol=1e-5)


def test_distribute_fpn_per_image_counts():
    rois = np.array([[0, 0, 10, 10], [0, 0, 300, 300],
                     [0, 0, 12, 12]], np.float32)
    outs, restore, nums = V.distribute_fpn_proposals(
        paddle.to_tensor(rois), 2, 5, 4, 224,
        rois_num=paddle.to_tensor(np.array([2, 1], np.int32)))
    # level 2 holds the two small rois: one from each image
    np.testing.assert_array_equal(np.asarray(nums[0]._data), [1, 1])
    # restore index reorders concatenated levels back to the input order
    cat = np.concatenate([np.asarray(o._data) for o in outs
                          if np.asarray(o._data).size])
    np.testing.assert_allclose(cat[np.asarray(restore._data)], rois)
