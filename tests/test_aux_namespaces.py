"""metric / regularizer / distribution / fft / signal / version / elastic
(SURVEY §2.6-2.7 inventory lines)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle


class TestMetric:
    def test_accuracy_stream(self):
        m = paddle.metric.Accuracy()
        pred = paddle.to_tensor(np.array([[0.1, 0.9], [0.8, 0.2]], np.float32))
        label = paddle.to_tensor(np.array([[1], [1]], np.int64))
        correct = m.compute(pred, label)
        m.update(correct)
        assert abs(m.accumulate() - 0.5) < 1e-6
        m.reset()
        assert m.accumulate() == 0.0

    def test_precision_recall(self):
        p = paddle.metric.Precision()
        r = paddle.metric.Recall()
        preds = np.array([0.9, 0.8, 0.2, 0.7], np.float32)
        labels = np.array([1, 0, 1, 1], np.int64)
        p.update(preds, labels)
        r.update(preds, labels)
        assert abs(p.accumulate() - 2 / 3) < 1e-6
        assert abs(r.accumulate() - 2 / 3) < 1e-6

    def test_auc_perfect(self):
        auc = paddle.metric.Auc()
        preds = np.array([0.9, 0.8, 0.1, 0.2], np.float32)
        labels = np.array([1, 1, 0, 0], np.int64)
        auc.update(preds, labels)
        assert auc.accumulate() > 0.99

    def test_functional_accuracy(self):
        acc = paddle.metric.accuracy(
            paddle.to_tensor(np.array([[0.1, 0.9], [0.9, 0.1]], np.float32)),
            paddle.to_tensor(np.array([[1], [0]], np.int64)))
        assert float(acc._data) == 1.0


class TestRegularizer:
    def test_l2_decay_changes_update(self):
        import paddle_tpu.nn as nn
        paddle.seed(0)
        m1 = nn.Linear(4, 4)
        m2 = nn.Linear(4, 4)
        m2.set_state_dict(m1.state_dict())
        o1 = paddle.optimizer.Momentum(0.1, parameters=m1.parameters(),
                                       weight_decay=None)
        o2 = paddle.optimizer.Momentum(
            0.1, parameters=m2.parameters(),
            weight_decay=paddle.regularizer.L2Decay(0.5))
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        for m, o in ((m1, o1), (m2, o2)):
            loss = m(x).sum()
            loss.backward()
            o.step()
        w1 = np.asarray(m1.weight._data)
        w2 = np.asarray(m2.weight._data)
        assert not np.allclose(w1, w2)


class TestDistribution:
    def test_normal_logprob_entropy_kl(self):
        d = paddle.distribution.Normal(0.0, 1.0)
        lp = float(d.log_prob(paddle.to_tensor(0.0))._data)
        assert abs(lp - (-0.5 * np.log(2 * np.pi))) < 1e-5
        e = float(d.entropy()._data)
        assert abs(e - 0.5 * (1 + np.log(2 * np.pi))) < 1e-5
        d2 = paddle.distribution.Normal(1.0, 2.0)
        kl = float(paddle.distribution.kl_divergence(d, d2)._data)
        assert kl > 0

    def test_sampling_shapes_and_determinism(self):
        paddle.seed(3)
        d = paddle.distribution.Normal(np.zeros(3, np.float32),
                                       np.ones(3, np.float32))
        s = d.sample((5,))
        assert s.shape == [5, 3]
        c = paddle.distribution.Categorical(
            np.log(np.array([0.999, 0.001], np.float32)))
        draws = c.sample((100,))
        assert np.asarray(draws._data).mean() < 0.1
        b = paddle.distribution.Bernoulli(np.float32(0.0))
        assert float(b.sample()._data) == 0.0


class TestFFT:
    def test_roundtrip(self):
        x = paddle.to_tensor(np.random.RandomState(0).randn(8).astype(
            np.float32))
        y = paddle.fft.fft(x)
        z = paddle.fft.ifft(y)
        np.testing.assert_allclose(np.asarray(z._data).real,
                                   np.asarray(x._data), atol=1e-5)

    def test_rfft_grad(self):
        x = paddle.to_tensor(np.random.RandomState(1).randn(16).astype(
            np.float32), stop_gradient=False)
        y = paddle.fft.rfft(x)
        mag = (y.abs() ** 2).sum()
        mag.backward()
        assert x.grad is not None


class TestSignal:
    def test_stft_istft_roundtrip(self):
        sig = np.sin(np.arange(256) * 0.1).astype(np.float32)[None]
        x = paddle.to_tensor(sig)
        spec = paddle.signal.stft(x, n_fft=64, hop_length=16)
        rec = paddle.signal.istft(spec, n_fft=64, hop_length=16,
                                  length=256)
        np.testing.assert_allclose(np.asarray(rec._data)[0, 8:-8],
                                   sig[0, 8:-8], atol=1e-4)


class TestVersionAndElastic:
    def test_version(self):
        assert paddle.version.full_version
        assert paddle.version.cuda() is False

    def test_elastic_membership(self):
        from paddle_tpu.core.native import load_native
        if load_native() is None:
            pytest.skip("native runtime unavailable")
        from paddle_tpu.distributed.fleet.elastic.manager import (
            ElasticManager, ElasticStatus)
        m = ElasticManager(server="", np="1:4")
        m.enable = True
        m._connect()
        m.register()
        assert m.worker_id in m.alive_workers()
        assert m.watch() == ElasticStatus.HOLD          # first observation
        assert m.watch() == ElasticStatus.HOLD          # unchanged
        m.exit()

    def test_elastic_disabled_noop(self):
        from paddle_tpu.distributed.fleet.elastic.manager import (
            ElasticManager, ElasticStatus)
        m = ElasticManager()
        assert not m.enable
        m.register()
        assert m.watch() == ElasticStatus.COMPLETED


class TestVisualDLLogWriter:
    """SURVEY §5.5 scalar logging: VisualDL-shaped LogWriter over
    TensorBoard event files (+ hapi VisualDL callback)."""

    def test_scalars_histogram_roundtrip(self, tmp_path):
        from paddle_tpu.visualdl import LogWriter
        with LogWriter(logdir=str(tmp_path)) as w:
            for i in range(5):
                w.add_scalar("train/loss", 1.0 / (i + 1), step=i)
            w.add_histogram("w", np.random.RandomState(0).randn(64), step=0)
            w.add_text("note", "hello", step=0)
        files = os.listdir(tmp_path)
        assert any("tfevents" in f or f == "scalars.jsonl" for f in files), \
            files

    def test_hapi_visualdl_callback(self, tmp_path):
        import paddle_tpu as paddle
        from paddle_tpu.hapi.callbacks import VisualDL
        from paddle_tpu.io import TensorDataset
        paddle.seed(0)
        m = paddle.Model(paddle.nn.Linear(4, 2))
        m.prepare(optimizer=paddle.optimizer.SGD(
            0.1, parameters=m.network.parameters()),
            loss=paddle.nn.CrossEntropyLoss())
        x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        y = np.random.RandomState(1).randint(0, 2, (8, 1))
        ds = TensorDataset([paddle.to_tensor(x), paddle.to_tensor(y)])
        cb = VisualDL(log_dir=str(tmp_path))
        m.fit(ds, epochs=1, batch_size=4, verbose=0, callbacks=[cb])
        assert os.listdir(tmp_path)         # events written
