"""Vision datasets vs synthesized standard-format files (SURVEY §2.6)."""
import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from paddle_tpu.vision.datasets import (MNIST, FashionMNIST, Cifar10,
                                        Cifar100, DatasetFolder, ImageFolder)


def _write_mnist(tmp, n=7):
    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 255, (n, 28, 28), dtype=np.uint8)
    labels = rng.randint(0, 10, (n,), dtype=np.uint8)
    ip = os.path.join(tmp, "imgs.gz")
    lp = os.path.join(tmp, "labels.gz")
    with gzip.open(ip, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, 28, 28) + imgs.tobytes())
    with gzip.open(lp, "wb") as f:
        f.write(struct.pack(">II", 2049, n) + labels.tobytes())
    return ip, lp, imgs, labels


def _write_cifar(tmp, cifar100=False, n=6):
    rng = np.random.RandomState(1)
    data = rng.randint(0, 255, (n, 3072), dtype=np.uint8)
    labels = rng.randint(0, 10, (n,)).tolist()
    key = b"fine_labels" if cifar100 else b"labels"
    member = "train" if cifar100 else "data_batch_1"
    payload = pickle.dumps({b"data": data, key: labels})
    path = os.path.join(tmp, "cifar.tar.gz")
    with tarfile.open(path, "w:gz") as tf:
        import io as _io
        info = tarfile.TarInfo(f"cifar/{member}")
        info.size = len(payload)
        tf.addfile(info, _io.BytesIO(payload))
    return path, data, labels


class TestVisionDatasets:
    def test_mnist_roundtrip(self, tmp_path):
        ip, lp, imgs, labels = _write_mnist(str(tmp_path))
        ds = MNIST(image_path=ip, label_path=lp)
        assert len(ds) == len(imgs)
        img, lab = ds[3]
        assert img.shape == (1, 28, 28)
        np.testing.assert_array_equal(img[0], imgs[3].astype(np.float32))
        assert lab == int(labels[3])
        ds2 = FashionMNIST(image_path=ip, label_path=lp)
        assert len(ds2) == len(imgs)

    def test_cifar10_and_100(self, tmp_path):
        p, data, labels = _write_cifar(str(tmp_path))
        ds = Cifar10(data_file=p, mode="train")
        img, lab = ds[2]
        assert img.shape == (3, 32, 32)
        np.testing.assert_array_equal(
            img.reshape(-1), data[2].astype(np.float32))
        assert lab == labels[2]

        p2, d2, l2 = _write_cifar(str(tmp_path), cifar100=True)
        ds2 = Cifar100(data_file=p2, mode="train")
        assert len(ds2) == len(d2)

    def test_missing_file_raises_clear_error(self, tmp_path):
        import pytest
        with pytest.raises(FileNotFoundError, match="network"):
            MNIST(image_path=str(tmp_path / "nope.gz"),
                  label_path=str(tmp_path / "nope2.gz"))

    def test_dataset_folder(self, tmp_path):
        from PIL import Image
        for cls in ("cat", "dog"):
            d = tmp_path / cls
            d.mkdir()
            for i in range(2):
                Image.fromarray(np.full((8, 8, 3), 100 + i,
                                        np.uint8)).save(d / f"{i}.png")
        ds = DatasetFolder(str(tmp_path))
        assert len(ds) == 4
        assert ds.classes == ["cat", "dog"]
        img, target = ds[0]
        assert img.shape == (3, 8, 8) and target == 0
        flat = ImageFolder(str(tmp_path))
        assert len(flat.samples) == 4
        assert flat[0][0].shape == (3, 8, 8)

    def test_with_dataloader(self, tmp_path):
        import paddle_tpu as paddle
        ip, lp, imgs, labels = _write_mnist(str(tmp_path), n=8)
        ds = MNIST(image_path=ip, label_path=lp)
        loader = paddle.io.DataLoader(ds, batch_size=4, shuffle=False)
        batches = list(loader)
        assert len(batches) == 2
        xb, yb = batches[0]
        assert tuple(xb.shape) == (4, 1, 28, 28)
