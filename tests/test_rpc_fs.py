"""paddle.distributed.rpc (multi-process, TCPStore rendezvous) and
fleet.utils.fs parity tests.
Reference: python/paddle/distributed/rpc/, fleet/utils/fs.py."""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet.utils.fs import (ExecuteError, HDFSClient,
                                                   LocalFS)

_RPC_COMPANION = textwrap.dedent("""
    import os, sys, time
    sys.path.insert(0, {repo!r})
    from paddle_tpu.distributed import rpc

    def square(x):
        return x * x

    def whoami():
        return rpc.get_worker_info().name

    rank = int(sys.argv[1])
    port = int(sys.argv[2])
    rpc.init_rpc(name=f"worker{{rank}}", rank=rank, world_size=2,
                 master_endpoint=f"127.0.0.1:{{port}}")
    if rank == 1:
        out = rpc.rpc_sync("worker0", square, args=(7,))
        assert out == 49, out
        fut = rpc.rpc_async("worker0", whoami)
        assert fut.wait(timeout=30) == "worker0"
        # exceptions propagate
        try:
            rpc.rpc_sync("worker0", square, args=("a",))
            raise SystemExit("expected TypeError")
        except TypeError:
            pass
        infos = {{w.name for w in rpc.get_all_worker_infos()}}
        assert infos == {{"worker0", "worker1"}}, infos
        agent = rpc._agent[0]
        agent.store.set("client_done", b"1")   # done-signal, not a sleep
        print("RPC_OK")
    else:
        agent = rpc._agent[0]
        deadline = time.time() + 90
        while time.time() < deadline:
            try:
                if agent.store.get("client_done"):
                    break
            except Exception:
                pass
            time.sleep(0.1)
    rpc.shutdown()
""")


def _free_port():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_rpc_two_process_roundtrip(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "rpc_worker.py"
    script.write_text(_RPC_COMPANION.format(repo=repo))
    port = _free_port()
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PALLAS_AXON_POOL_IPS": ""}
    p0 = subprocess.Popen([sys.executable, str(script), "0", str(port)],
                          stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                          text=True, env=env)
    p1 = subprocess.Popen([sys.executable, str(script), "1", str(port)],
                          stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                          text=True, env=env)
    out1, _ = p1.communicate(timeout=120)
    out0, _ = p0.communicate(timeout=120)
    assert p1.returncode == 0, f"client failed:\n{out1}\nserver:\n{out0}"
    assert "RPC_OK" in out1
    assert p0.returncode == 0, f"server failed:\n{out0}"


def test_localfs_contract(tmp_path):
    fs = LocalFS()
    root = str(tmp_path / "fsroot")
    fs.mkdirs(os.path.join(root, "sub"))
    fs.touch(os.path.join(root, "a.txt"))
    assert fs.is_exist(root) and fs.is_dir(root)
    assert fs.is_file(os.path.join(root, "a.txt"))
    dirs, files = fs.ls_dir(root)
    assert dirs == ["sub"] and files == ["a.txt"]
    fs.mv(os.path.join(root, "a.txt"), os.path.join(root, "b.txt"))
    assert fs.is_file(os.path.join(root, "b.txt"))
    with pytest.raises(ExecuteError):
        fs.touch(os.path.join(root, "b.txt"), exist_ok=False)
    # upload/download are copies locally
    fs.upload(os.path.join(root, "b.txt"), os.path.join(root, "c.txt"))
    assert fs.is_file(os.path.join(root, "c.txt"))
    fs.delete(root)
    assert not fs.is_exist(root)
    assert fs.ls_dir(root) == ([], [])


def test_hdfs_client_gated():
    with pytest.raises(ExecuteError, match="hadoop"):
        HDFSClient("/nonexistent/hadoop_home")
