"""paddle.sparse parity tests — dense NumPy oracles (SURVEY §4 OpTest
pattern). Reference surface: python/paddle/sparse/ + sparse Phi kernels."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse


def _rand_coo(shape=(4, 5), nnz=6, seed=0, with_dups=False):
    rng = np.random.RandomState(seed)
    # unique positions (duplicate cells only when with_dups: unary oracles
    # assume one value per cell, since f(a+b) != f(a)+f(b))
    cells = rng.choice(int(np.prod(shape)), size=nnz, replace=False)
    idx = np.stack(np.unravel_index(cells, shape)).astype(np.int32)
    vals = rng.randn(nnz).astype(np.float32)
    if with_dups:
        idx = np.concatenate([idx, idx[:, :2]], axis=1)
        vals = np.concatenate([vals, rng.randn(2).astype(np.float32)])
    dense = np.zeros(shape, np.float32)
    np.add.at(dense, tuple(idx), vals)
    return idx, vals, dense


def test_coo_create_to_dense_roundtrip():
    idx, vals, dense = _rand_coo(with_dups=True)
    s = sparse.sparse_coo_tensor(idx, vals, dense.shape)
    np.testing.assert_allclose(np.asarray(s.to_dense()._data), dense,
                               rtol=1e-6)
    s2 = paddle.to_tensor(dense).to_sparse_coo()
    np.testing.assert_allclose(np.asarray(s2.to_dense()._data), dense,
                               rtol=1e-6)
    assert s2.is_sparse_coo() and not s2.is_sparse_csr()


def test_csr_roundtrip_and_conversion():
    idx, vals, dense = _rand_coo(shape=(5, 7), nnz=8, seed=1)
    coo = sparse.sparse_coo_tensor(idx, vals, dense.shape)
    csr = coo.to_sparse_csr()
    np.testing.assert_allclose(np.asarray(csr.to_dense()._data), dense,
                               rtol=1e-6)
    back = csr.to_sparse_coo()
    np.testing.assert_allclose(np.asarray(back.to_dense()._data), dense,
                               rtol=1e-6)
    # crows is a proper prefix-sum
    assert csr.crows.shape[0] == dense.shape[0] + 1
    assert int(csr.crows[-1]) == csr.nnz


def test_coalesce_sums_duplicates():
    idx, vals, dense = _rand_coo(with_dups=True)
    s = sparse.coalesce(sparse.sparse_coo_tensor(idx, vals, dense.shape))
    # coalesced: unique indices
    flat = np.ravel_multi_index(np.asarray(s.indices), dense.shape)
    assert len(np.unique(flat)) == len(flat)
    np.testing.assert_allclose(np.asarray(s.to_dense()._data), dense,
                               rtol=1e-6)


def test_sparse_unary_matches_dense():
    idx, vals, dense = _rand_coo(seed=2)
    s = sparse.sparse_coo_tensor(idx, vals, dense.shape)
    for name in ["sin", "tanh", "square", "abs", "neg", "expm1", "relu"]:
        out = getattr(sparse, name)(s)
        ref = getattr(np, name, None)
        if name == "neg":
            expect = -dense
        elif name == "relu":
            expect = np.maximum(dense, 0)
        else:
            expect = ref(dense)
        np.testing.assert_allclose(np.asarray(out.to_dense()._data), expect,
                                   rtol=1e-5, atol=1e-6,
                                   err_msg=f"sparse.{name}")


def test_sparse_add_subtract():
    _, _, d1 = _rand_coo(seed=3)
    _, _, d2 = _rand_coo(seed=4)
    s1 = paddle.to_tensor(d1).to_sparse_coo()
    s2 = paddle.to_tensor(d2).to_sparse_coo()
    np.testing.assert_allclose(
        np.asarray(sparse.add(s1, s2).to_dense()._data), d1 + d2, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(sparse.subtract(s1, s2).to_dense()._data), d1 - d2,
        rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(sparse.multiply(s1, s2).to_dense()._data), d1 * d2,
        rtol=1e-6)


def test_sparse_matmul_coo_and_csr():
    idx, vals, dense = _rand_coo(shape=(4, 6), nnz=7, seed=5)
    y = np.random.RandomState(6).randn(6, 3).astype(np.float32)
    coo = sparse.sparse_coo_tensor(idx, vals, dense.shape)
    out = sparse.matmul(coo, paddle.to_tensor(y))
    np.testing.assert_allclose(np.asarray(out._data), dense @ y, rtol=1e-5,
                               atol=1e-5)
    csr = coo.to_sparse_csr()
    out2 = sparse.matmul(csr, paddle.to_tensor(y))
    np.testing.assert_allclose(np.asarray(out2._data), dense @ y, rtol=1e-5,
                               atol=1e-5)
    # dense @ sparse
    x = np.random.RandomState(7).randn(3, 4).astype(np.float32)
    out3 = sparse.matmul(paddle.to_tensor(x), coo)
    np.testing.assert_allclose(np.asarray(out3._data), x @ dense, rtol=1e-5,
                               atol=1e-5)


def test_sparse_matmul_grad_flows_to_dense_operand():
    idx, vals, dense = _rand_coo(shape=(3, 4), nnz=5, seed=8)
    coo = sparse.sparse_coo_tensor(idx, vals, dense.shape)
    y = paddle.to_tensor(np.random.RandomState(9).randn(4, 2).astype(
        np.float32), stop_gradient=False)
    out = sparse.matmul(coo, y)
    out.sum().backward()
    # d(sum(S@Y))/dY = S^T @ ones
    expect = dense.T @ np.ones((3, 2), np.float32)
    np.testing.assert_allclose(np.asarray(y.grad._data), expect, rtol=1e-5,
                               atol=1e-5)


def test_masked_matmul_sddmm():
    rng = np.random.RandomState(10)
    x = rng.randn(4, 5).astype(np.float32)
    y = rng.randn(5, 6).astype(np.float32)
    mask_d = (rng.rand(4, 6) < 0.4).astype(np.float32)
    mask = paddle.to_tensor(mask_d).to_sparse_csr()
    out = sparse.masked_matmul(paddle.to_tensor(x), paddle.to_tensor(y),
                               mask)
    np.testing.assert_allclose(np.asarray(out.to_dense()._data),
                               (x @ y) * mask_d, rtol=1e-5, atol=1e-5)


def test_addmm():
    rng = np.random.RandomState(11)
    inp = rng.randn(3, 2).astype(np.float32)
    sd = rng.randn(3, 4).astype(np.float32) * (rng.rand(3, 4) < 0.5)
    sd = sd.astype(np.float32)
    y = rng.randn(4, 2).astype(np.float32)
    s = paddle.to_tensor(sd).to_sparse_coo()
    out = sparse.addmm(paddle.to_tensor(inp), s, paddle.to_tensor(y),
                       beta=0.5, alpha=2.0)
    np.testing.assert_allclose(np.asarray(out._data),
                               0.5 * inp + 2.0 * (sd @ y), rtol=1e-5,
                               atol=1e-5)


def test_transpose_reshape_sum():
    idx, vals, dense = _rand_coo(shape=(4, 5), nnz=6, seed=12)
    s = sparse.sparse_coo_tensor(idx, vals, dense.shape)
    t = sparse.transpose(s, [1, 0])
    np.testing.assert_allclose(np.asarray(t.to_dense()._data), dense.T,
                               rtol=1e-6)
    r = sparse.reshape(s, [2, 10])
    np.testing.assert_allclose(np.asarray(r.to_dense()._data),
                               dense.reshape(2, 10), rtol=1e-6)
    total = sparse.sum(s)
    assert total.is_sparse_coo() and total.shape == ()  # reference: sparse out
    np.testing.assert_allclose(float(np.asarray(total.to_dense()._data)),
                               dense.sum(), rtol=1e-5)
    per_axis = sparse.sum(s, axis=1)
    assert per_axis.is_sparse_coo() and per_axis.shape == (4,)
    np.testing.assert_allclose(np.asarray(per_axis.to_dense()._data),
                               dense.sum(axis=1), rtol=1e-5)


def test_sparse_nn_activations_and_softmax():
    idx, vals, dense = _rand_coo(shape=(4, 5), nnz=8, seed=13)
    coo = sparse.sparse_coo_tensor(idx, vals, dense.shape)
    out = sparse.nn.ReLU()(coo)
    np.testing.assert_allclose(np.asarray(out.to_dense()._data),
                               np.maximum(dense, 0), rtol=1e-6)
    csr = coo.to_sparse_csr()
    soft = sparse.nn.Softmax()(csr)
    # oracle: softmax over stored entries per row
    dres = np.asarray(soft.to_dense()._data)
    crows = np.asarray(csr.crows)
    cols = np.asarray(csr.cols)
    v = np.asarray(csr.values._data)
    for r in range(4):
        seg = v[crows[r]:crows[r + 1]]
        if len(seg) == 0:
            continue
        e = np.exp(seg - seg.max())
        expect = e / e.sum()
        got = dres[r, cols[crows[r]:crows[r + 1]]]
        np.testing.assert_allclose(got, expect, rtol=1e-5)


def test_sparse_subm_conv3d_matches_dense_on_support():
    """SubmConv3D vs a dense conv oracle, compared on the input support."""
    rng = np.random.RandomState(14)
    # one batch, 4x4x4 grid, 2 channels, 6 active sites
    shape = (1, 4, 4, 4, 2)
    n = 6
    coords = np.unique(
        np.stack([np.zeros(n, np.int32)] +
                 [rng.randint(0, 4, n).astype(np.int32) for _ in range(3)]),
        axis=1)
    vals = rng.randn(coords.shape[1], 2).astype(np.float32)
    x = sparse.sparse_coo_tensor(coords, vals, shape)
    conv = sparse.nn.SubmConv3D(2, 3, kernel_size=3, padding=1)
    out = conv(x)
    assert out.shape == (1, 4, 4, 4, 3)
    # oracle: dense conv with the same weights, evaluated at active sites
    dense_in = np.asarray(x.to_dense()._data)[0]  # [4,4,4,2]
    w = np.asarray(conv.weight._data).reshape(3, 3, 3, 2, 3)
    b = np.asarray(conv.bias._data)
    out_d = np.asarray(out.to_dense()._data)[0]
    for ci in range(coords.shape[1]):
        _, z, y, xx = coords[:, ci]
        acc = b.copy()
        for dz in range(3):
            for dy in range(3):
                for dx in range(3):
                    iz, iy, ix = z + dz - 1, y + dy - 1, xx + dx - 1
                    if 0 <= iz < 4 and 0 <= iy < 4 and 0 <= ix < 4:
                        acc = acc + dense_in[iz, iy, ix] @ w[dz, dy, dx]
        np.testing.assert_allclose(out_d[z, y, xx], acc, rtol=1e-4,
                                   atol=1e-4)


def test_sparse_conv3d_strided_output_support():
    rng = np.random.RandomState(15)
    shape = (1, 4, 4, 4, 1)
    coords = np.array([[0, 0], [0, 2], [1, 1], [2, 0]], np.int32).T
    coords = np.concatenate([np.zeros((1, coords.shape[1]), np.int32),
                             coords[0:1], coords[1:2],
                             rng.randint(0, 4, (1, coords.shape[1])).astype(
                                 np.int32)])
    vals = rng.randn(coords.shape[1], 1).astype(np.float32)
    x = sparse.sparse_coo_tensor(coords, vals, shape)
    conv = sparse.nn.Conv3D(1, 2, kernel_size=2, stride=2)
    out = conv(x)
    assert out.shape == (1, 2, 2, 2, 2)
    assert np.isfinite(np.asarray(out.values._data)).all()


def test_sparse_batchnorm_and_cast():
    rng = np.random.RandomState(16)
    coords = np.stack([np.zeros(5, np.int32), rng.randint(0, 3, 5),
                       rng.randint(0, 3, 5), rng.randint(0, 3, 5)])
    vals = rng.randn(5, 4).astype(np.float32)
    x = sparse.sparse_coo_tensor(coords, vals, (1, 3, 3, 3, 4))
    bn = sparse.nn.BatchNorm(4)
    out = bn(x)
    v = np.asarray(out.values._data)
    np.testing.assert_allclose(v.mean(axis=0), 0, atol=1e-5)
    np.testing.assert_allclose(v.std(axis=0), 1, atol=1e-2)
    c = sparse.cast(x, value_dtype="int32", index_dtype="int64")
    assert "int32" in str(c.values.dtype)


def test_csr_plus_dense_densifies():
    _, _, d1 = _rand_coo(seed=20)
    csr = paddle.to_tensor(d1).to_sparse_csr()
    y = np.random.RandomState(21).randn(*d1.shape).astype(np.float32)
    out = sparse.add(csr, paddle.to_tensor(y))
    np.testing.assert_allclose(np.asarray(out._data), d1 + y, rtol=1e-6)
    out2 = sparse.subtract(csr, paddle.to_tensor(y))
    np.testing.assert_allclose(np.asarray(out2._data), d1 - y, rtol=1e-6)
    # dense + sparse
    out3 = sparse.add(paddle.to_tensor(y), csr)
    np.testing.assert_allclose(np.asarray(out3._data), y + d1, rtol=1e-6)


def test_transpose_dense_dims_permutes_values():
    rng = np.random.RandomState(22)
    # 1 sparse dim, 2 dense dims: shape (4, 2, 3)
    idx = np.array([[0, 2, 3]], np.int32)
    vals = rng.randn(3, 2, 3).astype(np.float32)
    s = sparse.sparse_coo_tensor(idx, vals, (4, 2, 3))
    t = sparse.transpose(s, [0, 2, 1])
    dense = np.asarray(s.to_dense()._data)
    np.testing.assert_allclose(np.asarray(t.to_dense()._data),
                               dense.transpose(0, 2, 1), rtol=1e-6)
    with pytest.raises(AssertionError):
        sparse.transpose(s, [1, 0, 2])  # mixes sparse/dense dims


def test_sparse_matmul_rejects_batched_dense():
    idx, vals, dense = _rand_coo(shape=(2, 2), nnz=2, seed=30)
    coo = sparse.sparse_coo_tensor(idx, vals, dense.shape)
    with pytest.raises(AssertionError, match="2-D"):
        sparse.matmul(coo, paddle.to_tensor(
            np.zeros((3, 2, 2), np.float32)))


def test_sparse_reshape_hybrid_preserves_dense_tail():
    rng = np.random.RandomState(31)
    idx = np.array([[0, 2, 3]], np.int32)
    vals = rng.randn(3, 2).astype(np.float32)
    s = sparse.sparse_coo_tensor(idx, vals, (4, 2))
    r = sparse.reshape(s, (2, 2, 2))
    dense = np.asarray(s.to_dense()._data)
    np.testing.assert_allclose(np.asarray(r.to_dense()._data),
                               dense.reshape(2, 2, 2), rtol=1e-6)


def test_rulebook_cache_reused_across_layers():
    from paddle_tpu.sparse import nn as snn
    snn.clear_rulebook_cache()
    rng = np.random.RandomState(50)
    coords = np.stack([np.zeros(6, np.int32), rng.randint(0, 4, 6),
                       rng.randint(0, 4, 6), rng.randint(0, 4, 6)])
    vals = rng.randn(6, 2).astype(np.float32)
    x = sparse.sparse_coo_tensor(coords, vals, (1, 4, 4, 4, 2))
    c1 = snn.SubmConv3D(2, 3, 3, padding=1)
    c2 = snn.SubmConv3D(3, 2, 3, padding=1)
    h = c1(x)
    n_after_first = len(snn._RULEBOOK_CACHE)
    out = c2(h)   # same active sites + geometry → cache hit
    assert len(snn._RULEBOOK_CACHE) == n_after_first
    assert np.isfinite(np.asarray(out.values._data)).all()
