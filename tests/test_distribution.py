"""paddle.distribution breadth: moment/log_prob/KL oracles.
Reference: python/paddle/distribution/."""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import distribution as D


def _lp(dist, v):
    return np.asarray(dist.log_prob(paddle.to_tensor(
        np.asarray(v, np.float32)))._data)


def test_beta_moments_logprob_entropy():
    d = D.Beta(2.0, 3.0)
    np.testing.assert_allclose(float(np.asarray(d.mean._data)), 0.4,
                               rtol=1e-6)
    np.testing.assert_allclose(float(np.asarray(d.variance._data)),
                               2 * 3 / (25.0 * 6), rtol=1e-5)
    # pdf(0.5; 2,3) = x(1-x)^2 / B(2,3), B(2,3)=1/12
    np.testing.assert_allclose(_lp(d, 0.5),
                               np.log(12 * 0.5 * 0.25), rtol=1e-5)
    paddle.seed(0)
    s = np.asarray(d.sample([20000])._data)
    assert ((s > 0) & (s < 1)).all()
    np.testing.assert_allclose(s.mean(), 0.4, atol=0.01)


def test_gamma_exponential_consistency():
    g = D.Gamma(1.0, 2.0)       # Gamma(1, rate) == Exponential(rate)
    e = D.Exponential(2.0)
    for v in (0.1, 0.7, 2.0):
        np.testing.assert_allclose(_lp(g, v), _lp(e, v), rtol=1e-5)
    np.testing.assert_allclose(float(np.asarray(g.mean._data)), 0.5)
    paddle.seed(1)
    s = np.asarray(D.Gamma(3.0, 2.0).sample([20000])._data)
    np.testing.assert_allclose(s.mean(), 1.5, atol=0.03)


def test_dirichlet():
    d = D.Dirichlet(np.array([2.0, 3.0, 5.0], np.float32))
    np.testing.assert_allclose(np.asarray(d.mean._data), [0.2, 0.3, 0.5],
                               rtol=1e-6)
    paddle.seed(2)
    s = np.asarray(d.sample([10000])._data)
    np.testing.assert_allclose(s.sum(-1), 1.0, atol=1e-5)
    np.testing.assert_allclose(s.mean(0), [0.2, 0.3, 0.5], atol=0.01)
    v = np.array([0.2, 0.3, 0.5], np.float32)
    # analytic: log Dir pdf with alpha (2,3,5)
    from math import lgamma, log
    expect = (lgamma(10) - lgamma(2) - lgamma(3) - lgamma(5)
              + 1 * log(0.2) + 2 * log(0.3) + 4 * log(0.5))
    np.testing.assert_allclose(_lp(d, v), expect, rtol=1e-5)


def test_discrete_families():
    paddle.seed(3)
    geo = D.Geometric(0.25)
    np.testing.assert_allclose(float(np.asarray(geo.mean._data)), 3.0)
    np.testing.assert_allclose(_lp(geo, 2), np.log(0.75 ** 2 * 0.25),
                               rtol=1e-5)
    s = np.asarray(geo.sample([30000])._data)
    np.testing.assert_allclose(s.mean(), 3.0, atol=0.15)

    poi = D.Poisson(4.0)
    np.testing.assert_allclose(_lp(poi, 3),
                               np.log(np.exp(-4) * 4 ** 3 / 6), rtol=1e-5)
    s = np.asarray(poi.sample([30000])._data)
    np.testing.assert_allclose(s.mean(), 4.0, atol=0.1)

    b = D.Binomial(10, 0.3)
    np.testing.assert_allclose(_lp(b, 4),
                               np.log(210 * 0.3 ** 4 * 0.7 ** 6),
                               rtol=1e-5)

    m = D.Multinomial(5, np.array([0.2, 0.8], np.float32))
    s = np.asarray(m.sample([2000])._data)
    np.testing.assert_allclose(s.sum(-1), 5.0)
    np.testing.assert_allclose(s.mean(0), [1.0, 4.0], atol=0.15)
    np.testing.assert_allclose(
        _lp(m, [2, 3]), np.log(10 * 0.2 ** 2 * 0.8 ** 3), rtol=1e-5)


def test_heavy_tails_and_location_scale():
    lap = D.Laplace(1.0, 2.0)
    np.testing.assert_allclose(_lp(lap, 3.0), -1.0 - np.log(4.0),
                               rtol=1e-5)
    gum = D.Gumbel(0.0, 1.0)
    np.testing.assert_allclose(_lp(gum, 0.0), -1.0, rtol=1e-5)
    st = D.StudentT(3.0)
    # t3 pdf at 0 = Γ(2)/(Γ(1.5)·sqrt(3π))
    expect = math.lgamma(2.0) - math.lgamma(1.5) - 0.5 * np.log(
        3 * np.pi)
    np.testing.assert_allclose(_lp(st, 0.0), expect, rtol=1e-5)
    c = D.Cauchy(0.0, 1.0)
    np.testing.assert_allclose(_lp(c, 0.0), -np.log(np.pi), rtol=1e-5)
    ln = D.LogNormal(0.0, 0.5)
    paddle.seed(4)
    s = np.asarray(ln.sample([30000])._data)
    np.testing.assert_allclose(np.log(s).mean(), 0.0, atol=0.01)
    np.testing.assert_allclose(float(np.asarray(ln.mean._data)),
                               np.exp(0.125), rtol=1e-5)


def test_kl_registry_and_formulas():
    # Normal — closed form
    kl = D.kl_divergence(D.Normal(0.0, 1.0), D.Normal(1.0, 2.0))
    expect = np.log(2.0) + (1 + 1) / 8.0 - 0.5
    np.testing.assert_allclose(float(np.asarray(kl._data)), expect,
                               rtol=1e-5)
    # KL(p||p) == 0 for several families
    for p in (D.Beta(2.0, 3.0), D.Gamma(2.0, 1.0), D.Exponential(0.7),
              D.Laplace(0.0, 1.0)):
        z = D.kl_divergence(p, p)
        np.testing.assert_allclose(float(np.asarray(z._data)), 0.0,
                                   atol=1e-5)
    # exponential KL formula vs monte carlo
    p, q = D.Exponential(2.0), D.Exponential(1.0)
    paddle.seed(5)
    s = p.sample([100000])
    mc = float(np.asarray((_lp(p, np.asarray(s._data))
                           - _lp(q, np.asarray(s._data))).mean()))
    np.testing.assert_allclose(float(np.asarray(
        D.kl_divergence(p, q)._data)), mc, atol=0.02)
    # custom registration
    class MyDist(D.Distribution):
        pass

    @D.register_kl(MyDist, MyDist)
    def _kl_my(p, q):
        return paddle.to_tensor(np.float32(42.0))

    assert float(np.asarray(D.kl_divergence(MyDist(), MyDist())._data)) \
        == 42.0
    with pytest.raises(NotImplementedError):
        D.kl_divergence(MyDist(), D.Normal(0.0, 1.0))


def test_transforms_and_transformed_distribution():
    t = D.AffineTransform(1.0, 2.0)
    x = paddle.to_tensor(np.array([0.0, 1.0], np.float32))
    y = t.forward(x)
    np.testing.assert_allclose(np.asarray(y._data), [1.0, 3.0])
    np.testing.assert_allclose(np.asarray(t.inverse(y)._data),
                               np.asarray(x._data), atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(t.forward_log_det_jacobian(x)._data), np.log(2.0))

    # LogNormal == exp(Normal): TransformedDistribution log_prob must match
    base = D.Normal(0.3, 0.7)
    td = D.TransformedDistribution(base, [D.ExpTransform()])
    ln = D.LogNormal(0.3, 0.7)
    v = np.array([0.5, 1.0, 2.5], np.float32)
    np.testing.assert_allclose(_lp(td, v), _lp(ln, v), rtol=1e-5)

    # chain: sigmoid(affine(x))
    chain = D.ChainTransform([D.AffineTransform(0.0, 2.0),
                              D.SigmoidTransform()])
    xv = np.array([0.3], np.float32)
    fwd = 1 / (1 + np.exp(-2 * 0.3))
    np.testing.assert_allclose(
        np.asarray(chain.forward(paddle.to_tensor(xv))._data), fwd,
        rtol=1e-6)
    inv = chain.inverse(paddle.to_tensor(np.array([fwd], np.float32)))
    np.testing.assert_allclose(np.asarray(inv._data), xv, atol=1e-5)
    # tanh transform ldj matches direct formula
    tt = D.TanhTransform()
    np.testing.assert_allclose(
        np.asarray(tt.forward_log_det_jacobian(paddle.to_tensor(
            np.array([0.5], np.float32)))._data),
        np.log(1 - np.tanh(0.5) ** 2), rtol=1e-5)


def test_transformed_sampling_statistics():
    paddle.seed(6)
    td = D.TransformedDistribution(D.Normal(0.0, 1.0),
                                   [D.AffineTransform(5.0, 3.0)])
    s = np.asarray(td.sample([50000])._data)
    np.testing.assert_allclose(s.mean(), 5.0, atol=0.05)
    np.testing.assert_allclose(s.std(), 3.0, atol=0.05)


def test_multivariate_normal():
    cov = np.array([[2.0, 0.3], [0.3, 1.0]], np.float32)
    loc = np.array([1.0, -1.0], np.float32)
    mvn = D.MultivariateNormal(loc, covariance_matrix=cov)
    paddle.seed(0)
    s = np.asarray(mvn.sample((20000,))._data)
    np.testing.assert_allclose(s.mean(0), loc, atol=0.05)
    np.testing.assert_allclose(np.cov(s.T), cov, atol=0.1)
    from scipy.stats import multivariate_normal as ref
    pt = np.array([0.5, 0.5], np.float32)
    np.testing.assert_allclose(
        float(np.asarray(mvn.log_prob(paddle.to_tensor(pt))._data)),
        ref(loc, cov).logpdf(pt), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(mvn.covariance_matrix._data),
                               cov, rtol=1e-5)
    ent = float(np.asarray(mvn.entropy()._data))
    np.testing.assert_allclose(ent, ref(loc, cov).entropy(), rtol=1e-5)
    with pytest.raises(ValueError):
        D.MultivariateNormal(loc)


def test_chi2_matches_gamma_and_scipy():
    c2 = D.Chi2(4.0)
    from scipy.stats import chi2 as ref
    v = np.array([1.0, 3.0, 7.0], np.float32)
    np.testing.assert_allclose(
        np.asarray(c2.log_prob(paddle.to_tensor(v))._data),
        ref(4.0).logpdf(v), rtol=1e-4)
    assert float(np.asarray(c2.mean._data)) == pytest.approx(4.0)
    assert float(np.asarray(c2.variance._data)) == pytest.approx(8.0)


def test_continuous_bernoulli():
    cb = D.ContinuousBernoulli(0.3)
    paddle.seed(1)
    s = np.asarray(cb.sample((40000,))._data)
    assert 0.0 <= s.min() and s.max() <= 1.0
    np.testing.assert_allclose(s.mean(),
                               float(np.asarray(cb.mean._data)), atol=0.01)
    # log_prob integrates to ~1 over [0,1]
    xs = np.linspace(1e-4, 1 - 1e-4, 2001).astype(np.float32)
    lp = np.asarray(cb.log_prob(paddle.to_tensor(xs))._data)
    integral = np.trapezoid(np.exp(lp), xs)
    np.testing.assert_allclose(integral, 1.0, atol=1e-3)
    # near-0.5 Taylor branch stays finite and ~Uniform
    cb5 = D.ContinuousBernoulli(0.5)
    lp5 = np.asarray(cb5.log_prob(paddle.to_tensor(
        np.array([0.25, 0.75], np.float32)))._data)
    np.testing.assert_allclose(lp5, 0.0, atol=1e-2)
