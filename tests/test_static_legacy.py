"""Legacy static-graph API: append_backward/gradients grad handles,
static.nn builders, scope_guard, places, EMA, py_func,
set_program_state."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as S


def _fresh_programs():
    main, startup = S.Program(), S.Program()
    return main, startup


def test_append_backward_grad_fetch():
    paddle.enable_static()
    try:
        main, startup = _fresh_programs()
        with S.program_guard(main, startup):
            x = S.data("x", [None, 4], "float32")
            w = paddle.create_parameter([4, 1], "float32")
            y = paddle.matmul(x, w)
            loss = paddle.mean(y)
            pairs = S.append_backward(loss, parameter_list=[w])
        exe = S.Executor()
        xs = np.ones((3, 4), np.float32)
        (gw,) = exe.run(main, feed={"x": xs}, fetch_list=[pairs[0][1]])
        # d(mean(x@w))/dw = mean over batch of x rows = column of 1s / 1
        np.testing.assert_allclose(gw.ravel(), np.full(4, 1.0), rtol=1e-5)
        # second run must give identical grads (no accumulation)
        (gw2,) = exe.run(main, feed={"x": xs}, fetch_list=[pairs[0][1]])
        np.testing.assert_allclose(gw2, gw, rtol=1e-6)
    finally:
        paddle.disable_static()


def test_gradients_wrt_input():
    paddle.enable_static()
    try:
        main, startup = _fresh_programs()
        with S.program_guard(main, startup):
            x = S.data("x", [2, 3], "float32")
            x.stop_gradient = False
            y = paddle.sum(x * x)
            (gx,) = S.gradients([y], [x])
        exe = S.Executor()
        xs = np.arange(6, dtype=np.float32).reshape(2, 3)
        (g,) = exe.run(main, feed={"x": xs}, fetch_list=[gx])
        np.testing.assert_allclose(g, 2 * xs, rtol=1e-5)
    finally:
        paddle.disable_static()


def test_gradients_multi_target_sums():
    paddle.enable_static()
    try:
        main, _ = _fresh_programs()
        with S.program_guard(main):
            x = S.data("x", [2, 2], "float32")
            x.stop_gradient = False
            y1 = paddle.sum(x * x)      # d/dx = 2x
            y2 = paddle.sum(3.0 * x)    # d/dx = 3
            (gx,) = S.gradients([y1, y2], [x])
        exe = S.Executor()
        xs = np.arange(4, dtype=np.float32).reshape(2, 2)
        (g,) = exe.run(main, feed={"x": xs}, fetch_list=[gx])
        np.testing.assert_allclose(g, 2 * xs + 3.0, rtol=1e-5)
    finally:
        paddle.disable_static()


def test_fc_dynamic_batch_with_flatten():
    paddle.enable_static()
    try:
        main, _ = _fresh_programs()
        with S.program_guard(main):
            x = S.data("x", [None, 4, 4], "float32")
            out = S.nn.fc(x, 16)     # flattens trailing dims at replay
        exe = S.Executor()
        res = exe.run(main, feed={"x": np.ones((3, 4, 4), np.float32)},
                      fetch_list=[out])
        assert res[0].shape == (3, 16)
    finally:
        paddle.disable_static()


def test_static_nn_builders():
    paddle.enable_static()
    try:
        main, startup = _fresh_programs()
        with S.program_guard(main, startup):
            x = S.data("x", [None, 8], "float32")
            h = S.nn.fc(x, 16, activation="relu")
            h = S.nn.dropout(h, 0.0)
            out = S.nn.fc(h, 3)
        exe = S.Executor()
        res = exe.run(main, feed={"x": np.ones((2, 8), np.float32)},
                      fetch_list=[out])
        assert res[0].shape == (2, 3)

        main2, _ = _fresh_programs()
        with S.program_guard(main2):
            img = S.data("img", [None, 3, 8, 8], "float32")
            c = S.nn.conv2d(img, 4, 3, padding=1, act="relu")
            c = S.nn.batch_norm(c)
            c = S.nn.layer_norm(c, begin_norm_axis=1)
        res2 = exe.run(main2, feed={"img": np.random.RandomState(0)
                                    .rand(2, 3, 8, 8).astype(np.float32)},
                       fetch_list=[c])
        assert res2[0].shape == (2, 4, 8, 8)

        main3, _ = _fresh_programs()
        with S.program_guard(main3):
            ids = S.data("ids", [None, 5], "int32")
            e = S.nn.embedding(ids, (100, 16))
        res3 = exe.run(main3, feed={"ids": np.zeros((2, 5), np.int32)},
                       fetch_list=[e])
        assert res3[0].shape == (2, 5, 16)
    finally:
        paddle.disable_static()


def test_scope_guard_and_places():
    sc = S.Scope()
    with S.scope_guard(sc):
        assert S.global_scope() is sc
    assert S.global_scope() is not sc
    assert len(S.cpu_places(2)) == 2
    with S.device_guard("cpu"):
        pass


def test_set_program_state():
    paddle.enable_static()
    try:
        main, _ = _fresh_programs()
        with S.program_guard(main):
            x = S.data("x", [1, 2], "float32")
            w = paddle.create_parameter([2, 2], "float32")
            w.name = "w0"
            y = paddle.matmul(x, w)
        new_w = np.eye(2, dtype=np.float32) * 3
        S.set_program_state(main, {"w0": new_w})
        exe = S.Executor()
        (out,) = exe.run(main, feed={"x": np.ones((1, 2), np.float32)},
                         fetch_list=[y])
        np.testing.assert_allclose(out, np.full((1, 2), 3.0))
    finally:
        paddle.disable_static()


def test_exponential_moving_average():
    paddle.enable_static()
    try:
        main, _ = _fresh_programs()
        with S.program_guard(main):
            w = paddle.create_parameter([2], "float32")
        import jax.numpy as jnp
        ema = S.ExponentialMovingAverage(decay=0.5)
        ema._params = [w]
        ema._ema[w._uid] = jnp.zeros(2, jnp.float32)
        w._data = jnp.asarray([3.0, 4.0], jnp.float32)
        ema.update()       # ema = .5*0 + .5*[3,4] = [1.5, 2]
        w._data = jnp.asarray([5.0, 6.0], jnp.float32)
        ema.update()       # ema = .5*[1.5,2] + .5*[5,6] = [3.25, 4]
        cur = np.asarray(w._data).copy()
        with ema.apply():
            # bias correction 1 - .5^2 = .75 -> [3.25,4]/.75
            np.testing.assert_allclose(np.asarray(w._data),
                                       [3.25 / 0.75, 4.0 / 0.75],
                                       rtol=1e-5)
        np.testing.assert_allclose(np.asarray(w._data), cur)
    finally:
        paddle.disable_static()


def test_py_func():
    paddle.enable_static()
    try:
        main, _ = _fresh_programs()
        with S.program_guard(main):
            x = S.data("x", [2, 3], "float32")
            out = paddle.zeros([2, 3], "float32")
            S.py_func(lambda a: a * 2.0, x, out)
        exe = S.Executor()
        xs = np.arange(6, dtype=np.float32).reshape(2, 3)
        (o,) = exe.run(main, feed={"x": xs}, fetch_list=[out])
        np.testing.assert_allclose(o, xs * 2)
        with pytest.raises(NotImplementedError):
            S.py_func(lambda a: a, x, out, backward_func=lambda g: g)
    finally:
        paddle.disable_static()
