"""paddle_tpu.distributed.launch CLI: env contract, logs, restart
(SURVEY §2.5 Launcher, §5.3 failure detection)."""
import os
import subprocess
import sys

COMPANION = """
import os, sys
rank = os.environ["PADDLE_TRAINER_ID"]
assert os.environ["PADDLE_TRAINERS_NUM"] == "2"
assert os.environ["PADDLE_MASTER"]
assert os.environ["JAX_PROCESS_ID"] == rank
print("rank", rank, "ok")
marker = sys.argv[1] + "/done." + rank
open(marker, "w").write("1")
"""

FLAKY = """
import os, sys
attempt_file = sys.argv[1] + "/attempts"
n = int(open(attempt_file).read()) if os.path.exists(attempt_file) else 0
open(attempt_file, "w").write(str(n + 1))
sys.exit(0 if n >= 1 else 1)      # fail on first attempt, pass on second
"""


def _run_launch(tmp_path, script_body, extra_args, script_args):
    script = tmp_path / "companion.py"
    script.write_text(script_body)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--log_dir", str(tmp_path / "log")] + extra_args +
        [str(script)] + script_args,
        env=env, cwd=os.path.dirname(os.path.dirname(__file__)),
        capture_output=True, text=True, timeout=120)


class TestLaunchCLI:
    def test_two_proc_env_contract_and_logs(self, tmp_path):
        r = _run_launch(tmp_path, COMPANION, ["--nproc_per_node", "2"],
                        [str(tmp_path)])
        assert r.returncode == 0, r.stderr
        assert (tmp_path / "done.0").exists()
        assert (tmp_path / "done.1").exists()
        # non-zero ranks log to workerlog.N
        assert "ok" in (tmp_path / "log" / "workerlog.1").read_text()

    def test_max_restart_retries_failed_pod(self, tmp_path):
        r = _run_launch(tmp_path, FLAKY,
                        ["--nproc_per_node", "1", "--max_restart", "2"],
                        [str(tmp_path)])
        assert r.returncode == 0, r.stderr
        assert (tmp_path / "attempts").read_text() == "2"

    def test_failure_propagates_exit_code(self, tmp_path):
        r = _run_launch(tmp_path, "import sys; sys.exit(3)\n",
                        ["--nproc_per_node", "1"], [])
        assert r.returncode == 3
