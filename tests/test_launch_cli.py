"""paddle_tpu.distributed.launch CLI: env contract, logs, restart
(SURVEY §2.5 Launcher, §5.3 failure detection)."""
import os
import subprocess
import sys

import numpy as np

COMPANION = """
import os, sys
rank = os.environ["PADDLE_TRAINER_ID"]
assert os.environ["PADDLE_TRAINERS_NUM"] == "2"
assert os.environ["PADDLE_MASTER"]
assert os.environ["JAX_PROCESS_ID"] == rank
print("rank", rank, "ok")
marker = sys.argv[1] + "/done." + rank
open(marker, "w").write("1")
"""

FLAKY = """
import os, sys
attempt_file = sys.argv[1] + "/attempts"
n = int(open(attempt_file).read()) if os.path.exists(attempt_file) else 0
open(attempt_file, "w").write(str(n + 1))
sys.exit(0 if n >= 1 else 1)      # fail on first attempt, pass on second
"""


def _run_launch(tmp_path, script_body, extra_args, script_args):
    script = tmp_path / "companion.py"
    script.write_text(script_body)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--log_dir", str(tmp_path / "log")] + extra_args +
        [str(script)] + script_args,
        env=env, cwd=os.path.dirname(os.path.dirname(__file__)),
        capture_output=True, text=True, timeout=240)


class TestLaunchCLI:
    def test_two_proc_env_contract_and_logs(self, tmp_path):
        r = _run_launch(tmp_path, COMPANION, ["--nproc_per_node", "2"],
                        [str(tmp_path)])
        assert r.returncode == 0, r.stderr
        assert (tmp_path / "done.0").exists()
        assert (tmp_path / "done.1").exists()
        # non-zero ranks log to workerlog.N
        assert "ok" in (tmp_path / "log" / "workerlog.1").read_text()

    def test_max_restart_retries_failed_pod(self, tmp_path):
        r = _run_launch(tmp_path, FLAKY,
                        ["--nproc_per_node", "1", "--max_restart", "2"],
                        [str(tmp_path)])
        assert r.returncode == 0, r.stderr
        assert (tmp_path / "attempts").read_text() == "2"

    def test_failure_propagates_exit_code(self, tmp_path):
        r = _run_launch(tmp_path, "import sys; sys.exit(3)\n",
                        ["--nproc_per_node", "1"], [])
        assert r.returncode == 3


FT_TRAIN = """
# Fault-tolerance companion (SURVEY §5.3): trains a Linear regressor,
# checkpoints every step, dies mid-training on the first attempt, and on
# relaunch resumes from the checkpoint. The loss curve file must end up
# identical to an uninterrupted run.
import os, sys, json
import numpy as np
import paddle_tpu as paddle

workdir = sys.argv[1]
kill_at = int(sys.argv[2])        # <0: never (the uninterrupted oracle run)
steps = 8

paddle.seed(7)
m = paddle.nn.Linear(4, 1)
opt = paddle.optimizer.SGD(0.2, parameters=m.parameters())

ck = os.path.join(workdir, "ck.pdparams")
curve_path = os.path.join(workdir, "curve.jsonl")
start = 0
if os.path.exists(ck):
    state = paddle.load(ck)
    m.set_state_dict(state["model"])
    opt.set_state_dict(state["opt"])
    start = state["step"]

rng = np.random.RandomState(0)
xs = rng.randn(steps, 16, 4).astype(np.float32)
w_true = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)

for step in range(start, steps):
    x = paddle.to_tensor(xs[step])
    y = paddle.to_tensor(xs[step] @ w_true)
    loss = ((m(x) - y) ** 2).mean()
    loss.backward()
    opt.step()
    opt.clear_grad()
    with open(curve_path, "a") as f:
        f.write(json.dumps({"step": step,
                            "loss": float(np.asarray(loss._data))}) + "\\n")
    paddle.save({"model": m.state_dict(), "opt": opt.state_dict(),
                 "step": step + 1}, ck)
    if step + 1 == kill_at and not os.path.exists(
            os.path.join(workdir, "died")):
        open(os.path.join(workdir, "died"), "w").write("1")
        os._exit(17)              # simulated worker crash mid-training
"""


class TestFaultToleranceResume:
    def _curve(self, path):
        import json
        rows = [json.loads(l) for l in open(path)]
        # resumed runs re-log nothing before `start`; keep last value per step
        by_step = {}
        for r in rows:
            by_step[r["step"]] = r["loss"]
        return [by_step[i] for i in sorted(by_step)]

    def test_kill_relaunch_resume_matches_uninterrupted(self, tmp_path):
        """Reference contract (launch/controllers/controller.py + elastic):
        a worker dying mid-training is relaunched by --max_restart and the
        checkpoint-resumed loss curve equals the uninterrupted one."""
        int_dir = tmp_path / "interrupted"
        ref_dir = tmp_path / "oracle"
        int_dir.mkdir(), ref_dir.mkdir()

        r = _run_launch(tmp_path, FT_TRAIN,
                        ["--nproc_per_node", "1", "--max_restart", "1"],
                        [str(int_dir), "4"])
        assert r.returncode == 0, r.stderr
        assert (int_dir / "died").exists()          # it really crashed
        assert "restarting" in r.stderr             # launcher relaunched it

        r2 = _run_launch(tmp_path, FT_TRAIN,
                         ["--nproc_per_node", "1"], [str(ref_dir), "-1"])
        assert r2.returncode == 0, r2.stderr

        got = self._curve(int_dir / "curve.jsonl")
        want = self._curve(ref_dir / "curve.jsonl")
        assert len(got) == len(want) == 8
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_no_restart_budget_fails(self, tmp_path):
        d = tmp_path / "nobudget"
        d.mkdir()
        r = _run_launch(tmp_path, FT_TRAIN, ["--nproc_per_node", "1"],
                        [str(d), "2"])
        assert r.returncode == 17                   # crash surfaces


MP_COLLECTIVES = """
# world=2 eager collectives companion: exercises ProcessGroupXLA's
# multi-process path (make_array_from_process_local_data + cached
# shard_map) against hand-computed values — VERDICT r1 weak-8.
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed as dist

env = dist.init_parallel_env()
rank, world = env.rank, env.world_size
assert world == 2, world

# all_reduce: sum of (rank+1)*[1,2,3] over 2 ranks
t = paddle.to_tensor(np.array([1., 2., 3.], np.float32) * (rank + 1))
dist.all_reduce(t)
np.testing.assert_allclose(np.asarray(t._data), [3., 6., 9.])

# all_gather
outs = []
dist.all_gather(outs, paddle.to_tensor(
    np.array([float(rank)], np.float32)))
got = sorted(float(np.asarray(o._data)[0]) for o in outs)
assert got == [0.0, 1.0], got

# broadcast from rank 0
b = paddle.to_tensor(np.array([rank * 10.0 + 5.0], np.float32))
dist.broadcast(b, src=0)
np.testing.assert_allclose(np.asarray(b._data), [5.0])

# reduce to dst=1: only dst must hold the sum
r = paddle.to_tensor(np.array([float(rank + 1)], np.float32))
dist.reduce(r, dst=1)
expect = 3.0 if rank == 1 else float(rank + 1)
np.testing.assert_allclose(np.asarray(r._data), [expect])

# reduce_scatter: each rank holds [r+1, r+2]; sums [3, 5]; rank r gets [3+2r]
rs_out = paddle.to_tensor(np.zeros((1,), np.float32))
rs_in = [paddle.to_tensor(np.array([rank + 1.0], np.float32)),
         paddle.to_tensor(np.array([rank + 2.0], np.float32))]
dist.reduce_scatter(rs_out, rs_in)
np.testing.assert_allclose(np.asarray(rs_out._data).reshape(-1),
                           [3.0 + 2.0 * rank])

# alltoall: rank r sends [r*10+0, r*10+1] -> rank r receives [r, 10+r]
a2a_out = []
dist.alltoall([paddle.to_tensor(np.array([rank * 10.0], np.float32)),
               paddle.to_tensor(np.array([rank * 10.0 + 1.0], np.float32))],
              a2a_out)
got2 = [float(np.asarray(t._data).reshape(-1)[0]) for t in a2a_out]
assert got2 == [0.0 + rank, 10.0 + rank], got2

open(sys.argv[1] + f"/ok.{rank}", "w").write("1")
print("rank", rank, "collectives ok")
"""


class TestMultiProcessCollectives:
    def test_world2_eager_collectives(self, tmp_path):
        r = _run_launch(tmp_path, MP_COLLECTIVES,
                        ["--nproc_per_node", "2"], [str(tmp_path)])
        assert r.returncode == 0, (r.stdout, r.stderr)
        assert (tmp_path / "ok.0").exists() and (tmp_path / "ok.1").exists()


ELASTIC_WORKER = """
# Elastic end-to-end companion: each worker registers + heartbeats; rank 0
# watches membership. Worker 1 exits mid-run -> rank 0 must observe RESTART
# (scale-down) within the timeout. SURVEY §5.3 / VERDICT r1 elastic gap.
import os, sys, time
from paddle_tpu.distributed.fleet.elastic.manager import (ElasticManager,
                                                          ElasticStatus)
workdir = sys.argv[1]
rank = os.environ["PADDLE_TRAINER_ID"]
os.environ["PADDLE_ELASTIC_ENABLE"] = "1"
os.environ["PADDLE_ELASTIC_NP"] = "1:2"
os.environ["PADDLE_ELASTIC_SERVER"] = os.environ["PADDLE_MASTER"].rsplit(
    ":", 1)[0] + ":" + str(int(os.environ["PADDLE_MASTER"].rsplit(
        ":", 1)[1]) + 37)

mgr = ElasticManager(heartbeat_interval=0.2)
mgr.register()
if rank == "1":
    time.sleep(2.0)
    mgr.exit(completed=False)      # stop heartbeating: simulated departure
    open(workdir + "/left.1", "w").write("1")
    sys.exit(0)

# rank 0: wait until both workers seen, then watch for the departure
deadline = time.time() + 30
st = None
saw_two = False
while time.time() < deadline:
    alive = mgr.alive_workers(timeout=1.5)
    if len(alive) == 2:
        saw_two = True
    st = mgr.watch()
    # only the DOWN transition counts: both workers must have been seen
    # and the restart must coincide with the shrunken membership
    if saw_two and st == ElasticStatus.RESTART and len(alive) == 1:
        open(workdir + "/restart.0", "w").write("1")
        break
    time.sleep(0.3)
mgr.exit()
assert os.path.exists(workdir + "/restart.0"), (saw_two, st)
print("elastic scale-down observed")
"""


class TestElasticEndToEnd:
    def test_scale_down_triggers_restart(self, tmp_path):
        r = _run_launch(tmp_path, ELASTIC_WORKER,
                        ["--nproc_per_node", "2"], [str(tmp_path)])
        assert r.returncode == 0, (r.stdout, r.stderr)
        assert (tmp_path / "left.1").exists()
        assert (tmp_path / "restart.0").exists()


DP4_TRAIN = """
# world=4 multi-host-shaped companion (VERDICT r2 #7): collectives at
# world=4, a data-parallel train loop over per-rank shards with grad
# all-reduce, a mid-training pod crash (rank 2 dies once), launcher
# restart, checkpoint-resume — final params must equal the uninterrupted
# full-batch oracle (computed by the test process).
import os, sys, json
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed as dist

workdir = sys.argv[1]
kill_at = int(sys.argv[2])
env = dist.init_parallel_env()
rank, world = env.rank, env.world_size
assert world == 4, world

# -- collectives at world=4, hand-computed oracles --
t = paddle.to_tensor(np.array([1.0, 2.0], np.float32) * (rank + 1))
dist.all_reduce(t)                       # sum over ranks: (1+2+3+4)=10
np.testing.assert_allclose(np.asarray(t._data), [10.0, 20.0])
outs = []
dist.all_gather(outs, paddle.to_tensor(np.array([float(rank)], np.float32)))
assert sorted(float(np.asarray(o._data)[0]) for o in outs) == [0., 1., 2., 3.]

# -- DP training with checkpoint-resume across a pod restart --
steps, per_rank = 6, 4
paddle.seed(3)
m = paddle.nn.Linear(4, 1)
opt = paddle.optimizer.SGD(0.2, parameters=m.parameters())

ck = os.path.join(workdir, "ck.pdparams")
start = 0
if os.path.exists(ck):
    state = paddle.load(ck)
    m.set_state_dict(state["model"])
    opt.set_state_dict(state["opt"])
    start = state["step"]

rng = np.random.RandomState(0)
xs = rng.randn(steps, world * per_rank, 4).astype(np.float32)
w_true = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)

for step in range(start, steps):
    sl = slice(rank * per_rank, (rank + 1) * per_rank)
    x = paddle.to_tensor(xs[step, sl])
    y = paddle.to_tensor(xs[step, sl] @ w_true)
    loss = ((m(x) - y) ** 2).mean()
    loss.backward()
    for p in m.parameters():             # DP grad averaging over the world
        dist.all_reduce(p.grad)
        p.grad._data = p.grad._data / world
    opt.step()
    opt.clear_grad()
    if rank == 0:
        paddle.save({"model": m.state_dict(), "opt": opt.state_dict(),
                     "step": step + 1}, ck)
    dist.barrier()
    if rank == 2 and step + 1 == kill_at and not os.path.exists(
            os.path.join(workdir, "died")):
        open(os.path.join(workdir, "died"), "w").write("1")
        os._exit(19)                     # simulated worker crash

if rank == 0:
    w = np.asarray(m.parameters()[0]._data)
    np.save(os.path.join(workdir, "final_w.npy"), w)
open(os.path.join(workdir, f"ok.{rank}"), "w").write("1")
print("rank", rank, "dp4 done")
"""


class TestWorld4LaunchTrainResume:
    def test_nprocs4_collectives_dp_train_crash_resume(self, tmp_path):
        """The multi-host-shaped proof at world=4: launch 4 ranks via the
        CLI, run collectives + a DP train loop, crash one rank mid-run,
        let --max_restart relaunch the pod, resume from the checkpoint,
        and match the single-process full-batch oracle exactly."""
        d = tmp_path / "dp4"
        d.mkdir()
        r = _run_launch(tmp_path, DP4_TRAIN,
                        ["--nproc_per_node", "4", "--max_restart", "1"],
                        [str(d), "3"])
        assert r.returncode == 0, (r.stdout, r.stderr)
        assert (d / "died").exists()                # really crashed
        for i in range(4):
            assert (d / f"ok.{i}").exists()

        # single-process full-batch oracle (same seed/init/schedule)
        import paddle_tpu as paddle
        steps, world, per_rank = 6, 4, 4
        paddle.seed(3)
        m = paddle.nn.Linear(4, 1)
        opt = paddle.optimizer.SGD(0.2, parameters=m.parameters())
        rng = np.random.RandomState(0)
        xs = rng.randn(steps, world * per_rank, 4).astype(np.float32)
        w_true = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
        for step in range(steps):
            x = paddle.to_tensor(xs[step])
            y = paddle.to_tensor(xs[step] @ w_true)
            loss = ((m(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        want = np.asarray(m.parameters()[0]._data)
        got = np.load(d / "final_w.npy")
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


ELASTIC4_WORKER = """
# 4-worker elastic companion: rank 3 departs mid-run; rank 0 must observe
# the scale-down (RESTART with 3 alive) within the timeout.
import os, sys, time
from paddle_tpu.distributed.fleet.elastic.manager import (ElasticManager,
                                                          ElasticStatus)
workdir = sys.argv[1]
rank = os.environ["PADDLE_TRAINER_ID"]
os.environ["PADDLE_ELASTIC_ENABLE"] = "1"
os.environ["PADDLE_ELASTIC_NP"] = "1:4"
os.environ["PADDLE_ELASTIC_SERVER"] = os.environ["PADDLE_MASTER"].rsplit(
    ":", 1)[0] + ":" + str(int(os.environ["PADDLE_MASTER"].rsplit(
        ":", 1)[1]) + 41)

mgr = ElasticManager(heartbeat_interval=0.2)
mgr.register()
if rank == "3":
    # leave only AFTER full membership was observable, else rank 0 may
    # never see 4 alive and the scale-down transition is unprovable
    deadline = time.time() + 25
    while time.time() < deadline:
        if len(mgr.alive_workers(timeout=1.5)) == 4:
            break
        time.sleep(0.2)
    time.sleep(1.0)                    # let rank 0 observe 4-alive too
    mgr.exit(completed=False)
    open(workdir + "/left.3", "w").write("1")
    sys.exit(0)
if rank != "0":
    # keep heartbeating at least as long as rank 0's 30 s watch window —
    # exiting earlier would drop alive below 3 and make the scale-down
    # condition unsatisfiable on a slow machine
    deadline = time.time() + 35
    while time.time() < deadline and not os.path.exists(
            workdir + "/restart.0"):
        time.sleep(0.3)
    mgr.exit()
    sys.exit(0)

deadline = time.time() + 30
saw_four = False
while time.time() < deadline:
    alive = mgr.alive_workers(timeout=1.5)
    if len(alive) == 4:
        saw_four = True
    st = mgr.watch()
    if saw_four and st == ElasticStatus.RESTART and len(alive) == 3:
        open(workdir + "/restart.0", "w").write("1")
        break
    time.sleep(0.3)
mgr.exit()
assert os.path.exists(workdir + "/restart.0")
print("elastic 4-worker scale-down observed")
"""


class TestElastic4:
    def test_four_worker_scale_down(self, tmp_path):
        r = _run_launch(tmp_path, ELASTIC4_WORKER,
                        ["--nproc_per_node", "4"], [str(tmp_path)])
        assert r.returncode == 0, (r.stdout, r.stderr)
        assert (tmp_path / "left.3").exists()
        assert (tmp_path / "restart.0").exists()
