"""Distributed flight recorder (ISSUE 9): per-rank collective event
rings, hang dumps, cross-rank desync diagnosis, cluster aggregation.

Contracts under test:
  * ring bounding + strict seq monotonicity; an in-flight (hung) event
    survives ring eviction;
  * disabled mode (`ring=0`): zero collection AND zero clock reads
    (counting-clock bound, same discipline as telemetry-off);
  * the choke point: every public collective records exactly ONE event
    (nested object-collectives suppressed), payload introspection,
    tracer-backed calls skipped, per-op wait histograms land in the
    runtime registry;
  * dump format: self-describing header (generation, watchdog gauges),
    all-thread stacks with the main thread tagged, faulthandler text,
    runtime registry snapshot; dump-once semantics;
  * cross-rank diagnosis: never-entered stragglers, the async
    in-flight-behind pattern, all-ranks-wedged, missing/unparsable
    dumps NAMED; deterministic text (byte-for-byte reproducible);
  * gang supervisor emission: `gang_diagnosis` logjson event with the
    structured verdict;
  * TCPStore cluster snapshot aggregation (heartbeat-style keys);
  * pid-per-rank Perfetto export over profiler.ChromeTrace;
  * structural checks (tools/check_collective_surface.py) pass tier-1;
  * END TO END on the gloo path: PADDLE_FI_HANG wedges one rank at a
    collective; the supervisor report names the stuck op + seq + the
    straggler rank; dumps contain in-collective stacks; and
    tools/flight_report.py reproduces the supervisor's diagnosis
    byte-for-byte. Every wait is bounded.
"""
import importlib.util
import io
import json
import os
import subprocess
import sys
import time
import types

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.core.native import TCPStore, TCPStoreServer, load_native
from paddle_tpu.distributed.resilience import flight_recorder as fr
from paddle_tpu.testing import FI_ENV_VARS, FR_ENV_VARS, fault

needs_native = pytest.mark.skipif(load_native() is None,
                                  reason="native runtime unavailable")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def rec():
    """A module-global recorder for choke-point tests; always reset so
    the cached global never leaks into other suites."""
    r = fr.configure(ring=64, rank=0, world=1)
    yield r
    fr.reset()


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO_ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# =====================================================================
# Recorder core
# =====================================================================
class TestRecorderCore:
    def test_seq_monotonic_and_ring_bounded(self):
        r = fr.FlightRecorder(ring=8, rank=0, world=1)
        for i in range(20):
            r.end(r.start("all_reduce", group="default", shape=(4,),
                          dtype="float32", nbytes=16))
        tail = r.tail()
        assert len(tail) == 8                       # bounded
        seqs = [e["seq"] for e in tail]
        assert seqs == sorted(seqs) == list(range(13, 21))
        assert all(e["status"] == "done" for e in tail)
        assert r.snapshot()["events_recorded"] == 20

    def test_gseq_is_per_group(self):
        r = fr.FlightRecorder(ring=16, rank=0, world=1)
        r.end(r.start("all_reduce", group="mp"))
        r.end(r.start("all_reduce", group="pp"))
        r.end(r.start("broadcast", group="mp"))
        by = {(e["group"], e["op"]): e["gseq"] for e in r.tail()}
        assert by[("mp", "all_reduce")] == 1
        assert by[("pp", "all_reduce")] == 1        # independent counter
        assert by[("mp", "broadcast")] == 2

    def test_in_flight_event_survives_ring_eviction(self):
        """THE hang case: the wedged collective must stay visible in
        tail() even after chatty later events (rpc from other threads)
        rotated it out of the ring."""
        r = fr.FlightRecorder(ring=4, rank=0, world=1)
        hung = r.start("all_reduce", group="mp", shape=(8,),
                       dtype="float32")
        for _ in range(10):
            r.end(r.start("rpc", kind="rpc", group="rpc:w1"))
        tail = r.tail()
        assert len(tail) == 5                       # ring + the hung one
        assert tail[0] is not hung                  # copies, not refs
        assert tail[0]["seq"] == hung["seq"]
        assert tail[0]["status"] == "in_flight"
        r.end(hung)
        assert all(e["status"] == "done" for e in r.tail())

    def test_disabled_zero_collection_zero_clock_reads(self):
        calls = [0]

        def counting_clock():
            calls[0] += 1
            return time.monotonic()

        r = fr.FlightRecorder(ring=0, rank=0, world=1,
                              clock=counting_clock)
        assert not r.enabled
        for _ in range(50):
            r.end(r.start("all_reduce", group="default"))
        assert calls[0] == 0                        # no clock reads at all
        assert r.tail() == []
        assert r.snapshot()["events_recorded"] == 0
        with pytest.raises(ValueError, match=">= 0"):
            fr.FlightRecorder(ring=-1)

    def test_error_status_and_wait_histogram(self):
        r = fr.FlightRecorder(ring=8, rank=0, world=1)
        ev = r.start("reduce_scatter", group="default")
        r.end(ev, error=RuntimeError("boom"))
        (e,) = r.tail()
        assert e["status"] == "error" and "boom" in e["error"]
        from paddle_tpu.inference.telemetry import (
            runtime_prometheus, runtime_registry_snapshot)
        name = fr.runtime_hist_name("reduce_scatter")
        snap = runtime_registry_snapshot()
        assert name in snap["histograms"]
        assert snap["histograms"][name]["count"] >= 1
        assert f"{name}_bucket" in "\n".join(runtime_prometheus())

    def test_env_default_on_iff_multiprocess(self, monkeypatch):
        monkeypatch.delenv("PADDLE_FLIGHT_RECORDER", raising=False)
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
        fr.reset()
        assert fr.recorder() is None                # single-process: off
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "4")
        fr.reset()
        r = fr.recorder()
        assert r is not None and r.ring == fr.DEFAULT_RING
        monkeypatch.setenv("PADDLE_FLIGHT_RECORDER", "0")
        fr.reset()
        assert fr.recorder() is None                # explicit off wins
        monkeypatch.setenv("PADDLE_FLIGHT_RECORDER", "32")
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
        fr.reset()
        assert fr.recorder().ring == 32             # explicit on wins
        fr.reset()

    def test_malformed_env_degrades_to_default_policy(self, monkeypatch):
        """recorder() is called lazily from inside the first collective
        — a typo'd env var must warn and fall back, not kill the job
        with a traceback pointing into an all_reduce."""
        monkeypatch.setenv("PADDLE_FLIGHT_RECORDER", "true")
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "4")
        fr.reset()
        r = fr.recorder()
        assert r is not None and r.ring == fr.DEFAULT_RING
        monkeypatch.setenv("PADDLE_FLIGHT_RECORDER", "-5")
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
        fr.reset()
        assert fr.recorder() is None                # default: off at w=1
        fr.reset()

    def test_configure_world_hint_enables_without_env(self, monkeypatch):
        """A jax-native launch never sets PADDLE_TRAINERS_NUM — the
        authoritative world passed by init_parallel_env must drive the
        default-on decision."""
        monkeypatch.delenv("PADDLE_FLIGHT_RECORDER", raising=False)
        monkeypatch.delenv("PADDLE_TRAINERS_NUM", raising=False)
        monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
        rec = fr.configure(rank=2, world=4)
        assert rec is not None and rec.enabled
        assert rec.rank == 2 and rec.world == 4
        fr.reset()
        assert fr.configure(rank=0, world=1) is None
        monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
        fr.reset()
        assert fr.recorder() is not None            # env contract too
        fr.reset()


# =====================================================================
# The choke point (instrumented public collectives)
# =====================================================================
class TestChokePoint:
    def test_public_collectives_record_one_event_each(self, rec):
        t = paddle.to_tensor(np.ones((4,), np.float32))
        dist.all_reduce(t)
        dist.barrier()
        objs = []
        dist.all_gather_object(objs, {"x": 1})      # nests 2 all_gathers
        ops = [e["op"] for e in rec.tail()]
        assert ops == ["all_reduce", "barrier", "all_gather_object"]
        ev = rec.tail()[0]
        assert ev["shape"] == [4] and ev["dtype"] == "float32"
        assert ev["nbytes"] == 16
        assert ev["group"] == "default"
        assert [e["gseq"] for e in rec.tail()] == [1, 2, 3]

    def test_named_group_events_align_on_group_name(self, rec):
        g = dist.new_group([0])
        t = paddle.to_tensor(np.zeros((2,), np.float32))
        dist.all_reduce(t, group=g)
        (ev,) = [e for e in rec.tail() if e["op"] == "all_reduce"]
        assert ev["group"] == g.name

    def test_disabled_recorder_skips_everything(self, monkeypatch):
        monkeypatch.delenv("PADDLE_FLIGHT_RECORDER", raising=False)
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
        fr.reset()
        assert fr.recorder() is None
        t = paddle.to_tensor(np.ones((4,), np.float32))
        dist.all_reduce(t)                          # must not blow up
        np.testing.assert_allclose(np.asarray(t._data), 1.0)
        fr.reset()

    def test_tracer_backed_payload_is_skipped(self, rec):
        tracer_like = types.SimpleNamespace(_trace=None, shape=(2,),
                                            dtype=np.dtype(np.float32))
        assert fr._payload_of((tracer_like,), {}) is fr._SKIP
        called = []

        @fr.instrumented("fake_op")
        def fake(x):
            called.append(x)
            return x

        fake(types.SimpleNamespace(_data=tracer_like))
        # keyword form must hit the same guard (traced calls record
        # per-compile, not per-execution — they must be skipped)
        assert fr._payload_of(
            (), {"tensor": types.SimpleNamespace(_data=tracer_like)}) \
            is fr._SKIP
        fake(x=types.SimpleNamespace(_data=tracer_like))
        assert len(called) == 2                     # ran untouched
        assert all(e["op"] != "fake_op" for e in rec.tail())

    def test_record_span_is_reentrancy_safe(self, rec):
        with fr.record_span("outer", group="g"):
            with fr.record_span("inner", group="g"):
                pass
        ops = [e["op"] for e in rec.tail()]
        assert ops == ["outer"]                     # outermost only

    def test_rpc_call_records_span(self, rec):
        if load_native() is None:
            pytest.skip("native runtime unavailable")
        from paddle_tpu.distributed import rpc
        rpc.init_rpc("w0", rank=0, world_size=1,
                     master_endpoint="127.0.0.1:0")
        try:
            assert rpc.rpc_sync("w0", _echo, args=(7,)) == 7
        finally:
            rpc.shutdown()
        evs = [e for e in rec.tail() if e["kind"] == "rpc"]
        assert evs and evs[-1]["op"] == "rpc"
        assert evs[-1]["group"] == "rpc:w0"
        assert evs[-1]["note"] == "_echo"
        assert evs[-1]["status"] == "done"

    def test_monitored_barrier_records_span(self, rec):
        if load_native() is None:
            pytest.skip("native runtime unavailable")
        from paddle_tpu.distributed.resilience import Watchdog
        srv = TCPStoreServer(0)
        try:
            wd = Watchdog(lambda t: TCPStore("127.0.0.1", srv.port,
                                             timeout_s=t),
                          0, 2, timeout_s=1.0, interval_s=0.1,
                          action="flag")
            from paddle_tpu.distributed.resilience import PeerFailureError
            with pytest.raises(PeerFailureError):
                wd.monitored_barrier(timeout_s=0.5, tag="fr-t")
        finally:
            srv.stop()
        evs = [e for e in rec.tail() if e["op"] == "monitored_barrier"]
        assert evs and evs[0]["status"] == "error"
        assert evs[0]["group"] == "world"

    def test_structural_check_passes(self, capsys):
        """tools/check_collective_surface.py: no public collective
        bypasses the choke point — tier-1, like the metrics surface."""
        mod = _load_tool("check_collective_surface")
        rc = mod.main()
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "ok" in out


def _echo(x):
    return x


# =====================================================================
# Fault-injection point targeting (PADDLE_FI_AT_POINT)
# =====================================================================
class TestFaultAtPoint:
    def test_registry_covers_new_knob(self):
        assert "PADDLE_FI_AT_POINT" in FI_ENV_VARS
        assert FR_ENV_VARS == ("PADDLE_FLIGHT_DUMP_DIR",
                               "PADDLE_FLIGHT_RECORDER")

    def test_at_point_gates_named_point(self, monkeypatch):
        fault.reset()
        monkeypatch.setenv("PADDLE_FI_HANG", "0")
        monkeypatch.setenv("PADDLE_FI_AT_POINT", "collective")
        monkeypatch.delenv("PADDLE_FI_AT_STEP", raising=False)
        assert not fault._should_fire("init")       # init no longer fires
        assert not fault._should_fire("step")
        assert fault._should_fire("collective")     # first occurrence
        fault.reset()

    def test_at_point_with_index(self, monkeypatch):
        fault.reset()
        monkeypatch.setenv("PADDLE_FI_HANG", "0")
        monkeypatch.setenv("PADDLE_FI_AT_POINT", "collective")
        monkeypatch.setenv("PADDLE_FI_AT_STEP", "2")
        fires = [fault._should_fire("collective") for _ in range(4)]
        assert fires == [False, False, True, False]  # exactly the 3rd
        fault.reset()

    def test_legacy_semantics_unchanged(self, monkeypatch):
        fault.reset()
        monkeypatch.setenv("PADDLE_FI_KILL_RANK", "0")
        monkeypatch.delenv("PADDLE_FI_AT_POINT", raising=False)
        monkeypatch.setenv("PADDLE_FI_AT_STEP", "1")
        assert not fault._should_fire("init")       # gated to a step
        assert not fault._should_fire("collective")
        assert not fault._should_fire("step")       # step 0
        assert fault._should_fire("step")           # step 1
        monkeypatch.delenv("PADDLE_FI_AT_STEP", raising=False)
        assert fault._should_fire("init")           # legacy default
        fault.reset()


# =====================================================================
# Dumps
# =====================================================================
class TestDump:
    def test_dump_is_self_describing(self, tmp_path, monkeypatch, rec):
        monkeypatch.setenv("PADDLE_RESTART_COUNT", "3")
        t = paddle.to_tensor(np.ones((4,), np.float32))
        dist.all_reduce(t)
        hung = rec.start("broadcast", group="mp", shape=(2, 2),
                         dtype="float32", nbytes=16)
        path = rec.dump(path=str(tmp_path / "flightdump.0.3.json"),
                        reason="unit")
        with open(path) as f:
            d = json.load(f)
        assert d["schema"] == fr.DUMP_SCHEMA
        assert d["rank"] == 0 and d["generation"] == 3
        assert d["reason"] == "unit" and d["pid"] == os.getpid()
        assert d["t_mono"] > 0 and d["t_wall"] > 0
        ops = {e["op"]: e["status"] for e in d["events"]}
        assert ops["all_reduce"] == "done"
        assert ops["broadcast"] == "in_flight"
        # all-thread stacks, main thread tagged, this test in the frames
        main = [k for k in d["stacks"] if k.endswith("[main]")]
        assert len(main) == 1
        frames = d["stacks"][main[0]]
        assert any("test_flight_recorder" in fs["file"] for fs in frames)
        assert "Thread" in d["faulthandler"] or \
            "thread" in d["faulthandler"]
        assert "histograms" in d["runtime_metrics"]
        rec.end(hung)

    @needs_native
    def test_watchdog_gauges_in_dump_header(self, rec):
        """Satellite: heartbeat ages + restart generation make a dump
        self-describing without the supervisor's context."""
        from paddle_tpu.distributed.resilience import watchdog as wdmod
        srv = TCPStoreServer(0)
        wd = wdmod.Watchdog(lambda t: TCPStore("127.0.0.1", srv.port,
                                               timeout_s=t),
                            0, 2, timeout_s=30.0, interval_s=0.1,
                            action="flag").start()
        wdmod._watchdog[0] = wd
        try:
            time.sleep(0.3)
            d = rec.dump_payload(reason="unit")
            assert d["watchdog"] is not None
            g = d["watchdog"]["gauges"]
            assert g["rank"] == 0 and g["world"] == 2
            assert 1 in g["heartbeat_age_s"] or \
                "1" in g["heartbeat_age_s"]
            assert d["watchdog"]["failure"] is None
        finally:
            wdmod._watchdog[0] = None
            wd.stop()
            srv.stop()

    def test_dump_once_keeps_first_failure_view(self, tmp_path, rec):
        p1 = rec.dump(path=str(tmp_path / "flightdump.0.0.json"),
                      reason="peer_failure")
        rec.end(rec.start("all_reduce"))
        p2 = rec.dump(path=str(tmp_path / "other.json"),
                      reason="sigterm")            # cascading trigger
        assert p1 == p2                            # first view wins
        with open(p1) as f:
            assert json.load(f)["reason"] == "peer_failure"
        assert not (tmp_path / "other.json").exists()
        p3 = rec.dump(path=str(tmp_path / "forced.json"),
                      reason="manual", force=True)
        assert p3.endswith("forced.json")

    def test_module_dump_on_failure_best_effort(self, tmp_path,
                                                monkeypatch, rec):
        monkeypatch.setenv("PADDLE_FLIGHT_DUMP_DIR", str(tmp_path))
        monkeypatch.setenv("PADDLE_RESTART_COUNT", "0")
        rec.end(rec.start("all_reduce"))
        path = fr.dump_on_failure("peer_failure")
        assert path == str(tmp_path / "flightdump.0.0.json")
        assert os.path.exists(path)


# =====================================================================
# Cross-rank diagnosis (synthesized dumps — documents the schema)
# =====================================================================
def _ev(seq, op, group="default", status="done", t0=10.0, t1=10.5,
        kind="collective"):
    return {"seq": seq, "gseq": seq, "op": op, "group": group,
            "kind": kind, "status": status, "t_start": t0,
            "t_end": None if status == "in_flight" else t1}


def _dump(rank, events, world=2, t_mono=50.0, stacks=None, wd=None,
          generation=0):
    return {"schema": fr.DUMP_SCHEMA, "rank": rank, "world": world,
            "generation": generation, "pid": 1000 + rank,
            "reason": "unit", "t_wall": 1e9 + t_mono, "t_mono": t_mono,
            "ring": 64, "events_recorded": len(events),
            "events": events, "watchdog": wd,
            "stacks": stacks or {}, "faulthandler": "",
            "runtime_metrics": None}


class TestDiagnosis:
    def test_never_entered_straggler_named(self):
        dumps = {
            0: _dump(0, [_ev(3, "all_reduce"),
                         _ev(4, "all_reduce", status="in_flight",
                             t0=12.0)]),
            1: _dump(1, [_ev(3, "all_reduce")],
                     stacks={"MainThread (tid 7) [main]": [
                         {"file": "/x/train.py", "line": 9,
                          "func": "<module>",
                          "code": "dist.all_reduce(t)"}]}),
        }
        text, diag = fr.diagnose(dumps, world=2, generation=0)
        assert diag["desync"] and diag["stragglers"] == [1]
        assert diag["stuck"] == {"group": "default", "op": "all_reduce",
                                 "seq": 4}
        assert "rank 0: in_flight in all_reduce seq=4 for 38.00s" in text
        assert "rank 1: completed seq=3, never entered all_reduce " \
            "seq=4" in text
        assert "stragglers: rank 1" in text
        assert "straggler rank 1 main-thread stack" in text
        assert "train.py:9 <module>: dist.all_reduce(t)" in text

    def test_in_flight_behind_pattern(self):
        """The NCCL-async exemplar: rank 2 still inside seq 417 while
        ranks 0,1,3 moved on to seq 418."""
        behind = [_ev(417, "all_reduce", group="mp",
                      status="in_flight", t0=12.0)]
        ahead = [_ev(417, "all_reduce", group="mp"),
                 _ev(418, "all_reduce", group="mp",
                     status="in_flight", t0=49.0)]
        dumps = {0: _dump(0, list(ahead), world=4),
                 1: _dump(1, list(ahead), world=4),
                 2: _dump(2, behind, world=4),
                 3: _dump(3, list(ahead), world=4)}
        text, diag = fr.diagnose(dumps, world=4, generation=2)
        assert diag["stragglers"] == [2]
        assert diag["stuck"] == {"group": "mp", "op": "all_reduce",
                                 "seq": 417}
        assert "rank 2: in_flight in all_reduce seq=417 for 38.00s" \
            in text
        assert "(waiting on stragglers)" in text    # ranks 0,1,3

    def test_wedged_inside_collective_peers_left(self):
        """Async completion: every peer finished seq 4 and LEFT the
        collective; the one rank still inside it IS the straggler (not
        'none identified')."""
        dumps = {
            0: _dump(0, [_ev(4, "all_reduce")]),
            1: _dump(1, [_ev(4, "all_reduce", status="in_flight",
                             t0=12.0)]),
        }
        text, diag = fr.diagnose(dumps, world=2)
        assert diag["desync"] and diag["stragglers"] == [1]
        assert "rank 1: in_flight in all_reduce seq=4 for 38.00s" \
            in text
        assert "(waiting on stragglers)" not in text  # it IS the straggler
        assert "stragglers: rank 1" in text
        assert "none identified" not in text

    def test_never_entered_names_the_stuck_seq_when_far_behind(self):
        """A straggler 3 collectives behind must be pointed at the seq
        the peers are actually stuck in, not last+1."""
        dumps = {
            0: _dump(0, [_ev(5, "all_reduce", status="in_flight",
                             t0=12.0)]),
            1: _dump(1, [_ev(2, "all_reduce")]),
        }
        text, diag = fr.diagnose(dumps, world=2)
        assert diag["stragglers"] == [1]
        assert "rank 1: completed seq=2, never entered all_reduce " \
            "seq=5" in text

    def test_all_ranks_wedged_has_no_scapegoat(self):
        evs = [_ev(4, "all_reduce", status="in_flight", t0=12.0)]
        dumps = {r: _dump(r, list(evs)) for r in range(2)}
        text, diag = fr.diagnose(dumps, world=2)
        assert diag["desync"] and diag["stragglers"] == []
        assert "collective itself is wedged" in text

    def test_missing_and_unparsable_dumps_named(self, tmp_path):
        """Satellite: a rank that crashed before dumping must be NAMED,
        not silently omitted."""
        with open(tmp_path / "flightdump.0.0.json", "w") as f:
            json.dump(_dump(0, [_ev(1, "all_reduce",
                                    status="in_flight", t0=12.0)],
                            world=3), f)
        with open(tmp_path / "flightdump.1.0.json", "w") as f:
            f.write("{torn json")
        text, diag = fr.diagnose_dir(str(tmp_path), world=3)
        assert diag["ranks_with_dump"] == [0]
        assert diag["ranks_missing_dump"] == [1, 2]
        assert "unparsable" in diag["missing_dump_errors"]["1"]
        assert "rank 2 (no dump file" in text
        assert "rank 1 (unparsable" in text
        # missing-dump ranks are straggler suspects: they never entered
        assert 1 in diag["stragglers"] and 2 in diag["stragglers"]

    def test_expected_ranks_bounds_missing_dump_suspects(self):
        """Multi-node: a node-0 supervisor only sees ranks 0-1's dumps;
        ranks 2-3 dump on their own host and must NOT be reported as
        crashed-before-dumping stragglers."""
        dumps = {0: _dump(0, [_ev(2, "all_reduce", status="in_flight",
                                  t0=12.0)], world=4),
                 1: _dump(1, [_ev(1, "all_reduce")], world=4)}
        text, diag = fr.diagnose(dumps, world=4, expected_ranks=[0, 1])
        assert diag["ranks_missing_dump"] == []
        assert diag["stragglers"] == [1]
        assert "missing dumps" not in text
        # default (single-node): every rank in world is expected
        _, diag_all = fr.diagnose(dumps, world=4)
        assert diag_all["ranks_missing_dump"] == [2, 3]

    def test_aligned_gang_reports_no_desync(self):
        evs = [_ev(5, "all_reduce"), _ev(6, "barrier")]
        dumps = {r: _dump(r, [dict(e) for e in evs]) for r in range(2)}
        text, diag = fr.diagnose(dumps, world=2)
        assert not diag["desync"] and diag["stragglers"] == []
        assert "no cross-rank desync detected" in text
        assert "group 'default': aligned at seq 6" in text

    def test_watchdog_flags_and_rpc_in_flight_surface(self):
        wd = {"gauges": {"rank": 0}, "failure": "no heartbeat",
              "failure_ranks": [1]}
        dumps = {0: _dump(0, [_ev(2, "all_reduce", status="in_flight",
                                  t0=12.0),
                              _ev(3, "rpc", group="rpc:w1",
                                  kind="rpc", status="in_flight",
                                  t0=20.0)], wd=wd),
                 1: _dump(1, [_ev(1, "all_reduce")])}
        text, diag = fr.diagnose(dumps, world=2)
        assert "watchdog flags: rank 0 -> [1]" in text
        assert "rank 0: rpc in_flight in rpc group=rpc:w1 for 30.00s" \
            in text

    def test_text_is_deterministic(self, tmp_path):
        for r in range(2):
            with open(tmp_path / f"flightdump.{r}.0.json", "w") as f:
                json.dump(_dump(r, [_ev(1, "all_reduce",
                                        status="in_flight", t0=1.0)]),
                          f)
        t1, _ = fr.diagnose_dir(str(tmp_path))
        t2, _ = fr.diagnose_dir(str(tmp_path))
        assert t1 == t2

    def test_generation_selection(self, tmp_path):
        for gen, seq in ((0, 1), (1, 9)):
            with open(tmp_path / f"flightdump.0.{gen}.json", "w") as f:
                json.dump(_dump(0, [_ev(seq, "all_reduce")], world=1,
                                generation=gen), f)
        gen, dumps, _ = fr.load_dumps(str(tmp_path))
        assert gen == 1                             # newest by default
        assert dumps[0]["events"][0]["gseq"] == 9
        gen, dumps, _ = fr.load_dumps(str(tmp_path), generation=0)
        assert dumps[0]["events"][0]["gseq"] == 1


# =====================================================================
# Supervisor emission (gang_diagnosis event) + flight_report CLI
# =====================================================================
class TestGangDiagnosisEvent:
    def _args(self, tmp_path, nprocs=3):
        return types.SimpleNamespace(log_dir=str(tmp_path),
                                     node_rank=0, nproc_per_node=nprocs)

    def test_json_event_carries_structured_verdict(self, tmp_path,
                                                   monkeypatch):
        import paddle_tpu.distributed.launch.__main__ as launch_main
        for r, evs in ((0, [_ev(2, "all_reduce", status="in_flight",
                                t0=12.0)]),
                       (1, [_ev(1, "all_reduce")])):
            with open(tmp_path / f"flightdump.{r}.0.json", "w") as f:
                json.dump(_dump(r, evs, world=3), f)
        monkeypatch.setenv("PADDLE_LOG_JSON", "1")
        monkeypatch.delenv("PADDLE_FLIGHT_DUMP_DIR", raising=False)
        buf = io.StringIO()
        diag = launch_main._emit_flight_diagnosis(
            self._args(tmp_path), 0, 3, stream=buf)
        rec_ = json.loads(buf.getvalue())
        assert rec_["component"] == "launch"
        assert rec_["event"] == "gang_diagnosis"
        assert rec_["desync"] is True
        assert rec_["stragglers"] == diag["stragglers"] == [1, 2]
        assert rec_["ranks_missing_dump"] == [2]
        assert rec_["stuck"]["op"] == "all_reduce"
        assert "never entered" in rec_["message"]

    def test_no_dumps_is_silent(self, tmp_path, monkeypatch):
        import paddle_tpu.distributed.launch.__main__ as launch_main
        monkeypatch.delenv("PADDLE_FLIGHT_DUMP_DIR", raising=False)
        buf = io.StringIO()
        assert launch_main._emit_flight_diagnosis(
            self._args(tmp_path, nprocs=2), 0, 2, stream=buf) is None
        assert buf.getvalue() == ""

    def test_flight_report_cli_matches_shared_impl(self, tmp_path,
                                                   capsys):
        for r in range(2):
            with open(tmp_path / f"flightdump.{r}.0.json", "w") as f:
                json.dump(_dump(r, [_ev(1, "all_reduce",
                                        status="in_flight", t0=2.0)]),
                          f)
        tool = _load_tool("flight_report")
        rc = tool.main([str(tmp_path)])
        out = capsys.readouterr().out
        text, _ = fr.diagnose_dir(str(tmp_path))
        assert rc == 0 and out == text + "\n"       # byte-for-byte
        rc = tool.main([str(tmp_path), "--json"])
        doc = json.loads(capsys.readouterr().out)
        assert doc["desync"] is True
        rc = tool.main([str(tmp_path / "empty")])
        assert rc == 2


# =====================================================================
# Cluster snapshot over TCPStore (heartbeat-style aggregation)
# =====================================================================
@needs_native
class TestClusterSnapshot:
    def test_publish_and_aggregate(self):
        srv = TCPStoreServer(0)
        try:
            store = TCPStore("127.0.0.1", srv.port, timeout_s=5.0)
            recs = {r: fr.FlightRecorder(ring=16, rank=r, world=3)
                    for r in range(2)}
            recs[0].end(recs[0].start("all_reduce", group="mp"))
            recs[1].start("all_reduce", group="mp")   # left hanging
            for r in recs.values():
                assert fr.publish_snapshot(store, rec=r)
            snap = fr.cluster_snapshot(
                lambda t: TCPStore("127.0.0.1", srv.port, timeout_s=t),
                world=3)
            assert snap[0]["groups"]["mp"]["seq"] == 1
            assert snap[1]["groups"]["mp"]["in_flight_op"] == \
                "all_reduce"
            assert snap[1]["in_flight"] == 1
            assert snap[2] is None                   # never published
            store.close()
        finally:
            srv.stop()

    def test_disabled_recorder_publishes_nothing(self):
        srv = TCPStoreServer(0)
        try:
            store = TCPStore("127.0.0.1", srv.port, timeout_s=5.0)
            off = fr.FlightRecorder(ring=0)
            assert fr.publish_snapshot(store, rec=off) is False
            # module-level maybe_publish with no recorder configured
            fr.reset()
            assert fr.maybe_publish(store) is False
            assert store.get("fr/0") is None
            store.close()
        finally:
            srv.stop()


# =====================================================================
# Perfetto export (pid per rank)
# =====================================================================
class TestPerfettoExport:
    def test_pid_per_rank_trace(self, tmp_path):
        from paddle_tpu.inference.telemetry import validate_chrome_trace
        dumps = {
            0: _dump(0, [_ev(1, "all_reduce"),
                         _ev(2, "all_reduce", status="in_flight",
                             t0=12.0)], t_mono=50.0),
            1: _dump(1, [_ev(1, "all_reduce")], t_mono=51.0),
        }
        path = str(tmp_path / "flight_trace.json")
        assert fr.export_chrome_tracing(dumps, path) == path
        doc = validate_chrome_trace(path)
        evs = doc["traceEvents"]
        names = {e["args"]["name"] for e in evs
                 if e["ph"] == "M" and e["name"] == "process_name"}
        assert names == {"rank 0 flight recorder",
                         "rank 1 flight recorder"}
        flights = [e for e in evs if e["ph"] == "X"
                   and e.get("args", {}).get("status") == "in_flight"]
        assert flights and flights[0]["pid"] == 0
        # the in-flight op is drawn to rank 0's dump time: 38s
        assert flights[0]["dur"] == pytest.approx(38e6, rel=1e-3)
        assert any(e["ph"] == "i" and "dump" in e["name"] for e in evs)

    def test_export_from_dir_and_empty_raises(self, tmp_path):
        with pytest.raises(ValueError, match="no flight dumps"):
            fr.export_chrome_tracing(str(tmp_path), str(tmp_path / "t"))
        with open(tmp_path / "flightdump.0.0.json", "w") as f:
            json.dump(_dump(0, [_ev(1, "barrier")], world=1), f)
        fr.export_chrome_tracing(str(tmp_path),
                                 str(tmp_path / "t.json"))
        assert os.path.exists(tmp_path / "t.json")


# =====================================================================
# End to end: fault-injected desync on the gloo path
# =====================================================================
DESYNC_E2E = """
import os, sys, time
os.environ["PADDLE_WATCHDOG_TIMEOUT_S"] = "8"
os.environ["PADDLE_HEARTBEAT_INTERVAL_S"] = "0.2"
os.environ["PADDLE_WATCHDOG_KILL_GRACE_S"] = "1"
if os.environ["PADDLE_TRAINER_ID"] == "0":
    # rank 0 (the coordinator): heartbeat dark from the start (the
    # watchdog's lever) AND wedge at the 4th collective entry (the
    # flight recorder's lever — the hang fires INSIDE the choke point,
    # before the entry records, so rank 0's dump shows seq=3 done and
    # never-entered seq=4). The COORDINATOR is the straggler on
    # purpose: a non-coordinator rank that outlives the coordinator is
    # aborted by jax's coordination client before the supervisor can
    # SIGTERM it (that path — no dump at all — is covered by the
    # missing-dump naming in the diagnosis unit tests).
    os.environ["PADDLE_FI_DROP_HEARTBEAT"] = "0"
    os.environ["PADDLE_FI_HANG"] = "0"
    os.environ["PADDLE_FI_AT_POINT"] = "collective"
    os.environ["PADDLE_FI_AT_STEP"] = "3"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed as dist

env = dist.init_parallel_env()
assert env.world_size == 2
t = paddle.to_tensor(np.ones((4,), np.float32))
for i in range(50):
    dist.all_reduce(t)          # rank 0 wedges at i == 3; rank 1 then
    time.sleep(0.05)            # blocks INSIDE the gloo collective
print("completed all collectives", flush=True)   # must never print
"""


@needs_native
class TestDesyncEndToEnd:
    def _run_launch(self, tmp_path, extra_args, timeout=240):
        script = tmp_path / "companion.py"
        script.write_text(DESYNC_E2E)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + \
            env.get("PYTHONPATH", "")
        return subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--log_dir", str(tmp_path / "log")] + extra_args +
            [str(script)],
            env=env, cwd=REPO_ROOT, capture_output=True, text=True,
            timeout=timeout)

    def test_hang_produces_dumps_and_named_straggler(self, tmp_path):
        """Acceptance: a fault-injected hang in one rank produces
        per-rank flightdump files and a supervisor report naming the
        desynced collective (op + seq + group), the stuck rank, and its
        in-collective stack — all bounded, no sleeps-as-sync."""
        from paddle_tpu.distributed.resilience import WATCHDOG_EXIT_CODE
        r = self._run_launch(tmp_path, ["--nproc_per_node", "2"])
        # rank 1 (wedged INSIDE the collective) escalates via the
        # watchdog once rank 0's heartbeats never arrive
        assert r.returncode == WATCHDOG_EXIT_CODE, (r.stdout, r.stderr)
        log = tmp_path / "log"
        # --- per-rank dumps exist
        d0p, d1p = (log / "flightdump.0.0.json",
                    log / "flightdump.1.0.json")
        assert d0p.exists() and d1p.exists(), list(log.iterdir())
        d0 = json.loads(d0p.read_text())
        d1 = json.loads(d1p.read_text())
        assert d1["reason"] == "peer_failure"       # watchdog trigger
        assert d0["reason"] == "sigterm"            # supervisor reap
        # --- rank 1: the collective is in flight at seq 4, and its
        # main thread stack is inside the collective call
        evs1 = {(e["op"], e["gseq"]): e["status"] for e in d1["events"]
                if e["kind"] == "collective"}
        assert evs1[("all_reduce", 4)] == "in_flight"
        assert evs1[("all_reduce", 3)] == "done"
        main1 = next(v for k, v in d1["stacks"].items()
                     if k.endswith("[main]"))
        assert any("all_reduce" in (fs.get("code") or "")
                   or "all_reduce" in fs.get("func", "")
                   for fs in main1), main1
        # --- rank 0 (the straggler): completed seq 3, never entered 4,
        # and its stack shows the injected hang inside the choke point
        evs0 = [e for e in d0["events"] if e["kind"] == "collective"]
        assert max(e["gseq"] for e in evs0) == 3
        assert all(e["status"] == "done" for e in evs0)
        main0 = next(v for k, v in d0["stacks"].items()
                     if k.endswith("[main]"))
        assert any(fs.get("func") == "inject" for fs in main0), main0
        # --- dump headers are self-describing
        assert d1["generation"] == 0 and d1["world"] == 2
        assert d1["watchdog"]["failure_ranks"] == [0]
        assert d1["watchdog"]["gauges"]["heartbeat_age_s"]
        # --- the supervisor report names op + seq + group + straggler
        assert "flight recorder: cross-rank diagnosis (generation 0, " \
            "world 2)" in r.stderr
        assert "group 'default': desync in all_reduce at seq 4" \
            in r.stderr
        assert "rank 1: in_flight in all_reduce seq=4 for" in r.stderr
        assert "rank 0: completed seq=3, never entered all_reduce " \
            "seq=4" in r.stderr
        assert "stragglers: rank 0" in r.stderr
        assert "straggler rank 0 main-thread stack" in r.stderr
        # --- tools/flight_report.py reproduces it byte-for-byte
        tool = _load_tool("flight_report")
        import contextlib
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            assert tool.main([str(log)]) == 0
        assert buf.getvalue() in r.stderr           # identical block
