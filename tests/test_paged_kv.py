"""Paged KV cache (ISSUE 6): ONE block pool + per-slot block tables.

Contracts under test:
  * allocator soundness: free-list/refcount reconciliation, the
    Smax % Bt construction-time assert, and the ONE-knob validation
    (pool block == prefix block == prefill_cap);
  * zero-copy prefix machinery: publish pins pool blocks by reference,
    eviction drops only the store's reference, reclaim frees under
    memory pressure;
  * EXACT paged-vs-dense token parity (greedy + sampled, fp + int8
    cache, prefix cache on/off, spec on/off) under admission/eviction
    churn — the paged layout must be invisible in the tokens;
  * zero retraces after warmup with the paged path (block ids are
    data, never structure);
  * copy-on-write: fork_slot shares every block, divergence copies
    exactly the touched block, the twin's view is untouched;
  * pool-bounded admission: AdmissionFull on an explicitly sized
    exhausted pool, recovery once eviction releases the commitment.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.paged_kv import (BlockPool, PagedPrefixCache,
                                           PagedPrefixStore)

V, E, H, FF, L = 97, 32, 4, 64, 2


def _model(seed=3):
    from paddle_tpu.incubate.nn import FusedMultiTransformer
    from paddle_tpu.nn.layer.common import Embedding, Linear
    paddle.seed(seed)
    embed = Embedding(V, E)
    fmt = FusedMultiTransformer(E, H, FF, num_layers=L,
                                normalize_before=True)
    head = Linear(E, V, bias_attr=False)
    fmt.eval()
    return fmt, embed, head


def _prompt(rng, n):
    return rng.randint(1, V, (n,)).astype(np.int32)


def _engine(fmt, embed, head, paged, **kw):
    from paddle_tpu.inference.serving import ServingEngine
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq_len", 128)
    kw.setdefault("decode_chunk", 2)
    return ServingEngine(fmt, embed, head, paged=paged, **kw)


def _run(eng, reqs):
    rids = [eng.submit(p, max_new_tokens=m) for p, m in reqs]
    eng.run()
    return [eng.results[r]["tokens"] for r in rids]


class TestBlockPool:
    def test_alloc_free_reconciles(self):
        pool = BlockPool(4, 8, 64)
        a = pool.alloc(2)
        assert sorted(a) == [0, 1] and pool.used == 2
        pool.ref([a[0]])
        pool.deref([a[0]])                       # still held once
        assert pool.used == 2
        pool.deref(a)                            # both free now
        assert pool.used == 0 and pool.free_count == 4
        assert pool.alloc(5) is None             # all-or-nothing
        assert pool.free_count == 4

    def test_refcount_underflow_and_free_ref_raise(self):
        pool = BlockPool(2, 8, 64)
        (b,) = pool.alloc(1)
        pool.deref([b])
        with pytest.raises(RuntimeError, match="underflow"):
            pool.deref([b])
        with pytest.raises(RuntimeError, match="free block"):
            pool.ref([b])

    def test_smax_must_align_to_block_tokens(self):
        """The satellite assert: a ragged last block would gather out
        of bounds downstream — refuse at construction with a clear
        message instead."""
        with pytest.raises(ValueError, match="multiple of block_tokens"):
            BlockPool(4, 8, 60)
        with pytest.raises(ValueError, match="power of two"):
            BlockPool(4, 6, 60)

    def test_one_knob_pool_vs_prefill_cap(self):
        """prefill_cap, prefix block_tokens and the pool Bt are ONE
        value — a mismatched explicit pool is refused naming both."""
        fmt, embed, head = _model()
        with pytest.raises(ValueError, match="block_tokens=8.*"
                           "prefill_cap=16"):
            _engine(fmt, embed, head, True, prefill_cap=16,
                    kv_pool=BlockPool(8, 8, 128))
        with pytest.raises(ValueError, match="ONE value"):
            PagedPrefixStore(4, 16, BlockPool(8, 8, 128))

    def test_copy_block_copies_exactly_one_block(self):
        import jax.numpy as jnp
        rng = np.random.RandomState(0)
        pool = BlockPool(4, 8, 64)
        caches = {"kv": jnp.asarray(rng.randn(L, 2, 4, H, 8, 8),
                                    jnp.float32)}
        before = np.asarray(caches["kv"])
        out = pool.copy_block(caches, 1, 3)
        after = np.asarray(out["kv"])
        np.testing.assert_array_equal(after[:, :, 3], before[:, :, 1])
        np.testing.assert_array_equal(after[:, :, :3], before[:, :, :3])
        assert pool.trace_count == 1
        pool.copy_block(out, 0, 2)
        assert pool.trace_count == 1             # executable reused


class TestPagedPrefixStore:
    def _pool_store(self, budget=4, bt=2, nb=8):
        pool = BlockPool(nb, bt, 16)
        return pool, PagedPrefixStore(budget, bt, pool)

    def test_publish_pins_by_reference_no_copy(self):
        pool, st = self._pool_store()
        ids = pool.alloc(2)                      # the "slot's" blocks
        plan = st.publish(np.asarray([1, 2, 3, 4]), ids)
        assert [new for _, new in plan] == [True, True]
        assert [n.block for n, _ in plan] == ids
        assert list(pool.refcounts[ids]) == [2, 2]   # slot + store
        # slot releases -> blocks stay resident through the store ref
        pool.deref(ids)
        assert pool.used == 2
        again = st.publish(np.asarray([1, 2, 3, 4]), [7, 7])
        assert [new for _, new in again] == [False, False]   # dedup

    def test_eviction_drops_only_store_reference(self):
        pool, st = self._pool_store(budget=1)
        ids = pool.alloc(2)
        st.publish(np.asarray([1, 2]), [ids[0]])
        # budget 1: the next publish evicts the LRU leaf, which merely
        # derefs — the "slot" still holds ids[0], so it stays resident
        st.publish(np.asarray([5, 6]), [ids[1]])
        assert st.stats()["evictions"] == 1
        assert pool.refcounts[ids[0]] == 1       # slot ref survives
        assert len(st.match(np.asarray([1, 2]))) == 0

    def test_reclaim_frees_cold_chains(self):
        pool, st = self._pool_store(budget=4, nb=4)
        ids = pool.alloc(4)
        st.publish(np.arange(1, 9), ids)         # 4-block chain
        pool.deref(ids)                          # owner finished
        assert pool.free_count == 0
        freed = st.reclaim(2)
        assert freed == 2 and pool.free_count == 2
        s = st.stats()
        assert s["blocks_used"] + s["blocks_free"] == s["blocks_capacity"]

    def test_insert_is_refused(self):
        pool, st = self._pool_store()
        with pytest.raises(NotImplementedError, match="publish"):
            st.insert(np.asarray([1, 2]))


class TestPagedParity:
    """The tentpole contract: the paged layout is INVISIBLE in the
    tokens — exact parity with the dense ring across every serving
    flavor, under slot churn (5+ requests through 2 slots)."""

    # prefill_cap=64 drives the paged Pallas kernel (Bt meets the
    # sublane tiling); prefill_cap=4 drives the gather-dense fallback
    @pytest.mark.parametrize("cap", [64, 4])
    def test_greedy_parity_under_churn(self, cap, serving_metrics_ok):
        fmt, embed, head = _model()
        rng = np.random.RandomState(0)
        reqs = [(_prompt(rng, s), m)
                for s, m in [(5, 6), (3, 4), (7, 8), (4, 5), (6, 3)]]
        toks_p = _run(_engine(fmt, embed, head, True, prefill_cap=cap),
                      reqs)
        eng_d = _engine(fmt, embed, head, False, prefill_cap=cap)
        toks_d = _run(eng_d, reqs)
        assert not eng_d.paged and eng_d.pool is None
        for a, b in zip(toks_p, toks_d):
            np.testing.assert_array_equal(a, b)

    def test_sampled_parity(self):
        fmt, embed, head = _model(seed=8)
        rng = np.random.RandomState(1)
        reqs = [(_prompt(rng, s), m)
                for s, m in [(5, 8), (3, 6), (6, 8), (4, 6)]]

        def run(paged):
            paddle.seed(0)               # identical sampling key stream
            return _run(_engine(fmt, embed, head, paged,
                                do_sample=True, top_k=5), reqs)
        for a, b in zip(run(True), run(False)):
            np.testing.assert_array_equal(a, b)

    def test_int8_cache_parity(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_DECODE_INT8_CACHE", "1")
        fmt, embed, head = _model(seed=5)
        rng = np.random.RandomState(2)
        reqs = [(_prompt(rng, s), m)
                for s, m in [(5, 6), (3, 5), (6, 4)]]
        for a, b in zip(_run(_engine(fmt, embed, head, True), reqs),
                        _run(_engine(fmt, embed, head, False), reqs)):
            np.testing.assert_array_equal(a, b)

    def _shared_reqs(self, rng, n=10):
        prefixes = [_prompt(rng, 8) for _ in range(3)]
        reqs = [(prefixes[0].copy(), 3), (prefixes[0].copy(), 3)]
        for i in range(n):
            reqs.append((np.concatenate(
                [prefixes[i % 3], _prompt(rng, 2 + i % 5)]), 4))
        return reqs

    @pytest.mark.parametrize("sample", [False, True])
    def test_prefix_cache_parity_under_eviction_churn(
            self, sample, serving_metrics_ok):
        """Paged prefix caching (zero-copy adopt/publish) must match
        BOTH the paged cache-off run and the dense cache-on run, token
        for token — with a 3-block store budget forcing constant
        eviction/republication churn."""
        fmt, embed, head = _model(seed=31)
        rng = np.random.RandomState(5)
        reqs = self._shared_reqs(rng)

        def run(paged, blocks):
            paddle.seed(0)
            eng = _engine(fmt, embed, head, paged, prefill_cap=4,
                          prefix_cache_blocks=blocks,
                          do_sample=sample, top_k=5)
            return eng, _run(eng, reqs)

        eng_on, t_on = run(True, 3)
        _, t_off = run(True, 0)
        _, t_dense = run(False, 3)
        for a, b in zip(t_on, t_off):
            np.testing.assert_array_equal(a, b)
        for a, b in zip(t_on, t_dense):
            np.testing.assert_array_equal(a, b)
        m = serving_metrics_ok(eng_on)
        assert isinstance(eng_on.prefix_cache, PagedPrefixCache)
        assert m["prefix_hits"] > 0
        assert m["prefill_tokens_saved"] > 0
        assert m["prefix_store"]["evictions"] > 0

    def test_spec_decode_parity(self, serving_metrics_ok):
        """spec_k on the paged path: greedy outputs token-identical to
        paged spec-off AND to the dense spec-on engine."""
        fmt, embed, head = _model(seed=13)
        rng = np.random.RandomState(0)
        reqs = [(np.tile(_prompt(rng, 6), 3), 24) for _ in range(5)]

        def run(paged, k):
            paddle.seed(0)
            eng = _engine(fmt, embed, head, paged, spec_k=k)
            return eng, _run(eng, reqs)

        eng_pk, t_pk = run(True, 4)
        _, t_p0 = run(True, 0)
        _, t_dk = run(False, 4)
        for a, b in zip(t_pk, t_p0):
            np.testing.assert_array_equal(a, b)
        for a, b in zip(t_pk, t_dk):
            np.testing.assert_array_equal(a, b)
        m = serving_metrics_ok(eng_pk)
        assert m["draft_accepted"] > 0           # speculation really ran


class TestPagedChurn:
    def test_zero_retraces_after_warmup(self, serving_metrics_ok):
        """Block ids are DATA: slot churn, lazy block mapping, prefix
        adoption and eviction must not trace anything new once warmup
        exercised the bucket ladder."""
        fmt, embed, head = _model(seed=32)
        rng = np.random.RandomState(6)
        prefixes = [_prompt(rng, 8) for _ in range(2)]
        reqs = [(np.concatenate([prefixes[i % 2],
                                 _prompt(rng, 2 + i % 4)]), 4)
                for i in range(12)]
        eng = _engine(fmt, embed, head, True, prefill_cap=4,
                      prefix_cache_blocks=16)
        for p, m in reqs[:6]:
            eng.submit(p, max_new_tokens=m)
        eng.run()
        warm = eng.metrics()["traces"]
        assert warm > 0
        for p, m in reqs[6:]:
            eng.submit(p, max_new_tokens=m)
        eng.run()
        m = serving_metrics_ok(eng)
        assert m["traces"] == warm, (
            f"paged churn retraced: {warm} -> {m['traces']}")
        assert m["prefix_hits"] > 0
        # everything returned to the pool except the store's pins
        assert m["kv_blocks_used"] == \
            int((eng.pool.refcounts > 0).sum())

    def test_request_at_exact_ring_capacity(self):
        """The boundary request (final write at Smax - 1) completes on
        the paged path and maps exactly Smax/Bt blocks."""
        fmt, embed, head = _model(seed=14)
        rng = np.random.RandomState(5)
        eng = _engine(fmt, embed, head, True, num_slots=1)
        rid = eng.submit(_prompt(rng, 120), max_new_tokens=8)
        eng.run()
        assert eng.results[rid]["tokens"].size == 8
        assert int(eng._lens[0]) == 127
        assert eng.metrics()["kv_blocks_used"] == 0   # freed on finish


class TestCopyOnWrite:
    def test_fork_shares_then_cow_diverges(self, serving_metrics_ok):
        """Two slots share a prefix block and diverge: fork_slot clones
        a running request by table copy (+refcounts, zero data
        movement); the first divergent write triggers the COW of just
        that block, and the twin's tokens/prefix stay intact."""
        fmt, embed, head = _model(seed=7)
        rng = np.random.RandomState(9)
        eng = _engine(fmt, embed, head, True, do_sample=True, top_k=20,
                      temperature=5.0)
        rid = eng.submit(_prompt(rng, 9), max_new_tokens=24)
        eng.step()
        eng.step()
        n_fork = len(eng._slot_req[0].tokens)    # generated so far
        used_before = eng.metrics()["kv_blocks_used"]
        child = eng.fork_slot(rid)
        # the fork added ZERO blocks: pure table copy + refcounts
        assert eng.metrics()["kv_blocks_used"] == used_before
        shared = int((eng.pool.refcounts > 1).sum())
        assert shared > 0
        eng.run()
        m = serving_metrics_ok(eng)
        a = eng.results[rid]["tokens"]
        b = eng.results[child]["tokens"]
        assert len(a) == len(b) == 24
        # the pre-fork generated prefix is common; the suffixes diverge
        np.testing.assert_array_equal(a[:n_fork], b[:n_fork])
        assert list(a) != list(b)
        # divergence copied at least the shared write block — and ONLY
        # blocks, never rows (the counter counts block copies)
        assert m["kv_cow_copies"] >= 1
        assert m["kv_blocks_used"] == 0          # both freed cleanly

    def test_fork_reconciles_with_prefix_metrics(self,
                                                 serving_metrics_ok):
        """A fork is a CLONE, not an admission: it performs no prefix
        lookup, so it must ride `requests_forked` — counting it as
        admitted broke hits + misses == admitted on prefix-cache
        engines."""
        fmt, embed, head = _model(seed=17)
        rng = np.random.RandomState(3)
        eng = _engine(fmt, embed, head, True, prefill_cap=4,
                      prefix_cache_blocks=8, do_sample=True, top_k=10)
        rid = eng.submit(_prompt(rng, 9), max_new_tokens=8)
        eng.step()
        eng.fork_slot(rid)
        eng.run()
        m = serving_metrics_ok(eng)        # reconciliation holds
        assert m["requests_forked"] == 1
        assert m["requests_admitted"] == 1
        assert m["requests_finished"] == 2

    def test_fork_requires_paged(self):
        fmt, embed, head = _model(seed=7)
        eng = _engine(fmt, embed, head, False)
        with pytest.raises(ValueError, match="paged"):
            eng.fork_slot(0)


class TestPoolExhaustion:
    def test_admission_full_then_recovery(self, serving_metrics_ok):
        """An EXPLICITLY sized pool is a stated memory budget: submit
        sheds with AdmissionFull when queued+running commitments would
        exceed it, and recovers once eviction releases blocks. The
        pool — not the slot count — is the bound (4 free slots here)."""
        from paddle_tpu.inference.serving import AdmissionFull
        fmt, embed, head = _model(seed=21)
        rng = np.random.RandomState(0)
        eng = _engine(fmt, embed, head, True, num_slots=4,
                      prefill_cap=4, kv_pool_blocks=6)
        assert eng._kv_gate
        # each request: 5 prompt + 6 new = 11 tokens -> 3 blocks
        eng.submit(_prompt(rng, 5), max_new_tokens=6)
        eng.submit(_prompt(rng, 5), max_new_tokens=6)
        with pytest.raises(AdmissionFull, match="kv pool exhausted"):
            eng.submit(_prompt(rng, 5), max_new_tokens=6)
        assert eng.metrics()["requests_rejected"] == 1
        eng.run()                                # eviction frees blocks
        rid = eng.submit(_prompt(rng, 5), max_new_tokens=6)
        eng.run()
        assert eng.results[rid]["tokens"].size == 6
        m = serving_metrics_ok(eng)
        assert m["requests_finished"] == 3
        assert m["kv_blocks_used"] == 0

    def test_exact_reservation_fill_completes(self, serving_metrics_ok):
        """Requests whose worst-case reservations EXACTLY fill the pool
        must run to completion: the per-chunk write-window mapping is
        clamped to each slot's token budget, so the final chunk (whose
        raw window [lens, lens+chunk) crosses past the last budgeted
        position) never asks for a block beyond the reservation
        (crashed with 'pool over-committed' before the clamp)."""
        fmt, embed, head = _model(seed=27)
        rng = np.random.RandomState(2)
        eng = _engine(fmt, embed, head, True, num_slots=2,
                      prefill_cap=4, kv_pool_blocks=6, decode_chunk=4)
        # 6 prompt + 6 new = 12 tokens = exactly 3 blocks each; the
        # last decode chunk's unclamped window would touch block 3
        rids = [eng.submit(_prompt(rng, 6), max_new_tokens=6)
                for _ in range(2)]
        eng.run()
        assert all(eng.results[r]["tokens"].size == 6 for r in rids)
        m = serving_metrics_ok(eng)
        assert m["kv_blocks_used"] == 0

    def test_never_fitting_request_is_a_value_error(self):
        fmt, embed, head = _model(seed=22)
        eng = _engine(fmt, embed, head, True, num_slots=1,
                      prefill_cap=4, kv_pool_blocks=4)
        with pytest.raises(ValueError, match="never"):
            eng.submit(np.ones(30, np.int32), max_new_tokens=40)

    def test_default_pool_never_sheds(self):
        """Default sizing (B x Smax/Bt == the dense HBM footprint) must
        behave exactly like the dense engine: queue absorbs any burst,
        no kv gate."""
        fmt, embed, head = _model(seed=23)
        rng = np.random.RandomState(1)
        eng = _engine(fmt, embed, head, True)
        assert not eng._kv_gate
        rids = [eng.submit(_prompt(rng, 4), max_new_tokens=3)
                for _ in range(12)]              # 6x the slot count
        eng.run()
        assert all(eng.results[r]["tokens"].size == 3 for r in rids)


class TestPagedEnvKnob:
    def test_env_flag_selects_the_layout(self, monkeypatch):
        fmt, embed, head = _model(seed=24)
        monkeypatch.setenv("PADDLE_SERVING_PAGED", "0")
        eng = _engine(fmt, embed, head, None)
        assert not eng.paged and eng.pool is None
        monkeypatch.setenv("PADDLE_SERVING_PAGED", "1")
        eng = _engine(fmt, embed, head, None)
        assert eng.paged and eng.pool is not None

    def test_kv_budget_on_a_dense_engine_is_refused(self, monkeypatch):
        """A stated pool budget must never be silently dropped: a
        dense-resolved engine (env off / paged=False) with
        kv_pool_blocks= fails fast instead of serving without the
        AdmissionFull gate the operator asked for."""
        fmt, embed, head = _model(seed=26)
        with pytest.raises(ValueError, match="DENSE"):
            _engine(fmt, embed, head, False, kv_pool_blocks=8)
        monkeypatch.setenv("PADDLE_SERVING_PAGED", "0")
        with pytest.raises(ValueError, match="DENSE"):
            _engine(fmt, embed, head, None,
                    kv_pool=BlockPool(8, 64, 128))

    def test_shared_dense_prefix_cache_forces_dense(self):
        """A cross-engine dense PrefixCache keeps working (its pool is
        separate storage): default-paged engines silently fall back,
        an EXPLICIT paged=True is refused loudly — and an engine-
        private PagedPrefixCache is refused as prefix_cache= instead
        of dying later with an AttributeError in _admit."""
        from paddle_tpu.inference.prefix_cache import PrefixCache
        fmt, embed, head = _model(seed=25)
        pc = PrefixCache(8, 64)
        eng = _engine(fmt, embed, head, None, prefix_cache=pc)
        assert not eng.paged
        with pytest.raises(ValueError, match="paged"):
            _engine(fmt, embed, head, True, prefix_cache=pc)
        paged_pc = PagedPrefixCache(8, 64, BlockPool(8, 64, 128))
        with pytest.raises(ValueError, match="PagedPrefixCache"):
            _engine(fmt, embed, head, None, prefix_cache=paged_pc)
