"""DataLoader / save-load / jit.to_static / hapi tests."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.io as io
from paddle_tpu import nn
from paddle_tpu.io import (BatchSampler, DataLoader, Dataset,
                           DistributedBatchSampler, IterableDataset,
                           TensorDataset)


class RangeDS(Dataset):
    def __init__(self, n=20):
        self.n = n

    def __getitem__(self, i):
        return np.float32([i, i * 2]), np.int64(i % 3)

    def __len__(self):
        return self.n


class TestDataLoader:
    def test_basic_batching(self):
        dl = DataLoader(RangeDS(10), batch_size=4)
        batches = list(dl)
        assert len(batches) == 3
        x, y = batches[0]
        assert x.shape == [4, 2]
        assert y.shape == [4]

    def test_drop_last_shuffle(self):
        dl = DataLoader(RangeDS(10), batch_size=4, drop_last=True,
                        shuffle=True)
        assert len(list(dl)) == 2

    def test_multiworker_order(self):
        dl = DataLoader(RangeDS(12), batch_size=3, num_workers=2)
        xs = [b[0].numpy()[:, 0] for b in dl]
        flat = np.concatenate(xs)
        np.testing.assert_array_equal(flat, np.arange(12))

    def test_iterable_dataset(self):
        class It(IterableDataset):
            def __iter__(self):
                for i in range(7):
                    yield np.float32([i])
        dl = DataLoader(It(), batch_size=3)
        bs = list(dl)
        assert len(bs) == 3
        assert bs[-1].shape == [1, 1]

    def test_tensor_dataset(self):
        t = TensorDataset([paddle.ones([6, 2]), paddle.zeros([6])])
        x, y = t[2]
        assert x.shape == [2]

    def test_distributed_batch_sampler(self):
        ds = RangeDS(16)
        s0 = DistributedBatchSampler(ds, batch_size=2, num_replicas=4, rank=0)
        s3 = DistributedBatchSampler(ds, batch_size=2, num_replicas=4, rank=3)
        idx0 = [i for b in s0 for i in b]
        idx3 = [i for b in s3 for i in b]
        assert len(idx0) == len(idx3) == 4
        assert set(idx0).isdisjoint(idx3)
        assert len(s0) == 2

    def test_distributed_sampler_epoch_shuffle(self):
        ds = RangeDS(16)
        s = DistributedBatchSampler(ds, batch_size=4, num_replicas=2, rank=0,
                                    shuffle=True)
        s.set_epoch(0)
        e0 = [i for b in s for i in b]
        s.set_epoch(1)
        e1 = [i for b in s for i in b]
        assert e0 != e1


class TestSaveLoad:
    def test_state_dict_file_roundtrip(self, tmp_path):
        m = nn.Sequential(nn.Linear(4, 8), nn.LayerNorm(8))
        path = str(tmp_path / "model.pdparams")
        paddle.save(m.state_dict(), path)
        m2 = nn.Sequential(nn.Linear(4, 8), nn.LayerNorm(8))
        m2.set_state_dict(paddle.load(path))
        np.testing.assert_array_equal(m[0].weight.numpy(),
                                      m2[0].weight.numpy())

    def test_optimizer_state_roundtrip(self, tmp_path):
        m = nn.Linear(4, 4)
        opt = paddle.optimizer.AdamW(parameters=m.parameters())
        m(paddle.ones([2, 4])).sum().backward()
        opt.step()
        path = str(tmp_path / "opt.pdopt")
        paddle.save(opt.state_dict(), path)
        sd = paddle.load(path)
        assert any("moment1" in k for k in sd)

    def test_nested_structures(self, tmp_path):
        obj = {"a": paddle.ones([2]), "b": [paddle.zeros([3]), 5],
               "c": {"d": "text"}}
        path = str(tmp_path / "obj.pd")
        paddle.save(obj, path)
        back = paddle.load(path)
        np.testing.assert_array_equal(back["a"].numpy(), [1, 1])
        assert back["b"][1] == 5
        assert back["c"]["d"] == "text"

    def test_jit_save_load(self, tmp_path):
        m = nn.Linear(4, 2)
        path = str(tmp_path / "infer")
        paddle.jit.save(m, path)
        loaded = paddle.jit.load(path)
        assert "weight" in loaded.state_dict()


class TestToStatic:
    def test_forward_cache_single_compile(self):
        m = nn.Linear(4, 4)
        calls = []
        orig_forward = m.forward

        def counting(x):
            calls.append(1)
            return orig_forward(x)
        fwd = paddle.jit.to_static(counting)
        x = paddle.ones([2, 4])
        fwd(x)
        fwd(x)
        fwd(x)
        assert len(calls) == 1  # traced once

    def test_shape_polymorphism_recompiles(self):
        m = nn.Linear(4, 4)
        fwd = paddle.jit.to_static(lambda x: m(x))
        a = fwd(paddle.ones([2, 4]))
        b = fwd(paddle.ones([3, 4]))
        assert a.shape == [2, 4] and b.shape == [3, 4]

    def test_train_step_state_threading(self):
        paddle.seed(0)
        m = nn.Linear(4, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        x = paddle.ones([4, 4])
        y = paddle.zeros([4, 1])

        @paddle.jit.to_static
        def step(x, y):
            loss = paddle.nn.functional.mse_loss(m(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        losses = [float(step(x, y).numpy()) for _ in range(5)]
        assert losses[-1] < losses[0]

    def test_jit_matches_eager_train(self):
        def build():
            paddle.seed(11)
            m = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
            opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                         parameters=m.parameters())
            return m, opt
        x = paddle.to_tensor(np.random.RandomState(0).randn(8, 4).astype(
            np.float32))
        y = paddle.to_tensor(np.random.RandomState(1).randn(8, 1).astype(
            np.float32))

        m1, o1 = build()
        eager_losses = []
        for _ in range(4):
            l = paddle.nn.functional.mse_loss(m1(x), y)
            l.backward()
            o1.step()
            o1.clear_grad()
            eager_losses.append(float(l.numpy()))

        m2, o2 = build()

        @paddle.jit.to_static
        def step(x, y):
            l = paddle.nn.functional.mse_loss(m2(x), y)
            l.backward()
            o2.step()
            o2.clear_grad()
            return l
        jit_losses = [float(step(x, y).numpy()) for _ in range(4)]
        np.testing.assert_allclose(eager_losses, jit_losses, rtol=1e-4,
                                   atol=1e-5)

    def test_run_steps_matches_sequential(self):
        """k steps in one scanned device program == k sequential compiled
        calls: same per-step losses, same final params, state written back."""
        def build():
            paddle.seed(11)
            m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
            o = paddle.optimizer.AdamW(learning_rate=0.01,
                                       parameters=m.parameters())

            @paddle.jit.to_static
            def step(x, y):
                l = paddle.nn.functional.mse_loss(m(x), y)
                l.backward()
                o.step()
                o.clear_grad()
                return l
            return m, step

        rng = np.random.RandomState(3)
        xs = rng.randn(5, 6, 4).astype(np.float32)
        ys = rng.randn(5, 6, 2).astype(np.float32)

        m1, s1 = build()
        seq = [float(s1(paddle.to_tensor(xs[i]),
                        paddle.to_tensor(ys[i])).numpy()) for i in range(5)]

        m2, s2 = build()
        first = float(s2(paddle.to_tensor(xs[0]),
                         paddle.to_tensor(ys[0])).numpy())
        outs = s2.run_steps(4, paddle.to_tensor(xs[1:]),
                            paddle.to_tensor(ys[1:]))
        got = [first] + [float(v) for v in np.asarray(outs._data)]
        np.testing.assert_allclose(seq, got, rtol=1e-5, atol=1e-6)
        for p, q in zip(m1.parameters(), m2.parameters()):
            np.testing.assert_allclose(np.asarray(p._data),
                                       np.asarray(q._data),
                                       rtol=1e-5, atol=1e-6)

    def test_run_steps_unsteady_state_raises(self):
        paddle.seed(12)
        m = nn.Linear(3, 3)
        o = paddle.optimizer.AdamW(learning_rate=0.01,
                                   parameters=m.parameters())

        @paddle.jit.to_static
        def step(x):
            l = m(x).sum()
            l.backward()
            o.step()
            o.clear_grad()
            return l
        xs = paddle.to_tensor(np.ones((3, 2, 3), np.float32))
        with pytest.raises(RuntimeError, match="persistent state"):
            step.run_steps(3, xs)

    def test_dropout_differs_across_jit_calls(self):
        """RNG key threads through the compiled step as state — two calls
        must produce different masks (trace-time constant would repeat)."""
        paddle.seed(5)
        drop = nn.Dropout(0.5)

        @paddle.jit.to_static
        def f(x):
            return drop(x)
        x = paddle.ones([100])
        a = f(x).numpy()
        b = f(x).numpy()
        assert not np.array_equal(a, b)


class TestHapi:
    def test_model_fit_evaluate(self, tmp_path):
        paddle.seed(2)
        net = nn.Sequential(nn.Linear(2, 16), nn.ReLU(), nn.Linear(16, 3))
        model = paddle.Model(net)
        model.prepare(
            optimizer=paddle.optimizer.Adam(learning_rate=0.01,
                                            parameters=net.parameters()),
            loss=nn.CrossEntropyLoss())

        class DS(paddle.io.Dataset):
            def __getitem__(self, i):
                x = np.float32([i % 3, (i % 3) * 2])
                return x, np.int64(i % 3)

            def __len__(self):
                return 30

        model.fit(DS(), batch_size=10, epochs=3, verbose=0)
        logs = model.evaluate(DS(), batch_size=10, verbose=0)
        assert logs["loss"] < 1.2
        model.save(str(tmp_path / "ckpt"))
        model.load(str(tmp_path / "ckpt"))


class TestStaticAPI:
    def test_program_executor(self):
        prog = paddle.static.Program()
        paddle.enable_static()
        try:
            with paddle.static.program_guard(prog):
                x = paddle.static.data("x", [None])
                out = x * 2 + 1
        finally:
            paddle.disable_static()
        exe = paddle.static.Executor()
        res = exe.run(prog, feed={"x": np.array([1.0, 2.0], np.float32)},
                      fetch_list=[out])
        np.testing.assert_allclose(res[0], [3.0, 5.0])

    def test_input_spec(self):
        spec = paddle.static.InputSpec([None, 4], "float32", "x")
        assert spec.shape == [None, 4]


class TestProcessWorkers:
    """Process-based DataLoader workers + device-prefetch buffer
    (VERDICT r1 missing-6; reference: python/paddle/io/dataloader/ worker
    processes & pin-memory thread)."""

    def _ds(self, n=12):
        class SquareDS(io.Dataset):
            def __len__(self):
                return n

            def __getitem__(self, i):
                return (np.full((3,), i, np.float32), i * i)
        return SquareDS()

    def test_process_workers_order_and_values(self):
        loader = io.DataLoader(self._ds(), batch_size=4, shuffle=False,
                               num_workers=2)
        batches = list(loader)
        assert len(batches) == 3
        for bi, (xb, yb) in enumerate(batches):
            np.testing.assert_allclose(
                np.asarray(xb._data)[:, 0], [bi * 4 + j for j in range(4)])
            np.testing.assert_allclose(
                np.asarray(yb._data), [(bi * 4 + j) ** 2 for j in range(4)])

    def test_worker_exception_propagates(self):
        class BadDS(io.Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                if i == 5:
                    raise ValueError("boom at 5")
                return np.zeros((2,), np.float32)

        loader = io.DataLoader(BadDS(), batch_size=2, shuffle=False,
                               num_workers=2)
        import pytest
        with pytest.raises(RuntimeError, match="boom at 5"):
            list(loader)

    def test_get_worker_info_in_process(self):
        class WhoDS(io.Dataset):
            def __len__(self):
                return 4

            def __getitem__(self, i):
                info = io.get_worker_info()
                assert info is not None and info.num_workers == 2
                return np.asarray([info.id], np.int64)

        loader = io.DataLoader(WhoDS(), batch_size=2, shuffle=False,
                               num_workers=2)
        ids = np.concatenate([np.asarray(b._data) for b in list(loader)])
        assert set(ids.reshape(-1)) <= {0, 1}

    def test_device_prefetch_yields_device_tensors(self):
        loader = io.DataLoader(self._ds(4), batch_size=2, shuffle=False,
                               num_workers=0, use_buffer_reader=True)
        xb, yb = next(iter(loader))
        import jax
        assert isinstance(xb._data, jax.Array)


class TestFailedTraceRollback:
    """A trace/compile failure must not poison later jit calls.

    Regression: a config whose to_static trace aborted mid-step (observed
    live: a transient remote-compile error) left lazily-created optimizer
    slots registered with escaped tracers, and every LATER unrelated
    to_static call in the process died with UnexpectedTracerError."""

    def _mk(self):
        paddle.seed(0)
        m = nn.Linear(4, 4)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters(),
                                     multi_precision=True)
        return m, opt

    def test_rollback_then_fresh_model_and_retry(self):
        import jax
        from paddle_tpu.tensor.tensor import persistent_tensors

        m1, opt1 = self._mk()
        boom = [True]

        def step1(x):
            loss = m1(x).sum()
            loss.backward()
            opt1.step()        # lazily creates moment/master slots
            opt1.clear_grad()
            if boom[0]:
                raise ValueError("injected trace failure")
            return loss

        s1 = paddle.jit.to_static(step1)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        with pytest.raises(Exception):
            s1(x)

        # the registry must hold no escaped tracers / dead tensors
        for t in persistent_tensors():
            assert t._data is not None
            assert not isinstance(t._data, jax.core.Tracer)

        # an unrelated fresh model+optimizer compiles and steps fine
        m2, opt2 = self._mk()

        def step2(x):
            loss = m2(x).sum()
            loss.backward()
            opt2.step()
            opt2.clear_grad()
            return loss

        out = paddle.jit.to_static(step2)(x)
        assert np.isfinite(float(np.asarray(out._data)))

        # retrying the SAME optimizer recreates its dead slots
        boom[0] = False
        out = s1(x)
        assert np.isfinite(float(np.asarray(out._data)))

    def test_rollback_heals_rng_key_and_state_dict(self):
        import jax
        import paddle_tpu.core.rng as rng_mod
        from paddle_tpu.tensor.tensor import persistent_tensors

        m, opt = self._mk()
        # force the global RNG key to be lazily created INSIDE the failing
        # trace so the rollback kills it too (must come AFTER _mk():
        # paddle.seed(0) in there eagerly recreates the key)
        rng_mod._rng.key_tensor = None
        drop = nn.Dropout(0.5)

        def step(x):
            loss = drop(m(x)).sum()   # dropout pulls next_key() under trace
            loss.backward()
            opt.step()
            opt.clear_grad()
            raise ValueError("injected trace failure")

        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        with pytest.raises(Exception):
            paddle.jit.to_static(step)(x)

        # checkpointing right after the failure must not see dead slots
        sd = opt.state_dict()
        for k, v in sd.items():
            if hasattr(v, "_data"):
                assert v._data is not None, k

        # RNG recovers: seeded retry path rebuilds a live, tracked key
        k = rng_mod.next_key()
        assert not isinstance(k, jax.core.Tracer)
        live = {id(t) for t in persistent_tensors()}
        assert id(rng_mod._rng.key_tensor) in live
