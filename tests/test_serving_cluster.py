"""Cluster serving front-end: gateway + router + replicas.

Contracts under test:
  * consistent-hash ring: replica add/remove moves only the
    removed/added replica's keys (prefix affinity survives churn);
  * router policies: queue-depth tie-breaking, saturation spill,
    template->replica affinity, idempotent re-submission by request id,
    schema_version trust;
  * the engine's incremental-harvest API: a tracked reader never loses
    a finished request to the bounded results cap (the documented SSE
    race this API closes);
  * e2e over real HTTP: OpenAI-compatible JSON + SSE match the
    sequential FusedDecoder oracle token-for-token, zero retraces per
    replica across router churn, and a replica killed MID-STREAM fails
    over with greedy token parity — all waits bounded;
  * tools/check_http_surface.py passes (the wire protocol is pinned).
"""
import importlib.util
import json
import os
import socket
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate.nn import FusedMultiTransformer
from paddle_tpu.inference.generation import FusedDecoder
from paddle_tpu.inference.serving import AdmissionFull, ServingEngine
from paddle_tpu.inference.telemetry import SNAPSHOT_SCHEMA_VERSION
from paddle_tpu.nn.layer.common import Embedding, Linear
from paddle_tpu.serving_cluster import (Gateway, HashRing, LocalReplica,
                                        NoReplicaError, Router)
from paddle_tpu.serving_cluster.replica import ReplicaError

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
V, E, H, FF, L = 97, 32, 4, 64, 2
WAIT_S = 120                              # bound on every drain loop


def _model(seed=3):
    paddle.seed(seed)
    embed = Embedding(V, E)
    fmt = FusedMultiTransformer(E, H, FF, num_layers=L,
                                normalize_before=True)
    head = Linear(E, V, bias_attr=False)
    fmt.eval()
    return fmt, embed, head


def _engine(fmt, embed, head, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq_len", 128)
    kw.setdefault("prefill_cap", 8)
    return ServingEngine(fmt, embed, head, **kw)


def _oracle(fmt, embed, head, prompt, max_new):
    dec = FusedDecoder(fmt, embed, head, max_seq_len=128)
    out = dec.generate(paddle.to_tensor(np.asarray(prompt, np.int32)[None]),
                       max_new_tokens=max_new)
    return [int(t) for t in np.asarray(out._data)[0, len(prompt):]]


# =====================================================================
# consistent-hash ring
# =====================================================================
class TestHashRing:
    def test_minimal_key_movement_on_remove_and_add(self):
        ring = HashRing()
        for n in ("r0", "r1", "r2", "r3"):
            ring.add(n)
        keys = [f"template-{i}".encode() for i in range(256)]
        before = {k: ring.owner(k) for k in keys}
        ring.remove("r2")
        after = {k: ring.owner(k) for k in keys}
        moved = [k for k in keys if before[k] != after[k]]
        # ONLY keys r2 owned may move, and they all must
        assert all(before[k] == "r2" for k in moved)
        assert all(after[k] != "r2" for k in keys)
        assert moved == [k for k in keys if before[k] == "r2"]
        # re-adding restores the exact previous ownership (hash points
        # are a pure function of the name)
        ring.add("r2")
        assert {k: ring.owner(k) for k in keys} == before
        # balance sanity: every replica owns SOME keys at 256 keys
        from collections import Counter
        counts = Counter(before.values())
        assert set(counts) == {"r0", "r1", "r2", "r3"}

    def test_empty_ring_owner_is_none(self):
        assert HashRing().owner(b"k") is None


# =====================================================================
# router policies over stub replicas (no engines, no devices)
# =====================================================================
class FakeReplica:
    def __init__(self, name, queue_depth=0, slots_free=2, num_slots=2,
                 kv_used=None, schema=SNAPSHOT_SCHEMA_VERSION,
                 prefill_cap=4, full=False):
        self.name = name
        self.engine = None
        self.queue_depth = queue_depth
        self.slots_free = slots_free
        self.num_slots = num_slots
        self.kv_used = kv_used
        self.schema = schema
        self.prefill_cap = prefill_cap
        self.full = full
        self.submitted = []
        self._rid = 0

    def snapshot(self):
        snap = {"schema_version": self.schema, "replica": self.name,
                "queue_depth": self.queue_depth,
                "slots_free": self.slots_free,
                "num_slots": self.num_slots,
                "prefill_cap": self.prefill_cap}
        if self.kv_used is not None:
            snap["kv_blocks"] = {"kv_blocks_total": 16,
                                 "kv_blocks_used": self.kv_used,
                                 "kv_blocks_free": 16 - self.kv_used,
                                 "kv_blocks_used_peak": self.kv_used}
        return snap

    def submit(self, prompt, **kw):
        if self.full:
            raise AdmissionFull(f"{self.name} full")
        self._rid += 1
        self.submitted.append((self._rid, list(prompt), kw))
        return self._rid

    def harvest(self, rid):
        return [], True, "finished"

    def release(self, rid):
        pass

    def heartbeat_age(self):
        return 0.0

    def metrics_prometheus(self):
        return ("# HELP fake_metric a stub sample\n"
                "# TYPE fake_metric gauge\nfake_metric 1\n")

    @property
    def alive(self):
        return True


def _router(reps, **kw):
    kw.setdefault("snap_max_age_s", 0.0)   # stubs: always re-snapshot
    return Router(reps, **kw)


class TestRouterPolicies:
    def test_least_loaded_scores_and_tie_break(self):
        reps = [FakeReplica("a", queue_depth=3, slots_free=0),
                FakeReplica("b", queue_depth=1, slots_free=1),
                FakeReplica("c", queue_depth=1, slots_free=1)]
        r = _router(reps, policy="least_loaded")
        r.submit([1, 2, 3], max_new_tokens=2)
        # b and c tie on score; the name breaks the tie deterministically
        assert reps[1].submitted and not reps[0].submitted
        # pool pressure breaks a queue/slot tie: c's pool is emptier
        reps2 = [FakeReplica("a", queue_depth=0, slots_free=2, kv_used=12),
                 FakeReplica("b", queue_depth=0, slots_free=2, kv_used=2)]
        r2 = _router(reps2, policy="least_loaded")
        r2.submit([1, 2, 3], max_new_tokens=2)
        assert reps2[1].submitted and not reps2[0].submitted

    def test_prefix_affinity_same_template_same_replica(self):
        reps = [FakeReplica(f"r{i}") for i in range(3)]
        r = _router(reps, policy="prefix_affinity")
        t1 = [7, 8, 9, 10, 1]             # >= prefill_cap=4: affine
        t2 = [20, 21, 22, 23, 1]
        for sfx in range(5):
            r.submit(t1[:4] + [sfx], max_new_tokens=2)
            r.submit(t2[:4] + [sfx], max_new_tokens=2)
        homes = {tuple(p[:4]): set() for _, p, _ in
                 [s for rep in reps for s in rep.submitted]}
        for rep in reps:
            for _, p, _ in rep.submitted:
                homes[tuple(p[:4])].add(rep.name)
        # every template lives on exactly ONE replica
        assert all(len(v) == 1 for v in homes.values()), homes

    def test_prefix_affinity_short_prompt_falls_back_to_load(self):
        reps = [FakeReplica("a", queue_depth=5), FakeReplica("b")]
        r = _router(reps, policy="prefix_affinity")
        r.submit([1, 2, 3], max_new_tokens=2)   # < prefill_cap: no block
        assert reps[1].submitted and not reps[0].submitted

    def test_prefix_affinity_saturation_spill(self):
        reps = [FakeReplica("r0"), FakeReplica("r1")]
        r = _router(reps, policy="prefix_affinity", spill_depth=4)
        template = [5, 6, 7, 8, 9]
        r.submit(template, max_new_tokens=2)
        owner = next(rep for rep in reps if rep.submitted)
        other = next(rep for rep in reps if not rep.submitted)
        # saturate the owner past spill_depth: the SAME template must
        # spill to the least-loaded replica instead of queueing forever
        owner.queue_depth = 4
        r.submit(template, max_new_tokens=2)
        assert other.submitted, "saturated owner did not spill"
        # drain the owner: affinity resumes (the spill is pressure-
        # scoped, not a permanent re-home)
        owner.queue_depth = 0
        n_owner = len(owner.submitted)
        r.submit(template, max_new_tokens=2)
        assert len(owner.submitted) == n_owner + 1

    def test_admission_full_spills_then_propagates(self):
        a, b = FakeReplica("a", full=True), FakeReplica("b")
        r = _router([a, b], policy="least_loaded")
        r.submit([1, 2, 3], max_new_tokens=2)   # a sheds -> spills to b
        assert b.submitted
        b.full = True
        with pytest.raises(AdmissionFull):
            r.submit([1, 2, 3], max_new_tokens=2)

    def test_idempotent_by_request_id(self):
        a = FakeReplica("a")
        r = _router([a], policy="least_loaded")
        g1 = r.submit([1, 2, 3], request_id="client-1", max_new_tokens=2)
        g2 = r.submit([1, 2, 3], request_id="client-1", max_new_tokens=2)
        assert g1 == g2 and len(a.submitted) == 1

    def test_schema_version_mismatch_refused(self):
        ok = FakeReplica("ok")
        drift = FakeReplica("drift", schema=SNAPSHOT_SCHEMA_VERSION + 1)
        r = _router([drift, ok], policy="least_loaded")
        r.refresh(force=True)
        assert r.version_mismatches >= 1
        # the drifted replica is unscored (= worst score): traffic goes
        # to the replica whose payload the router can trust
        r.submit([1, 2, 3], max_new_tokens=2)
        assert ok.submitted and not drift.submitted

    def test_no_alive_replica_raises(self):
        a = FakeReplica("a")
        r = _router([a], policy="least_loaded")
        r.mark_dead("a")
        with pytest.raises(NoReplicaError):
            r.submit([1, 2, 3], max_new_tokens=2)

    def test_failover_resubmits_with_remaining_deadline(self):
        """A deadline_s request fails over with its REMAINING budget
        (measured from the original submit), and an already-expired
        one goes straight to state 'expired' instead of restarting its
        clock on the new engine."""
        clock = [0.0]
        # b reports heavy load, so least_loaded pins both requests on a
        a = FakeReplica("a")
        b = FakeReplica("b", queue_depth=50)
        r = _router([a, b], policy="least_loaded",
                    clock=lambda: clock[0])
        g1 = r.submit([1, 2, 3], max_new_tokens=4, deadline_s=10.0)
        g2 = r.submit([4, 5, 6], max_new_tokens=4, deadline_s=1.0)
        assert r.poll(g1)["replica"] == r.poll(g2)["replica"] == "a"
        clock[0] = 3.0                     # g2's 1.0s budget is gone
        r.mark_dead("a")
        p2 = r.poll(g2)
        assert p2["done"] and p2["state"] == "expired"
        assert p2["resubmits"] == 0
        p1 = r.poll(g1)
        assert p1["resubmits"] == 1 and p1["replica"] == "b"
        kw = b.submitted[-1][2]
        assert kw["deadline_s"] == pytest.approx(7.0)

    def test_concurrent_readers_each_see_full_stream(self):
        """harvest(gid, cursor): the assignment keeps the full token
        history, so two readers of ONE gid (an idempotent client
        retry) each stream everything — the old shared destructive
        cursor split the tokens between them."""

        class Scripted(FakeReplica):
            def __init__(self, name, script):
                super().__init__(name)
                self.script = list(script)

            def harvest(self, rid):
                if self.script:
                    return self.script.pop(0), not self.script, \
                        ("finished" if not self.script else "running")
                return [], True, "finished"

        rep = Scripted("s", [[1, 2], [3], [4, 5]])
        r = _router([rep], policy="least_loaded")
        gid = r.submit([7, 8, 9], request_id="dup", max_new_tokens=5)
        assert r.submit([7, 8, 9], request_id="dup",
                        max_new_tokens=5) == gid
        c1 = c2 = 0
        s1, s2 = [], []
        done = False
        while not done:
            new, done, _ = r.harvest(gid, c1)
            s1 += new
            c1 += len(new)
        new, d2, _ = r.harvest(gid, c2)    # reader 2 starts late
        s2 += new
        assert d2 and s1 == s2 == [1, 2, 3, 4, 5]


# =====================================================================
# router decision audit (the placement explainability surface)
# =====================================================================
class TestRouterAudit:
    def test_reason_coverage_and_counters(self):
        from paddle_tpu.serving_cluster import AUDIT_REASONS
        reps = [FakeReplica("r0"), FakeReplica("r1")]
        r = _router(reps, policy="prefix_affinity", spill_depth=4)
        template = [5, 6, 7, 8, 9]
        r.submit(template, max_new_tokens=2)        # affinity_hit
        assert r.audit[-1]["reason"] == "affinity_hit"
        owner_name = r.audit[-1]["chosen"]
        r.submit([1, 2, 3], max_new_tokens=2)       # short: least_loaded
        assert r.audit[-1]["reason"] == "least_loaded"
        owner = next(rep for rep in reps if rep.name == owner_name)
        owner.queue_depth = 4                       # saturate the owner
        r.submit(template, max_new_tokens=2)        # -> spill
        assert r.audit[-1]["reason"] == "spill"
        owner.queue_depth = 0
        owner.full = True                           # shedding owner
        r.submit(template, max_new_tokens=2)        # -> spill (retry)
        assert r.audit[-1]["reason"] == "spill"
        owner.full = False
        # failover: kill the replica holding a live assignment
        gid = r.submit(template, max_new_tokens=2, trace_id="aud-1")
        held_by = r.poll(gid)["replica"]
        r.mark_dead(held_by)
        assert r.audit[-1]["reason"] == "failover"
        assert r.audit[-1]["trace_id"] == "aud-1"
        assert r.audit[-1]["attempt"] == 2
        # orphaned: the survivor dies too, draining onto nothing
        survivor = next(n for n in r.alive_names())
        r.submit(template, max_new_tokens=2)
        r.mark_dead(survivor)
        assert any(e["reason"] == "orphaned" and e["chosen"] is None
                   for e in r.audit)
        # counters reconcile with the ring's full history (the ring
        # here is unbounded enough to hold everything)
        assert sum(r.audit_counts.values()) == len(r.audit)
        assert set(r.audit_counts) == set(AUDIT_REASONS)
        # every entry is JSON-able (the cluster trace consumes it)
        json.dumps(list(r.audit))
        # round_robin policy stamps its own reason
        rr = _router([FakeReplica("a"), FakeReplica("b")],
                     policy="round_robin")
        rr.submit([1, 2, 3], max_new_tokens=2)
        assert rr.audit[-1]["reason"] == "round_robin"
        # ... and the exposition carries the per-reason counters
        text = rr.metrics_prometheus()
        assert ('paddle_gateway_route_decisions_total'
                '{reason="round_robin"} 1') in text
        assert ('paddle_gateway_route_decisions_total'
                '{reason="failover"} 0') in text

    def test_audit_ring_bounded(self):
        reps = [FakeReplica("a"), FakeReplica("b")]
        r = _router(reps, policy="least_loaded", audit_ring=4)
        for i in range(10):
            r.submit([1, 2, i], max_new_tokens=2)
        assert len(r.audit) == 4                    # bounded
        assert r.audit_counts["least_loaded"] == 10  # counters keep all
        # the ring holds the MOST RECENT decisions
        assert [e["gid"] for e in r.audit] == \
            [f"req-{i}" for i in range(7, 11)]

    def test_audit_ring_zero_disables_entries_not_counters(self):
        # PADDLE_ROUTER_AUDIT_RING=0 turns the ring off entirely, but
        # the per-reason counters (pinned in /metrics) keep counting
        r = _router([FakeReplica("a"), FakeReplica("b")],
                    policy="least_loaded", audit_ring=0)
        for i in range(5):
            r.submit([1, 2, i], max_new_tokens=2)
        assert len(r.audit) == 0
        assert r.audit_counts["least_loaded"] == 5

    def test_idempotent_repeat_keeps_original_trace_id(self):
        # a retry with the same request_id but a fresh proxy-minted
        # trace id must resolve to the ORIGINAL submission's trace id
        # — that is the id the engine spans and the audit carry
        r = _router([FakeReplica("a"), FakeReplica("b")],
                    policy="least_loaded")
        gid = r.submit([1, 2, 3], max_new_tokens=2,
                       request_id="ridem", trace_id="trace-orig")
        gid2 = r.submit([1, 2, 3], max_new_tokens=2,
                        request_id="ridem", trace_id="trace-retry")
        assert gid2 == gid
        assert r.trace_id_of(gid) == "trace-orig"
        r.release(gid)
        assert r.trace_id_of(gid) is None


# =====================================================================
# engine incremental harvest (the SSE primitive)
# =====================================================================
class TestEngineHarvest:
    def test_tracked_reader_survives_results_cap(self):
        """The documented race this API closes: telemetry_ring=2 caps
        results at 2, but 5 TRACKED requests all stream their full
        outputs to an arbitrarily slow reader."""
        fmt, embed, head = _model()
        eng = _engine(fmt, embed, head, telemetry_ring=2)
        rng = np.random.RandomState(0)
        rids = [eng.submit(rng.randint(1, V, (5,)).astype(np.int32),
                           max_new_tokens=4) for _ in range(5)]
        for rid in rids:
            eng.track(rid)
        eng.run()                          # everything finishes FIRST
        assert len(eng.results) == 2       # the cap did its job
        for rid in rids:                   # ... and nobody lost tokens
            toks, done, state = eng.harvest_new_tokens(rid)
            assert done and state == "finished" and len(toks) == 4
        assert not eng._req_index and not eng._harvest

    def test_incremental_monotone_and_poll(self):
        fmt, embed, head = _model()
        eng = _engine(fmt, embed, head)
        rid = eng.submit(np.arange(1, 9, dtype=np.int32),
                         max_new_tokens=6)
        eng.track(rid)
        assert eng.poll(rid)["state"] == "queued"
        got = []
        deadline = time.monotonic() + WAIT_S
        done = False
        while not done:
            assert time.monotonic() < deadline
            eng.step()
            new, done, state = eng.harvest_new_tokens(rid)
            got.extend(new)
        assert got == [int(t) for t in eng.results[rid]["tokens"]]
        assert eng.poll(rid)["n_tokens"] == 6
        # the cursor is gone: a re-harvest is the unknown-rid error...
        # unless the results dict still holds it (it does here)
        new, done, _ = eng.harvest_new_tokens(rid)
        assert done and new == got         # fresh cursor, full replay

    def test_untracked_evicted_request_raises(self):
        fmt, embed, head = _model()
        eng = _engine(fmt, embed, head, telemetry_ring=2)
        rng = np.random.RandomState(1)
        rids = [eng.submit(rng.randint(1, V, (5,)).astype(np.int32),
                           max_new_tokens=3) for _ in range(4)]
        eng.run()
        assert rids[0] not in eng.results  # evicted by the cap
        with pytest.raises(KeyError):
            eng.harvest_new_tokens(rids[0])


# =====================================================================
# e2e: gateway over >= 2 replicas, real HTTP
# =====================================================================
def _post(port, body, timeout=WAIT_S):
    import http.client
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    c.request("POST", "/v1/completions", json.dumps(body))
    r = c.getresponse()
    data = r.read()
    c.close()
    return r.status, data


def _sse_collect(port, body, timeout=WAIT_S):
    payload = json.dumps(body).encode()
    s = socket.create_connection(("127.0.0.1", port), timeout=timeout)
    s.sendall(b"POST /v1/completions HTTP/1.1\r\nHost: x\r\n"
              b"Content-Length: %d\r\n\r\n%s" % (len(payload), payload))
    buf = b""
    while True:
        chunk = s.recv(65536)
        if not chunk:
            break
        buf += chunk
    s.close()
    toks, reason = [], None
    for ln in buf.partition(b"\r\n\r\n")[2].split(b"\n"):
        ln = ln.strip()
        if not ln.startswith(b"data: ") or ln == b"data: [DONE]":
            continue
        ch = json.loads(ln[6:])["choices"][0]
        toks += ch["tokens"]
        reason = ch["finish_reason"] or reason
    return toks, reason


class TestClusterE2E:
    def test_gateway_completions_match_oracle_json_and_sse(self):
        """Two replicas behind one endpoint: JSON and SSE both produce
        exactly the sequential-decoder tokens — routing is invisible."""
        fmt, embed, head = _model()
        reps = [LocalReplica(f"replica{i}", _engine(fmt, embed, head))
                for i in range(2)]
        gw = Gateway(Router(reps, policy="round_robin",
                            snap_max_age_s=0.0),
                     port=0, hb_s=0.1).start_background()
        try:
            rng = np.random.RandomState(0)
            for _ in range(3):
                prompt = [int(t) for t in rng.randint(1, V, (10,))]
                want = _oracle(fmt, embed, head, prompt, 6)
                st, data = _post(gw.port, {"prompt": prompt,
                                           "max_tokens": 6})
                obj = json.loads(data)
                assert st == 200 and obj["choices"][0]["tokens"] == want
                toks, reason = _sse_collect(
                    gw.port, {"prompt": prompt, "max_tokens": 6,
                              "stream": True})
                assert toks == want and reason == "length"
        finally:
            gw.stop()
            for r in reps:
                r.close()

    def test_zero_retraces_across_router_churn(self):
        """The router is pure host code: after each replica compiled
        its executables once, cluster churn must not trace anything new
        on ANY replica."""
        fmt, embed, head = _model()
        reps = [LocalReplica(f"replica{i}", _engine(fmt, embed, head),
                             threaded=False)
                for i in range(2)]
        router = Router(reps, policy="round_robin", snap_max_age_s=0.0)
        rng = np.random.RandomState(7)

        def drive(n):
            gids = [router.submit(
                [int(t) for t in rng.randint(1, V, (12,))],
                max_new_tokens=5) for _ in range(n)]
            deadline = time.monotonic() + WAIT_S
            done = set()
            while len(done) < len(gids):
                assert time.monotonic() < deadline
                for r in reps:
                    r.pump()
                for g in gids:
                    if g not in done and router.harvest(g)[1]:
                        done.add(g)

        drive(4)                           # warmup: compile everything
        traces = [r.engine.metrics()["traces"] for r in reps]
        drive(8)                           # churn through both replicas
        assert [r.engine.metrics()["traces"] for r in reps] == traces

    def test_kill_replica_mid_stream_token_identical(self):
        """THE failover contract: a replica killed mid-request (step
        hook fires at exactly step 4, while the request is in flight)
        is detected, its stream re-routed, and the client sees the
        byte-identical greedy token sequence with no duplicates."""
        fmt, embed, head = _model()
        hits = {"n": 0}

        def killer(rep):
            hits["n"] += 1
            if hits["n"] == 4:
                rep.kill()

        reps = [LocalReplica(f"replica{i}", _engine(fmt, embed, head),
                             step_hook=killer)
                for i in range(2)]
        router = Router(reps, policy="round_robin", hb_dead_s=0.3,
                        snap_max_age_s=0.0)
        gw = Gateway(router, port=0, hb_s=0.05,
                     poll_s=0.002).start_background()
        try:
            prompt = [int(t) for t in
                      np.random.RandomState(0).randint(1, V, (12,))]
            want = _oracle(fmt, embed, head, prompt, 60)
            toks, reason = _sse_collect(
                gw.port, {"prompt": prompt, "max_tokens": 60,
                          "stream": True})
            assert toks == want, (len(toks), len(want))
            assert reason == "length"
            assert router.failovers_total == 1
            assert len(router.dead) == 1
        finally:
            gw.stop()
            for r in reps:
                r.close()

    def test_failover_deterministic_virtual_clock(self):
        """The same drain->re-submit path with NO real time: unthreaded
        replicas, injected clock, explicit health sweeps — kill the
        owner after 3 harvested tokens, advance the clock past the
        heartbeat threshold, and the request finishes elsewhere with
        exact token parity and exactly-once delivery."""
        fmt, embed, head = _model()
        clock = [0.0]
        reps = [LocalReplica(f"replica{i}", _engine(fmt, embed, head),
                             threaded=False, clock=lambda: clock[0])
                for i in range(2)]
        router = Router(reps, policy="round_robin", hb_dead_s=1.0,
                        snap_max_age_s=0.0, clock=lambda: clock[0])
        prompt = [int(t) for t in
                  np.random.RandomState(3).randint(1, V, (10,))]
        want = _oracle(fmt, embed, head, prompt, 20)
        gid = router.submit(prompt, max_new_tokens=20)
        victim = router._table[gid].replica
        vrep = router.replicas[victim]
        got = []
        deadline = time.monotonic() + WAIT_S
        while len(got) < 3:
            assert time.monotonic() < deadline
            vrep.pump()
            got += router.harvest(gid)[0]
        vrep.kill()
        clock[0] += 2.0                    # heartbeat goes stale
        assert router.check_health() == [victim]
        assert router._table[gid].resubmits == 1
        other = router.replicas[router._table[gid].replica]
        assert other is not vrep
        done = False
        while not done:
            assert time.monotonic() < deadline
            other.pump()
            new, done, state = router.harvest(gid)
            got += new
        assert got == want                 # identical, no dup, no gap
        assert state == "finished"
        assert router.failovers_total == 1

    def test_trace_id_survives_failover_virtual_clock(self):
        """THE trace-context contract, deterministically: one trace id
        threads submit -> victim replica (attempt 1) -> failover ->
        replacement replica (attempt 2), with token parity — the
        engines' request spans join on the id across the kill."""
        fmt, embed, head = _model()
        clock = [0.0]
        reps = [LocalReplica(f"replica{i}", _engine(fmt, embed, head),
                             threaded=False, clock=lambda: clock[0])
                for i in range(2)]
        router = Router(reps, policy="round_robin", hb_dead_s=1.0,
                        snap_max_age_s=0.0, clock=lambda: clock[0])
        prompt = [int(t) for t in
                  np.random.RandomState(3).randint(1, V, (10,))]
        want = _oracle(fmt, embed, head, prompt, 20)
        gid = router.submit(prompt, max_new_tokens=20,
                            trace_id="trace-failover-1")
        assert router.poll(gid)["trace_id"] == "trace-failover-1"
        assert router.poll(gid)["attempt"] == 1
        victim = router._table[gid].replica
        vrep = router.replicas[victim]
        got = []
        deadline = time.monotonic() + WAIT_S
        while len(got) < 3:
            assert time.monotonic() < deadline
            vrep.pump()
            got += router.harvest(gid)[0]
        # the victim engine's live span carries the trace id, attempt 1
        vspan = next(sp for sp in vrep.engine.telemetry._live.values()
                     if sp.trace_id == "trace-failover-1")
        assert vspan.attempt == 1
        vrep.kill()
        clock[0] += 2.0
        assert router.check_health() == [victim]
        assert router.poll(gid)["attempt"] == 2
        other = router.replicas[router._table[gid].replica]
        done = False
        while not done:
            assert time.monotonic() < deadline
            other.pump()
            new, done, _ = router.harvest(gid)
            got += new
        assert got == want
        # the replacement engine's span: SAME trace id, attempt 2
        dump = other.trace_dump()
        span = next(s for s in dump["spans"]
                    if s["trace_id"] == "trace-failover-1")
        assert span["attempt"] == 2 and span["state"] == "finished"
        # the victim's post-mortem dump still shows attempt 1
        vdump = vrep.trace_dump()
        vs = next(s for s in vdump["spans"]
                  if s["trace_id"] == "trace-failover-1")
        assert vs["attempt"] == 1 and vs["state"] != "finished"

    def test_cluster_trace_merged_export(self, tmp_path):
        """The acceptance gate: a kill-mid-stream drill exports ONE
        merged Perfetto trace that validates and contains, for a
        single trace id, the gateway HTTP span, a router decision,
        and engine request spans on TWO replica pids at attempts 1
        and 2 — with zero retraces per replica and greedy parity."""
        from paddle_tpu.inference.telemetry import validate_chrome_trace
        from paddle_tpu.serving_cluster import export_cluster_trace
        fmt, embed, head = _model()
        hits = {"n": 0}

        def killer(rep):
            hits["n"] += 1
            if hits["n"] == 4:
                rep.kill()

        reps = [LocalReplica(f"replica{i}", _engine(fmt, embed, head),
                             step_hook=killer)
                for i in range(2)]
        router = Router(reps, policy="round_robin", hb_dead_s=0.3,
                        snap_max_age_s=0.0)
        gw = Gateway(router, port=0, hb_s=0.05,
                     poll_s=0.002).start_background()
        try:
            prompt = [int(t) for t in
                      np.random.RandomState(0).randint(1, V, (12,))]
            want = _oracle(fmt, embed, head, prompt, 60)
            payload = json.dumps({"prompt": prompt, "max_tokens": 60,
                                  "stream": True}).encode()
            s = socket.create_connection(("127.0.0.1", gw.port),
                                         timeout=WAIT_S)
            s.sendall(b"POST /v1/completions HTTP/1.1\r\nHost: x\r\n"
                      b"X-Request-Id: trace-drill-1\r\n"
                      b"Content-Length: %d\r\n\r\n%s"
                      % (len(payload), payload))
            buf = b""
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                buf += chunk
            s.close()
            toks = []
            for ln in buf.partition(b"\r\n\r\n")[2].split(b"\n"):
                ln = ln.strip()
                if not ln.startswith(b"data: ") or ln == b"data: [DONE]":
                    continue
                toks += json.loads(ln[6:])["choices"][0]["tokens"]
            assert toks == want               # greedy parity through kill
            assert router.failovers_total == 1

            path = str(tmp_path / "cluster_trace.json")
            export_cluster_trace(gw, path)
            doc = validate_chrome_trace(path)
            evs = doc["traceEvents"]
            tid = "trace-drill-1"
            http_spans = [e for e in evs
                          if e.get("pid") == 0 and e.get("ph") == "X"
                          and (e.get("args") or {}).get("trace_id") == tid
                          and e["name"].startswith("POST")]
            decisions = [e for e in evs
                         if e.get("pid") == 0 and e.get("ph") == "X"
                         and str(e["name"]).startswith("decision")
                         and e["args"].get("trace_id") == tid]
            rep_spans = [e for e in evs
                         if e.get("pid", 0) > 0 and e.get("ph") == "X"
                         and (e.get("args") or {}).get("trace_id") == tid]
            assert http_spans, "gateway HTTP span missing"
            assert decisions, "router decision event missing"
            attempts = sorted(e["args"]["attempt"] for e in rep_spans)
            pids = {e["pid"] for e in rep_spans}
            assert attempts[0] == 1 and attempts[-1] == 2, attempts
            assert len(pids) == 2, "failover did not span two replicas"
            assert {e["args"]["reason"] for e in decisions} >= \
                {"failover"}
            # every event ts is non-negative (the anchor rebase holds)
            assert all(e.get("ts", 0) >= 0 for e in evs)
        finally:
            gw.stop()
            for r in reps:
                r.close()

    def test_orphaned_when_no_replica_left(self):
        fmt, embed, head = _model()
        rep = LocalReplica("only", _engine(fmt, embed, head),
                           threaded=False)
        router = Router([rep], policy="round_robin", snap_max_age_s=0.0)
        gid = router.submit([1, 2, 3, 4, 5], max_new_tokens=8)
        rep.kill()
        router.mark_dead("only")
        assert router._table[gid].orphaned
        with pytest.raises(NoReplicaError):
            router.harvest(gid)


# =====================================================================
# disaggregated prefill/decode serving (role-specialized replicas)
# =====================================================================
def _drive_cluster(router, reps, gids):
    """Pump every unthreaded replica and harvest every stream until all
    finish (bounded). Returns {gid: [tokens]}."""
    outs = {g: [] for g in gids}
    done = {g: False for g in gids}
    deadline = time.monotonic() + WAIT_S
    while not all(done.values()):
        assert time.monotonic() < deadline, "disagg drive stalled"
        for r in reps:
            r.pump()
        for g in gids:
            if not done[g]:
                new, d, _ = router.harvest(g, len(outs[g]))
                outs[g].extend(new)
                done[g] = d
    return outs


class TestDisaggServing:
    """Role-split cluster (prefill workers hold prompt-complete
    sessions; the router ships their KV to decode workers) vs the SAME
    arrivals on a mixed single-engine baseline: token parity, zero
    prompt recompute, streamed mid-prefill handoff, backpressure
    bounce-back on a tight decode pool, zero retraces after warmup."""

    def _prompts(self, seed, n):
        rng = np.random.RandomState(seed)
        return [[int(t) for t in rng.randint(1, V, (int(ln),))]
                for ln in rng.randint(6, 15, (n,))]

    def _mixed_baseline(self, fmt, embed, head, prompts, max_new=6,
                        **ekw):
        eng = _engine(fmt, embed, head, num_slots=4,
                      prefix_cache_blocks=32, **ekw)
        rep = LocalReplica("m0", eng, threaded=False)
        rt = Router([rep], snap_max_age_s=0.0)
        paddle.seed(1234)                 # per-request sampler seeds
        gids = [rt.submit(p, max_new_tokens=max_new) for p in prompts]
        outs = _drive_cluster(rt, [rep], gids)
        return eng, [outs[g] for g in gids]

    def _disagg_cluster(self, fmt, embed, head, handoff_blocks=None,
                        dc_kw=None, **ekw):
        eng_p = _engine(fmt, embed, head, role="prefill", num_slots=2,
                        prefix_cache_blocks=32, **ekw)
        dkw = dict(num_slots=4, prefix_cache_blocks=32, **ekw)
        dkw.update(dc_kw or {})
        eng_d = _engine(fmt, embed, head, role="decode", **dkw)
        reps = [LocalReplica("pf0", eng_p, threaded=False),
                LocalReplica("dc0", eng_d, threaded=False)]
        rt = Router(reps, snap_max_age_s=0.0,
                    handoff_blocks=handoff_blocks)
        return eng_p, eng_d, reps, rt

    def test_greedy_parity_and_zero_recompute(self):
        fmt, embed, head = _model()
        prompts = self._prompts(21, 6)
        eng_m, want = self._mixed_baseline(fmt, embed, head, prompts)
        eng_p, eng_d, reps, rt = self._disagg_cluster(fmt, embed, head)
        paddle.seed(1234)
        gids = [rt.submit(p, max_new_tokens=6) for p in prompts]
        outs = _drive_cluster(rt, reps, gids)
        assert [outs[g] for g in gids] == want
        # every session prefilled on pf0, decoded on dc0 — one handoff
        # each, no failover/replay anywhere
        assert rt.handoffs_total == len(prompts)
        assert rt.failovers_total == 0
        assert rt.migration_aborts_total == 0
        # ZERO prompt recompute: the decode engine never ran a prefill
        # (its sessions all arrived prompt-complete over the KV wire),
        # and the prefill side computed exactly what the mixed
        # baseline did for the same arrivals
        mp, md = eng_p.metrics(), eng_d.metrics()
        assert md["prefill_tokens_computed"] == 0
        assert mp["prefill_tokens_computed"] == \
            eng_m.metrics()["prefill_tokens_computed"]
        # the transfer counters reconcile across the wire
        assert mp["kv_blocks_shipped"] == md["kv_blocks_adopted"] > 0

    def test_sampled_parity_across_handoff(self):
        """Sampler state (per-request seed + counter) rides the export:
        a sampled stream is identical whether it decodes in place or on
        the other side of a KV handoff."""
        fmt, embed, head = _model()
        prompts = self._prompts(22, 4)
        samp = dict(do_sample=True, top_k=12, top_p=0.9,
                    temperature=0.8)
        eng_m, want = self._mixed_baseline(fmt, embed, head, prompts,
                                           **samp)
        eng_p, eng_d, reps, rt = self._disagg_cluster(fmt, embed, head,
                                                      **samp)
        paddle.seed(1234)                 # same seed draw order
        gids = [rt.submit(p, max_new_tokens=6) for p in prompts]
        outs = _drive_cluster(rt, reps, gids)
        assert [outs[g] for g in gids] == want
        assert rt.handoffs_total == len(prompts)
        assert eng_d.metrics()["prefill_tokens_computed"] == 0

    def test_streamed_handoff_ships_mid_prefill(self):
        """handoff_blocks=1: committed prompt blocks stream to the
        decode target WHILE the prefill tail is still running — the
        shipped counter moves before the request produces a token."""
        fmt, embed, head = _model()
        long_prompt = [int(t) for t in
                       np.random.RandomState(9).randint(1, V, (40,))]
        eng_m, want = self._mixed_baseline(fmt, embed, head,
                                           [long_prompt], max_new=6)
        eng_p, eng_d, reps, rt = self._disagg_cluster(
            fmt, embed, head, handoff_blocks=1)
        gid = rt.submit(long_prompt, max_new_tokens=6)
        got, shipped_mid = [], 0
        deadline = time.monotonic() + WAIT_S
        done = False
        while not done:
            assert time.monotonic() < deadline
            for r in reps:
                r.pump()
            new, done, _ = rt.harvest(gid, len(got))
            got.extend(new)
            if not got and not done:
                # still prefilling (prefill_cap=8 chunks a 40-token
                # prompt): record the transfer progress so far
                shipped_mid = max(shipped_mid,
                                  eng_p.metrics()["kv_blocks_shipped"])
        assert shipped_mid > 0, \
            "no KV block left the prefill worker before the first token"
        assert got == want[0]
        assert rt.handoffs_total == 1 and rt.failovers_total == 0
        assert eng_d.metrics()["prefill_tokens_computed"] == 0
        # staged prefix + final handoff moved every block exactly once
        assert eng_p.metrics()["kv_blocks_shipped"] == \
            eng_d.metrics()["kv_blocks_adopted"]

    def test_tight_decode_pool_backpressure_then_parity(self):
        """A decode pool too small for the offered load: handoffs
        bounce back ('held' = backpressure, not failure) and retry as
        sessions retire — everything still finishes with exact parity
        and zero drops/replays."""
        fmt, embed, head = _model()
        prompts = self._prompts(23, 6)
        eng_m, want = self._mixed_baseline(fmt, embed, head, prompts)
        # decode: 2 slots, pool sized to ~2 resident sessions
        eng_p, eng_d, reps, rt = self._disagg_cluster(
            fmt, embed, head, dc_kw=dict(num_slots=2,
                                         prefix_cache_blocks=8))
        paddle.seed(1234)
        gids = [rt.submit(p, max_new_tokens=6) for p in prompts]
        outs = _drive_cluster(rt, reps, gids)
        assert [outs[g] for g in gids] == want
        assert rt.handoffs_total == len(prompts)
        assert rt.failovers_total == 0
        assert eng_d.metrics()["prefill_tokens_computed"] == 0

    def test_zero_retraces_after_warmup_both_roles(self):
        """After one warmup wave compiled both roles' executables
        (prefill chunks + export on pf0, import + decode on dc0),
        steady-state disagg traffic traces NOTHING new on either."""
        fmt, embed, head = _model()
        eng_p, eng_d, reps, rt = self._disagg_cluster(fmt, embed, head)
        rng = np.random.RandomState(31)

        def wave(n):
            gids = [rt.submit([int(t) for t in rng.randint(1, V, (10,))],
                              max_new_tokens=5) for _ in range(n)]
            _drive_cluster(rt, reps, gids)

        wave(3)                            # warmup: compile everything
        traces = [eng_p.metrics()["traces"], eng_d.metrics()["traces"]]
        wave(6)
        assert [eng_p.metrics()["traces"],
                eng_d.metrics()["traces"]] == traces
        assert rt.handoffs_total == 9

    def test_prefill_drain_routes_by_remaining_work(self):
        """THE drain-role contract: draining a PREFILL replica sends a
        session that still owes prefill work to another prefill-capable
        replica (a decode-only target would starve it), while a
        prompt-complete held session drains to the decode pool."""
        fmt, embed, head = _model()
        kw = dict(prefix_cache_blocks=32)
        reps = [LocalReplica("pf0", _engine(fmt, embed, head,
                                            role="prefill", **kw),
                             threaded=False),
                LocalReplica("pf1", _engine(fmt, embed, head,
                                            role="prefill", **kw),
                             threaded=False),
                LocalReplica("dc0", _engine(fmt, embed, head,
                                            role="decode", **kw),
                             threaded=False)]
        rt = Router(reps, snap_max_age_s=0.0)
        prompt = [int(t) for t in
                  np.random.RandomState(4).randint(1, V, (12,))]
        want = _oracle(fmt, embed, head, prompt, 8)
        gid = rt.submit(prompt, max_new_tokens=8)
        first = rt._table[gid].replica
        assert first in ("pf0", "pf1")     # placement is role-aware too
        # (a) un-prefilled (queued) session: drain must land it on the
        # OTHER prefill replica, never the decode-only one
        summary = rt.remove_replica(first, migrate=True)
        assert summary["migrated"] == 1
        second = rt._table[gid].replica
        assert second == ({"pf0", "pf1"} - {first}).pop()
        # (b) run the prompt to completion on the prefill engine: it
        # HOLDS the session; draining now must land it decode-side
        srep = rt.replicas[second]
        deadline = time.monotonic() + WAIT_S
        while srep.engine.has_work:
            assert time.monotonic() < deadline
            srep.pump()
        summary = rt.remove_replica(second, migrate=True)
        assert summary["migrated"] == 1
        assert rt._table[gid].replica == "dc0"
        got, done = [], False
        while not done:
            assert time.monotonic() < deadline
            reps[2].pump()
            new, done, state = rt.harvest(gid, len(got))
            got.extend(new)
        assert got == want and state == "finished"
        assert rt.failovers_total == 0     # drains, not replays


# =====================================================================
# role-aware autoscaler: per-pool watermarks
# =====================================================================
class TestRoleAutoscaler:
    def _scaler(self, router=None, spawn=None, **kw):
        from paddle_tpu.serving_cluster.autoscale import Autoscaler
        kw.setdefault("role_aware", True)
        kw.setdefault("pf_queue_high", 4.0)
        kw.setdefault("pf_queue_low", 1.0)
        kw.setdefault("dc_kv_free_low", 0.2)
        kw.setdefault("dc_sessions_high", 0.8)
        kw.setdefault("dc_sessions_low", 0.3)
        kw.setdefault("max_replicas", 8)
        return Autoscaler(router if router is not None else Router([]),
                          spawn or (lambda *a: None), **kw)

    def test_decide_roles_truth_table(self):
        """The per-pool watermark logic, pinned case by case: the two
        pools scale on DIFFERENT signal families, scale-up beats
        scale-down, prefill backlog beats decode pressure, and a pool
        with no snapshots contributes no verdict."""
        a = self._scaler()

        def sig(pq=2.0, kv=0.5, sess=0.5, npf=1, ndc=1):
            return {"prefill_replicas": npf, "decode_replicas": ndc,
                    "prefill_snapshots": npf, "decode_snapshots": ndc,
                    "prefill_queue_mean": pq,
                    "decode_kv_free_frac": kv,
                    "decode_sessions_frac": sess}

        cases = [
            (sig(), None),                            # mid-band: hold
            (sig(pq=5.0), ("up", "prefill")),         # prompt backlog
            (sig(kv=0.1), ("up", "decode")),          # kv starvation
            (sig(sess=0.9), ("up", "decode")),        # slots resident
            # both pools want up: the user-visible TTFT backlog wins
            (sig(pq=5.0, kv=0.1), ("up", "prefill")),
            (sig(pq=0.5), ("down", "prefill")),       # idle prefill
            (sig(sess=0.2), ("down", "decode")),      # idle decode
            # decode-down needs BOTH idle sessions and kv headroom
            (sig(sess=0.2, kv=0.1), ("up", "decode")),
            # up beats down across pools
            (sig(pq=5.0, sess=0.2), ("up", "prefill")),
            (sig(sess=0.9, pq=0.5), ("up", "decode")),
            # prefill-down is evaluated before decode-down
            (sig(pq=0.5, sess=0.2), ("down", "prefill")),
            # a pool with no snapshot data contributes nothing
            (sig(pq=9.0, npf=0), None),
            (sig(kv=0.0, sess=1.0, ndc=0), None),
            (sig(pq=0.0, npf=0, ndc=0), None),
        ]
        for s, want in cases:
            assert a.decide_roles(s) == want, (s, want)

    def test_tick_scales_pools_independently(self):
        """e2e over stub replicas: a hot prefill queue spawns into the
        prefill pool (spawn hook receives the role), an idle prefill
        pool drains back — the decode pool is untouched either way."""
        pf = FakeReplica("pf0", queue_depth=9)
        dc = FakeReplica("dc0")
        pf.role, dc.role = "prefill", "decode"
        clock = [0.0]
        spawned = []

        def spawn(name, role):
            rep = FakeReplica(name)
            rep.role = role
            spawned.append((name, role))
            return rep

        rt = _router([pf, dc])
        a = self._scaler(rt, spawn, hysteresis=1, cooldown_s=0.0,
                         clock=lambda: clock[0])
        assert a.tick() == "up:prefill"
        assert spawned and spawned[-1][1] == "prefill"
        assert sorted(rt.roles.values()) == \
            ["decode", "prefill", "prefill"]
        # queues drain: the 2-replica prefill pool contracts; the
        # decode pool (1 replica) is never drained below one
        pf.queue_depth = 0
        clock[0] += 1.0
        assert a.tick() == "down:prefill"
        names = set(rt.alive_names())
        assert "dc0" in names
        assert sum(1 for n in names
                   if rt.roles.get(n) == "prefill") == 1
        # ... and the now-single prefill pool refuses to drain to zero
        clock[0] += 1.0
        assert a.tick() is None

    def test_pool_floor_repair_bypasses_hysteresis(self):
        """An empty pool (operator drain, replica death) is repaired on
        the NEXT tick regardless of hysteresis/cooldown — an empty
        prefill pool strands every new prompt, an empty decode pool
        strands every prefilled session."""
        pf = FakeReplica("pf0")
        pf.role = "prefill"
        spawned = []

        def spawn(name, role):
            rep = FakeReplica(name)
            rep.role = role
            spawned.append((name, role))
            return rep

        rt = _router([pf])
        a = self._scaler(rt, spawn, hysteresis=99, cooldown_s=1e9)
        assert a.tick() == "up:decode"     # decode pool was empty
        assert spawned[-1][1] == "decode"
        # both pools populated now: the huge hysteresis holds
        assert a.tick() is None


# =====================================================================
# RpcReplica: the same interface across a process boundary
# =====================================================================
class TestRpcReplica:
    def test_rpc_replica_parity_and_backpressure(self):
        from paddle_tpu.core.native import load_native
        if load_native() is None:
            pytest.skip("native runtime unavailable")
        from paddle_tpu.distributed import rpc
        from paddle_tpu.serving_cluster import RpcReplica, serve_engine

        fmt, embed, head = _model()
        rpc.init_rpc("cluster_worker0", rank=0, world_size=1,
                     master_endpoint="127.0.0.1:0")
        worker = None
        try:
            # world_size=1: the "remote" worker is this process's own
            # rpc agent — the full transport path (token preamble,
            # pickling, exception channel) without a subprocess
            worker = serve_engine(
                _engine(fmt, embed, head, max_pending=1),
                name="replica-rpc", threaded=False)
            rep = RpcReplica("cluster_worker0", ping_timeout=5)
            assert rep.alive
            prompt = [int(t) for t in
                      np.random.RandomState(5).randint(1, V, (10,))]
            want = _oracle(fmt, embed, head, prompt, 6)
            rid = rep.submit(prompt, max_new_tokens=6,
                             trace_id="trace-rpc-1", attempt=2)
            snap = rep.snapshot()
            assert snap["schema_version"] == SNAPSHOT_SCHEMA_VERSION
            assert snap["replica"] == "replica-rpc"
            # snapshot v2: the slo block crosses the wire too
            assert "slo" in snap and "objectives" in snap["slo"]
            # trace context PROPAGATES over rpc: the worker engine's
            # span carries the id/attempt the client submitted with
            dump = rep.trace_dump()
            assert dump["replica"] == "replica-rpc"
            sp = next(s for s in dump["spans"]
                      if s["trace_id"] == "trace-rpc-1")
            assert sp["attempt"] == 2
            # AdmissionFull crosses the rpc boundary AS AdmissionFull
            # (backpressure stays backpressure, never a transport error)
            long = [1] * 20
            with pytest.raises(AdmissionFull):
                for _ in range(5):
                    rep.submit(long, max_new_tokens=8)
            got, done = [], False
            deadline = time.monotonic() + WAIT_S
            while not done:
                assert time.monotonic() < deadline
                worker.pump()
                new, done, state = rep.harvest(rid)
                got += new
            assert got == want
            # a dead served replica surfaces as ReplicaError through
            # the live transport — the router's failover trigger
            worker.kill()
            with pytest.raises(ReplicaError):
                rep.submit(prompt, max_new_tokens=2)
        finally:
            rpc.shutdown()


# =====================================================================
# supervised worker gang (python -m paddle_tpu.serving_cluster --workers)
# =====================================================================
@pytest.mark.slow
def test_supervised_worker_gang_e2e(tmp_path):
    """The CLI's --workers recipe end to end: the supervisor spawns a
    worker process, rendezvouses it over rpc, fronts it with an
    RpcReplica, and serves a completion through the gateway — the
    promoted replacement for hand-rolled init_rpc glue."""
    import re
    import signal
    import subprocess
    import sys
    import urllib.request

    from paddle_tpu.core.native import load_native
    if load_native() is None:
        pytest.skip("native runtime unavailable")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.serving_cluster",
         "--workers", "1", "--port", "0",
         "--log-dir", str(tmp_path / "log")],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    try:
        port = None
        deadline = time.monotonic() + WAIT_S
        for line in p.stdout:
            m = re.search(r"http://127\.0\.0\.1:(\d+)", line)
            if m:
                port = int(m.group(1))
                break
            assert time.monotonic() < deadline, "supervisor never ready"
        assert port is not None
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/completions",
            data=json.dumps({"prompt": [5, 9, 2, 41],
                             "max_tokens": 8}).encode(),
            headers={"Content-Type": "application/json"})
        doc = json.load(urllib.request.urlopen(req, timeout=WAIT_S))
        toks = doc["choices"][0]["tokens"]
        assert len(toks) == 8
        # the worker engine serves the SAME weights as an in-process
        # replica would — the tokens match the local oracle (the CLI's
        # toy model: E,H,FF,L,V = 64,4,128,2,256, seed 0)
        paddle.seed(0)
        embed = Embedding(256, 64)
        fmt = FusedMultiTransformer(64, 4, 128, num_layers=2,
                                    normalize_before=True)
        head = Linear(64, 256, bias_attr=False)
        fmt.eval()
        dec = FusedDecoder(fmt, embed, head, max_seq_len=256)
        out = dec.generate(
            paddle.to_tensor(np.array([[5, 9, 2, 41]], np.int32)),
            max_new_tokens=8)
        want = [int(t) for t in np.asarray(out._data)[0, 4:]]
        assert toks == want
    finally:
        p.send_signal(signal.SIGINT)
        try:
            rc = p.wait(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
            rc = p.wait()
    assert rc == 0


# =====================================================================
# structural pins
# =====================================================================
def test_http_surface_pinned(capsys):
    """tools/check_http_surface.py as a tier-1 test: every endpoint's
    field set and every error-status row asserted over live HTTP."""
    spec = importlib.util.spec_from_file_location(
        "check_http_surface",
        os.path.join(REPO_ROOT, "tools", "check_http_surface.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rc = mod.main()
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "ok" in out


def test_gateway_env_registry_complete():
    """Every PADDLE_GATEWAY_*/PADDLE_ROUTER_*/PADDLE_SLO_*/
    PADDLE_AUTOSCALE_*/PADDLE_QOS_*/PADDLE_TENANT_*/PADDLE_ROLE*/
    PADDLE_SERVING_MESH_* env the serving stack reads is registered in
    testing.GW_ENV_VARS (the conftest leak guard's list), and the
    registry carries no dead entries — same structural discipline as
    FI_ENV_VARS/FR_ENV_VARS. The SLO knobs live in
    inference/telemetry.py (SloPolicy.from_env) and the QoS shares +
    engine role in inference/serving.py, so both files join the scan;
    the autoscale knobs live in serving_cluster/autoscale.py (already
    in the package scan); the RPC client timeouts are read by
    serving_cluster/replica.py (RpcReplica), also in the package scan;
    the serving-mesh knobs are read by parallel/__init__.py
    (init_serving_mesh) and inference/generation.py (the weight-shard
    placement), so those two join the scan as well."""
    import re

    import paddle_tpu.inference.generation as gen_mod
    import paddle_tpu.inference.serving as serving_mod
    import paddle_tpu.inference.telemetry as tele_mod
    import paddle_tpu.parallel as par_mod
    import paddle_tpu.serving_cluster as sc
    from paddle_tpu.testing import GW_ENV_VARS
    pkg = os.path.dirname(os.path.abspath(sc.__file__))
    paths = [os.path.join(pkg, fn) for fn in os.listdir(pkg)
             if fn.endswith(".py")]
    paths.append(os.path.abspath(tele_mod.__file__))
    paths.append(os.path.abspath(serving_mod.__file__))
    paths.append(os.path.abspath(par_mod.__file__))
    paths.append(os.path.abspath(gen_mod.__file__))
    found = set()
    for path in paths:
        with open(path) as f:
            found |= set(re.findall(
                r"PADDLE_(?:(?:GATEWAY|ROUTER|SLO|AUTOSCALE|QOS"
                r"|TENANT|ROLE|RPC|SERVING_MESH)_[A-Z_0-9]+|ROLE\b)",
                f.read()))
    # the rpc-replica probe knob lives in replica.py; bench/tests may
    # reference more — the guard list must cover everything READ here
    assert found <= set(GW_ENV_VARS), (
        f"unregistered gateway env vars: {found - set(GW_ENV_VARS)} — "
        "add them to paddle_tpu.testing.GW_ENV_VARS")
    assert set(GW_ENV_VARS) <= found, (
        f"dead GW_ENV_VARS entries: {set(GW_ENV_VARS) - found}")
    # the SLO registry constant in telemetry.py must agree with the
    # guard list (one source of truth for the knob names)
    from paddle_tpu.inference.telemetry import SLO_ENV_VARS
    assert set(SLO_ENV_VARS) <= set(GW_ENV_VARS)


# =====================================================================
# gray-failure defense: health scoring, circuit breaker, hedging
# =====================================================================
class RecordingReplica(FakeReplica):
    """FakeReplica + scripted harvests, recorded releases, a snapshot
    failure switch (the flake/breaker lever), and a ``do_sample`` flag
    in the snapshot (the hedge safety gate reads it off the wire)."""

    def __init__(self, name, script=None, do_sample=False, **kw):
        super().__init__(name, **kw)
        self.script = list(script or [])
        self.do_sample = do_sample
        self.fail_snap = False
        self.released = []

    def snapshot(self):
        if self.fail_snap:
            raise ReplicaError(f"{self.name}: injected snapshot flake")
        snap = super().snapshot()
        snap["do_sample"] = self.do_sample
        return snap

    def harvest(self, rid):
        if self.script:
            return self.script.pop(0)
        return [], False, "running"

    def release(self, rid):
        self.released.append(rid)


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class TestGrayFailureDefense:
    def test_snapshot_flake_keeps_replica_alive(self):
        """Contract: ONE failed snapshot drops the snapshot (the
        replica scores worst until it answers again) but must NOT mark
        the replica dead — and one flake alone must not open the
        breaker either."""
        a, b = RecordingReplica("a"), RecordingReplica("b")
        r = _router([a, b], policy="least_loaded", hedge_quantile=0)
        r.refresh(force=True)
        assert r._snap("a") is not None
        a.fail_snap = True
        r.refresh(force=True)
        assert "a" in r.alive_names()
        assert r._snap("a") is None
        assert r.breaker_state("a") == "closed"
        a.fail_snap = False
        r.refresh(force=True)
        assert r._snap("a") is not None

    def test_raced_death_placement_runs_failover(self):
        """Deterministic replay of the submit/mark_dead race: the
        replica is declared dead AFTER its engine accepted the request
        but BEFORE the router's bookkeeping wrote the placement.
        mark_dead's drain skips the still-pending assignment (replica
        is None), so submit() itself must detect the raced death and
        run the failover — the request may not strand on the corpse."""
        class DiesOnSubmit(RecordingReplica):
            router = None

            def submit(self, prompt, **kw):
                rid = super().submit(prompt, **kw)
                self.router.mark_dead(self.name)
                return rid

        a = DiesOnSubmit("a")
        b = RecordingReplica("b", queue_depth=5,
                             script=[([3, 4], True, "finished")])
        r = _router([a, b], policy="least_loaded", hedge_quantile=0)
        a.router = r
        gid = r.submit([1, 2])
        assert r.poll(gid)["replica"] == "b"
        assert r.failovers_total == 1
        assert "a" in r.dead
        toks, done, _ = r.harvest(gid)
        assert toks == [3, 4] and done
        # exactly one engine-side submission landed on each replica:
        # the corpse's accepted request was replayed once, not re-driven
        assert len(a.submitted) == 1 and len(b.submitted) == 1

    def test_breaker_opens_sheds_and_recovers(self):
        """closed -> open on accumulated snapshot errors (replica stays
        ALIVE), open sheds from placement, cooldown -> half_open admits
        exactly breaker_probes probe placements, and a healthy probe
        first-token closes the breaker — no operator action anywhere."""
        clk = _Clock()
        a = RecordingReplica("a", script=[([7], True, "finished")])
        b = RecordingReplica("b", queue_depth=5)
        r = _router([a, b], policy="least_loaded", clock=clk,
                    breaker_errs=2, breaker_cooldown_s=5.0,
                    breaker_probes=1, hedge_quantile=0)
        a.fail_snap = True
        r.refresh(force=True)
        clk.t += 1.0
        r.refresh(force=True)
        assert r.breaker_state("a") == "open"
        assert "a" in r.alive_names()          # shed, NOT dead
        a.fail_snap = False
        # placement avoids the open breaker though a is less loaded
        gid = r.submit([1, 2, 3])
        assert r.poll(gid)["replica"] == "b"
        # cooldown elapses -> half_open admits ONE probe placement
        clk.t += 10.0
        gid2 = r.submit([4, 5, 6])
        assert r.poll(gid2)["replica"] == "a"
        assert r.breaker_state("a") == "half_open"
        # with the probe outstanding further placements stay off a
        gid3 = r.submit([7, 8, 9])
        assert r.poll(gid3)["replica"] == "b"
        # the probe's first token closes the breaker
        clk.t += 0.01
        toks, done, _ = r.harvest(gid2)
        assert toks == [7] and done
        assert r.breaker_state("a") == "closed"
        assert r.breaker_transitions == {"open": 1, "half_open": 1,
                                         "closed": 1}

    def test_health_verdicts_are_median_relative(self):
        """A replica whose latency signal is a breaker_ratio outlier
        against the cluster median reads degraded, and check_health
        opens its breaker (shed while still alive and heartbeating)."""
        reps = [RecordingReplica(n) for n in ("a", "b", "c")]
        r = _router(reps, hedge_quantile=0)
        r.refresh(force=True)
        with r._lock:
            for _ in range(3):
                r._observe_ttft("a", 0.01)
                r._observe_ttft("b", 0.012)
                r._observe_ttft("c", 0.4)      # ~33x median: degraded
        st = r.health_status()
        assert st["a"]["verdict"] == "healthy"
        assert st["c"]["verdict"] == "degraded"
        assert r.check_health() == []          # nobody DIES
        assert r.breaker_state("c") == "open"
        assert "c" in r.alive_names()

    def _hedge_router(self, a, b, clk, **kw):
        kw.setdefault("policy", "least_loaded")
        kw.setdefault("hedge_quantile", 95)
        kw.setdefault("hedge_margin", 1.0)
        kw.setdefault("hedge_min_s", 0.001)
        r = _router([a, b], clock=clk, **kw)
        for _ in range(8):                     # cluster TTFT history
            r.hist_ttft.observe(0.001)
        return r

    def test_hedge_wins_and_loser_is_released(self):
        """A greedy request whose owner is silent past the cluster's
        own p95 TTFT is speculatively re-submitted; the hedge leg's
        first token wins, the original leg is aborted through the
        normal release path, and its tokens never reach the stream."""
        clk = _Clock()
        a = RecordingReplica("a")              # silent gray owner
        b = RecordingReplica("b", queue_depth=5,
                             script=[([5, 6], True, "finished")])
        r = self._hedge_router(a, b, clk)
        gid = r.submit([1, 2, 3])
        assert r.poll(gid)["replica"] == "a"
        toks, done, _ = r.harvest(gid)         # not overdue yet
        assert toks == [] and not done and r.hedges_total == 0
        clk.t += 1.0                           # way past p95 * margin
        r.harvest(gid)                         # arms the hedge
        assert r.hedges_total == 1
        rid_a = a.submitted[0][0]
        toks, done, _ = r.harvest(gid)         # hedge leg polls + wins
        assert toks == [5, 6] and done
        assert r.hedge_wins_total == 1
        assert rid_a in a.released             # loser leg aborted
        assert r.audit_counts["hedge"] == 1
        assert r.poll(gid)["resubmits"] == 1

    def test_hedge_loses_when_owner_answers_first(self):
        """The owner producing its first token makes the hedge leg the
        loser: released immediately, zero hedge wins, and the stream is
        exactly the owner's (no duplicate tokens)."""
        clk = _Clock()
        a = RecordingReplica("a", script=[([], False, "running"),
                                          ([], False, "running"),
                                          ([9], True, "finished")])
        b = RecordingReplica("b", queue_depth=5)   # hedge target, silent
        r = self._hedge_router(a, b, clk)
        gid = r.submit([1, 2, 3])
        r.harvest(gid)
        clk.t += 1.0
        r.harvest(gid)                         # arms the hedge -> b
        assert r.hedges_total == 1
        rid_b = b.submitted[0][0]
        toks, done, _ = r.harvest(gid)         # owner answers
        assert toks == [9] and done
        assert r.hedge_wins_total == 0
        assert rid_b in b.released             # loser leg aborted
        assert b.released.count(rid_b) == 1

    def test_sampled_requests_never_hedge(self):
        """Sampling re-draws the per-request seed on each engine
        submit, so two legs would diverge and the delivered stream
        would depend on the race — the gate reads do_sample off the v6
        snapshot and refuses."""
        clk = _Clock()
        a = RecordingReplica("a", do_sample=True)
        b = RecordingReplica("b", queue_depth=5, do_sample=True)
        r = self._hedge_router(a, b, clk)
        gid = r.submit([1, 2, 3])
        clk.t += 5.0
        r.harvest(gid)
        r.harvest(gid)
        assert r.hedges_total == 0

    def test_hedge_respects_retry_budget(self):
        """An empty cluster-wide retry budget blocks the speculative
        hedge (and counts the refusal); death failovers still proceed
        — they are the stream's only copy."""
        clk = _Clock()
        a = RecordingReplica("a")
        b = RecordingReplica("b", queue_depth=5)
        r = self._hedge_router(a, b, clk, retry_rate=0.0,
                               retry_burst=0)
        gid = r.submit([1, 2, 3])
        clk.t += 5.0
        r.harvest(gid)
        r.harvest(gid)
        assert r.hedges_total == 0
        assert r.retry_budget_exhausted_total >= 1

    def test_hedged_away_probe_reopens_breaker(self):
        """A half-open breaker PROBE that gets hedged away before its
        first token IS the probe verdict: the loser observation
        carries the probe gid, the outlier pending age re-opens the
        breaker, and the probe slot is freed — without this, the
        vanished probe wedges the breaker half-open forever."""
        clk = _Clock()
        a = RecordingReplica("a")              # silent owner
        b = RecordingReplica("b", queue_depth=5,
                             script=[([5], False, "running")])
        r = self._hedge_router(a, b, clk, breaker_errs=2,
                               breaker_cooldown_s=5.0,
                               breaker_probes=1)
        with r._lock:
            for _ in range(3):                 # b's healthy signal
                r._observe_ttft("b", 0.001)
        a.fail_snap = True
        r.refresh(force=True)
        clk.t += 1.0
        r.refresh(force=True)
        assert r.breaker_state("a") == "open"
        a.fail_snap = False
        clk.t += 10.0                          # cooldown elapses
        gid = r.submit([1, 2, 3])              # the probe placement
        assert r.poll(gid)["replica"] == "a"
        assert r.breaker_state("a") == "half_open"
        rid_a = a.submitted[0][0]
        clk.t += 1.0
        r.harvest(gid)                         # overdue: hedge -> b
        assert r.hedges_total == 1
        clk.t += 0.001
        toks, done, _ = r.harvest(gid)         # hedge wins, a loses
        assert toks == [5]
        assert r.hedge_wins_total == 1
        assert rid_a in a.released             # probe leg aborted
        assert r.breaker_state("a") == "open"  # probe verdict: failed

    def test_released_probe_frees_the_probe_slot(self):
        """A probe released before any first token must not occupy
        the half-open breaker's probe slot forever: _breaker_admits
        prunes gids that no longer live on the replica, so the next
        placement can probe again."""
        clk = _Clock()
        a = RecordingReplica("a")
        b = RecordingReplica("b", queue_depth=5)
        r = _router([a, b], policy="least_loaded", clock=clk,
                    breaker_errs=2, breaker_cooldown_s=5.0,
                    breaker_probes=1, hedge_quantile=0)
        a.fail_snap = True
        r.refresh(force=True)
        clk.t += 1.0
        r.refresh(force=True)
        a.fail_snap = False
        clk.t += 10.0
        gid = r.submit([1, 2, 3])
        assert r.poll(gid)["replica"] == "a"
        assert r.breaker_state("a") == "half_open"
        r.release(gid)                         # client went away
        gid2 = r.submit([4, 5, 6])             # slot freed: probe again
        assert r.poll(gid2)["replica"] == "a"
        assert r.breaker_state("a") == "half_open"
