"""Serving telemetry subsystem (ISSUE 8): request spans, step timeline,
bounded histograms, Prometheus/Perfetto export, runtime gauges.

Contracts under test:
  * span ordering + the TTFT event: the span's first_token - queued IS
    the request's measured ttft_s (same engine clock, same floats);
  * ring bounding: spans, steps, AND the results dict stay bounded
    under churn while total counts survive in the window counters;
  * telemetry-off fast path: ring 0 records nothing, metrics percentile
    surface still works (histograms are independent of the ring);
  * Prometheus exposition parses and counters are monotonic across
    reset_metrics (the lifetime-base fold);
  * histogram percentiles sit within one bucket width of exact numpy
    percentiles;
  * Chrome-trace export of a mixed prefill/decode/spec run is valid
    trace JSON with >= 1 complete request span and the kv_blocks_used
    counter track (the acceptance criterion);
  * watchdog heartbeat-age gauge goes stale on a dropped heartbeat
    (riding the fault-injection harness) and folds into the runtime
    exposition;
  * tools/check_metrics_surface.py passes (every metrics key covered by
    reset_metrics + conftest reconciliation + Prometheus — tier-1).
"""
import importlib.util
import json
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate.nn import FusedMultiTransformer
from paddle_tpu.inference.serving import ServingEngine
from paddle_tpu.inference.telemetry import (LogHistogram, Telemetry,
                                            export_chrome_tracing,
                                            parse_prometheus,
                                            validate_chrome_trace)
from paddle_tpu.nn.layer.common import Embedding, Linear

V, E, H, FF, L = 97, 32, 4, 64, 2
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _model(seed=3):
    paddle.seed(seed)
    embed = Embedding(V, E)
    fmt = FusedMultiTransformer(E, H, FF, num_layers=L,
                                normalize_before=True)
    head = Linear(E, V, bias_attr=False)
    fmt.eval()
    return fmt, embed, head


def _prompt(rng, n):
    return rng.randint(1, V, (n,)).astype(np.int32)


# =====================================================================
# LogHistogram
# =====================================================================
class TestLogHistogram:
    def test_percentiles_within_one_bucket_width(self):
        """The documented accuracy contract: p50/p90/p99 estimates land
        within one bucket width of exact numpy percentiles."""
        rng = np.random.RandomState(7)
        values = rng.lognormal(mean=-3.0, sigma=1.5, size=2000)
        h = LogHistogram(1e-6, 1e4)
        for v in values:
            h.observe(v)
        assert h.count == values.size
        assert abs(h.sum - values.sum()) < 1e-6 * values.sum() + 1e-9
        for q in (50, 90, 99):
            exact = float(np.percentile(values, q))
            est = h.percentile(q)
            w = max(h.bucket_width_at(exact), h.bucket_width_at(est))
            assert abs(est - exact) <= w + 1e-12, (q, est, exact, w)

    def test_monotone_in_q_and_empty(self):
        h = LogHistogram(1e-6, 1e3)
        assert h.percentile(50) is None
        rng = np.random.RandomState(1)
        for v in rng.uniform(0.001, 10.0, 500):
            h.observe(v)
        ps = [h.percentile(q) for q in (1, 25, 50, 75, 90, 99)]
        assert ps == sorted(ps)

    def test_underflow_and_overflow_bounded(self):
        h = LogHistogram(1e-3, 1.0)
        h.observe(0.0)                       # underflow: frozen clocks
        h.observe(1e9)                       # overflow: clamps to the
        assert 0.0 <= h.percentile(1) < 1e-3  # last (pow-2-rounded) edge
        assert h.percentile(99) <= float(h.edges[-1]) + 1e-12

    def test_le_edges_are_inclusive(self):
        """Prometheus `le` boundaries are INCLUSIVE: a sample exactly on
        a bucket edge (tokens-per-step lands on the pow-2 edges every
        run) must count under le=edge, or histogram_quantile skews a
        whole bucket high."""
        h = LogHistogram(1.0, 1 << 16)
        for _ in range(10):
            h.observe(4.0)               # exactly a per-octave edge
        by_le = {}
        for ln in h.prometheus_lines("t"):
            if ln.startswith('t_bucket{le="'):
                le = ln.split('"')[1]
                by_le[le] = int(ln.rsplit(" ", 1)[1])
        assert by_le["4"] == 10, by_le
        assert by_le["2"] == 0
        # the internal percentile view agrees: p50 sits in the bucket
        # 4 closes, not the one above it
        assert h.percentile(50) <= 4.0 + 1e-12

    def test_reset_folds_into_cumulative(self):
        h = LogHistogram(1e-3, 10.0)
        for v in (0.1, 0.2, 0.3):
            h.observe(v)
        h.reset()
        assert h.count == 0 and h.percentile(50) is None
        h.observe(0.5)
        counts, total, s = h.cumulative_counts()
        assert total == 4 and int(counts.sum()) == 4
        assert abs(s - 1.1) < 1e-9
        lines = h.prometheus_lines("x_seconds")
        assert "x_seconds_count 4" in lines
        # cumulative bucket counts are non-decreasing in le
        vals = [int(ln.rsplit(" ", 1)[1]) for ln in lines
                if ln.startswith("x_seconds_bucket")]
        assert vals == sorted(vals) and vals[-1] == 4


# =====================================================================
# Request spans + ring bounding
# =====================================================================
class TestRequestSpans:
    def test_span_ordering_and_ttft_event(self, serving_metrics_ok):
        """Span events are time-ordered with the canonical lifecycle
        sequence, and the TTFT implied by the span (first_token -
        queued) EQUALS the request's measured ttft_s exactly — one
        clock, one set of floats."""
        fmt, embed, head = _model(seed=5)
        rng = np.random.RandomState(2)
        eng = ServingEngine(fmt, embed, head, num_slots=2,
                            max_seq_len=128, decode_chunk=2,
                            prefill_cap=4, prefix_cache_blocks=8)
        rids = [eng.submit(_prompt(rng, 9), max_new_tokens=4)
                for _ in range(3)]
        eng.run()
        serving_metrics_ok(eng)
        spans = {sp.rid: sp for sp in eng.telemetry.spans}
        assert set(rids) <= set(spans)
        for rid in rids:
            sp = spans[rid]
            names = [n for n, _ in sp.events]
            ts = [t for _, t in sp.events]
            assert ts == sorted(ts)
            assert names[0] == "queued" and names[-1] == "finished"
            assert sp.state == "finished"
            order = [names.index(n) for n in
                     ("queued", "admitted", "first_token", "finished")]
            assert order == sorted(order)
            assert "prefill_chunk" in names
            ev = dict(sp.events)             # first_token is unique
            assert ev["first_token"] - ev["queued"] == \
                eng.results[rid]["ttft_s"]
            assert sp.slot is not None
        # shared prompts: requests 2..3 hit the prefix cache published
        # by request 1 — the adopt event shows in their spans
        rid2 = eng.submit(_prompt(np.random.RandomState(2), 9),
                          max_new_tokens=2)
        eng.run()
        sp2 = {sp.rid: sp for sp in eng.telemetry.spans}[rid2]
        assert "prefix_adopt" in [n for n, _ in sp2.events]

    def test_ring_bounds_spans_steps_and_results(self, serving_metrics_ok):
        """PADDLE_TELEMETRY_RING bounds all three retention surfaces
        under churn; total counts survive in the window counters (the
        unbounded-results leak fix)."""
        fmt, embed, head = _model(seed=6)
        rng = np.random.RandomState(3)
        eng = ServingEngine(fmt, embed, head, num_slots=1,
                            max_seq_len=128, decode_chunk=2,
                            telemetry_ring=4)
        assert eng.telemetry.ring == 4 and eng._results_cap == 4
        rids = []
        for _ in range(10):
            rids.append(eng.submit(_prompt(rng, 5), max_new_tokens=2))
            eng.run()
        m = serving_metrics_ok(eng)
        assert m["requests_finished"] == 10      # totals preserved
        assert m["requests_admitted"] == 10
        assert eng.telemetry.hist_latency.count == 10
        assert len(eng.telemetry.spans) == 4     # rings bounded
        assert len(eng.results) == 4
        assert set(eng.results) == set(rids[-4:])  # newest retained
        assert len(eng.telemetry.steps) <= 4

    def test_env_knob(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TELEMETRY_RING", "16")
        fmt, embed, head = _model(seed=7)
        eng = ServingEngine(fmt, embed, head, num_slots=1,
                            max_seq_len=64)
        assert eng.telemetry.ring == 16 and eng._results_cap == 16
        with pytest.raises(ValueError, match=">= 0"):
            Telemetry(-1)

    def test_telemetry_off_fast_path(self, serving_metrics_ok):
        """Ring 0: no spans, no step events, no per-event clock reads —
        but the histogram-backed metrics surface still works (it rides
        timestamps the engine takes anyway) and results stay bounded at
        the default cap."""
        fmt, embed, head = _model(seed=8)
        rng = np.random.RandomState(4)
        calls = [0]
        base = time.perf_counter

        def counting_clock():
            calls[0] += 1
            return base()

        eng = ServingEngine(fmt, embed, head, num_slots=2,
                            max_seq_len=128, decode_chunk=2,
                            telemetry_ring=0, clock=counting_clock)
        on = ServingEngine(fmt, embed, head, num_slots=2,
                           max_seq_len=128, decode_chunk=2,
                           clock=lambda: base())
        assert not eng.telemetry.enabled
        for e in (eng, on):
            for _ in range(2):
                e.submit(_prompt(np.random.RandomState(4), 6),
                         max_new_tokens=3)
        off_calls0 = calls[0]
        eng.run()
        on.run()
        off_calls = calls[0] - off_calls0
        m = serving_metrics_ok(eng)
        assert len(eng.telemetry.spans) == 0
        assert len(eng.telemetry.steps) == 0
        assert m["ttft_p50_s"] is not None       # histograms still on
        assert eng._results_cap == 2048
        # the off engine reads the clock strictly less often than the
        # on engine would for the same flow (no dispatch timing, no
        # admission stamps): sanity-bound it to the step-level reads
        steps = sum(1 for _ in eng.chunk_log)
        assert off_calls <= 6 * steps + 4 * m["requests_admitted"] + 8
        text = eng.metrics_prometheus()          # exposition still works
        assert "paddle_serving_ttft_seconds_count" in text

    def test_rejected_span_and_expiry_state(self, serving_metrics_ok):
        from paddle_tpu.inference.serving import AdmissionFull
        fmt, embed, head = _model(seed=9)
        rng = np.random.RandomState(5)
        clk = [0.0]

        def ticking():                           # strictly advancing
            clk[0] += 1e-4
            return clk[0]

        eng = ServingEngine(fmt, embed, head, num_slots=1,
                            max_seq_len=128, decode_chunk=2,
                            max_pending=2, clock=ticking)
        eng.submit(_prompt(rng, 4), max_new_tokens=2)
        rid_exp = eng.submit(_prompt(rng, 4), max_new_tokens=2,
                             deadline_s=0.5)
        with pytest.raises(AdmissionFull):
            eng.submit(_prompt(rng, 4), max_new_tokens=2)
        states = [sp.state for sp in eng.telemetry.spans]
        assert states == ["rejected"]
        clk[0] = 10.0                            # expire the queued one
        eng.run()
        m = serving_metrics_ok(eng)
        assert m["requests_expired"] == 1 and m["requests_rejected"] == 1
        by_rid = {sp.rid: sp for sp in eng.telemetry.spans}
        assert by_rid[rid_exp].state == "expired"
        # expired requests never reach the latency histograms
        assert eng.telemetry.hist_latency.count == m["requests_finished"]


# =====================================================================
# Prometheus exposition
# =====================================================================
class TestSloGoodput:
    """SLO/goodput layer (the cluster trace plane's accounting half):
    every finished request gets exactly one verdict against the
    declared objectives, violations attribute to queueing vs service,
    and the snapshot schema carries the slo block + the
    queue/service decomposition the autoscaler consumes."""

    def test_classify_pure(self):
        from paddle_tpu.inference.telemetry import SloPolicy
        p = SloPolicy(ttft_s=0.5, itl_s=0.1, e2e_s=2.0)
        assert p.enabled
        # all objectives met
        assert p.classify(0.0, 1.0, 0.4, 0.05, 1.0) == "ok"
        # ttft blown, time dominated by service
        assert p.classify(0.1, 1.0, 0.9, 0.05, 1.1) == "service"
        # e2e blown, time dominated by queueing
        assert p.classify(3.0, 0.5, 0.4, 0.05, 3.5) == "queue"
        # itl objective alone
        assert p.classify(0.0, 1.0, 0.4, 0.2, 1.0) == "service"
        # no objectives = never violated
        none = SloPolicy()
        assert not none.enabled
        assert none.classify(99.0, 99.0, 99.0, 99.0, 198.0) == "ok"
        with pytest.raises(ValueError):
            SloPolicy(ttft_s=0.0)

    def test_queue_vs_service_attribution_virtual_clock(
            self, serving_metrics_ok):
        """num_slots=1 + a virtual clock: the head request is admitted
        instantly (ok), the second waits a whole request's worth of
        steps in the queue and blows the TTFT objective — attributed
        to QUEUEING, deterministically."""
        from paddle_tpu.inference.telemetry import SloPolicy
        fmt, embed, head = _model()
        clock = [0.0]

        def tick():
            # every read advances 1ms (busy_s must survive metrics()'
            # 4-decimal rounding); the BIG advances happen between
            # steps below
            clock[0] += 1e-3
            return clock[0]

        eng = ServingEngine(fmt, embed, head, num_slots=1,
                            max_seq_len=64, prefill_cap=4,
                            clock=tick, slo=SloPolicy(ttft_s=0.5))
        rng = np.random.RandomState(0)
        r1 = eng.submit(_prompt(rng, 5), max_new_tokens=3)
        r2 = eng.submit(_prompt(rng, 6), max_new_tokens=3)
        while eng.has_work:
            eng.step()
            clock[0] += 1.0               # 1 virtual second per step
        m = serving_metrics_ok(eng)
        assert m["requests_finished"] == 2
        assert m["slo_ok"] == 1           # r1: ttft 0.0
        assert m["slo_violated_queue"] == 1   # r2 queued for seconds
        assert m["slo_violated_service"] == 0
        # the decomposition histograms saw exactly the finished pair
        assert eng.telemetry.hist_queue.count == 2
        assert m["queue_p99_s"] >= m["queue_p50_s"] >= 0.0
        # and the per-request records reconcile with the verdicts
        assert eng.results[r1]["ttft_s"] <= 0.5
        assert eng.results[r2]["ttft_s"] > 0.5

    def test_snapshot_slo_block_and_exposition(
            self, serving_metrics_ok):
        from paddle_tpu.inference.telemetry import (
            SNAPSHOT_SCHEMA_VERSION, SloPolicy)
        fmt, embed, head = _model()
        eng = ServingEngine(fmt, embed, head, num_slots=2,
                            max_seq_len=64, prefill_cap=4,
                            slo=SloPolicy(ttft_s=1e-9))
        rng = np.random.RandomState(1)
        for _ in range(3):
            eng.submit(_prompt(rng, 5), max_new_tokens=2)
        eng.run()
        m = serving_metrics_ok(eng)
        # a 1ns TTFT objective is unmeetable on a real clock: every
        # request is violated, split across the two causes
        assert m["slo_ok"] == 0
        assert (m["slo_violated_queue"]
                + m["slo_violated_service"]) == 3
        snap = eng.telemetry_snapshot()
        # v8: the v4 QoS additions (preemption accounting in the
        # requests block, per-class queue depths at the top level, the
        # per-class queue-violation split in slo) plus the role (v5),
        # health (v6) and weights (v7, + quant modes in v8) blocks —
        # the full-version pin lives in
        # tools/check_metrics_surface.py; here just assert the
        # snapshot self-reports the module constant
        assert snap["schema_version"] == SNAPSHOT_SCHEMA_VERSION == 8
        assert snap["requests"]["migrated_in"] == 0
        assert snap["requests"]["migrated_out"] == 0
        assert snap["requests"]["preempted"] == 0
        assert snap["requests"]["resumed"] == 0
        assert snap["queue_depths"] == {"high": 0, "normal": 0,
                                        "low": 0}
        slo = snap["slo"]
        assert slo["objectives"]["ttft_s"] == 1e-9
        assert (slo["ok"] + slo["violated_queue"]
                + slo["violated_service"]) == 3
        # every queued-violation lands in exactly one class bucket
        # (this all-default run: "normal")
        assert sum(slo["violated_queue_by_class"].values()) == \
            slo["violated_queue"]
        assert slo["violated_queue_by_class"]["high"] == 0
        assert snap["histograms"]["queue_s"]["count"] == 3
        assert snap["histograms"]["service_s"]["count"] == 3
        json.dumps(snap)                  # still a wire payload
        text = eng.metrics_prometheus()
        assert "paddle_serving_slo_ok_total 0" in text
        assert "paddle_serving_queue_time_seconds_bucket" in text
        assert "paddle_serving_service_time_seconds_count 3" in text

    def test_trace_dump_payload(self):
        from paddle_tpu.inference.telemetry import trace_dump
        fmt, embed, head = _model()
        eng = ServingEngine(fmt, embed, head, num_slots=2,
                            max_seq_len=64, prefill_cap=4)
        rng = np.random.RandomState(2)
        eng.submit(_prompt(rng, 5), max_new_tokens=2,
                   trace_id="trc-dump", attempt=3)
        eng.run()
        d = trace_dump(eng)
        json.dumps(d)                     # crosses the rpc boundary
        assert d["num_slots"] == 2 and d["t_wall"] > 0
        sp = next(s for s in d["spans"] if s["trace_id"] == "trc-dump")
        assert sp["attempt"] == 3 and sp["state"] == "finished"
        assert [e[0] for e in sp["events"]][0] == "queued"
        assert d["steps"], "step timeline missing from the dump"


class TestPrometheus:
    def test_parse_and_counter_monotonic_across_reset(self):
        """The exposition round-trips a text parse, and every counter is
        monotonic across reset_metrics (the lifetime-base fold): the
        scrape a Prometheus server sees never moves backwards."""
        fmt, embed, head = _model(seed=10)
        rng = np.random.RandomState(6)
        eng = ServingEngine(fmt, embed, head, num_slots=2,
                            max_seq_len=128, decode_chunk=2)
        for _ in range(2):
            eng.submit(_prompt(rng, 6), max_new_tokens=3)
        eng.run()
        s1 = parse_prometheus(eng.metrics_prometheus())
        counters = [k for k in s1 if k.endswith("_total")
                    or k.endswith("_count")]
        assert "paddle_serving_tokens_emitted_total" in counters
        eng.reset_metrics(keep_results=False)
        eng.submit(_prompt(rng, 6), max_new_tokens=3)
        eng.run()
        s2 = parse_prometheus(eng.metrics_prometheus())
        for k in counters:
            assert s2[k] >= s1[k], (k, s1[k], s2[k])
        # and the window genuinely moved (not a trivially-frozen scrape)
        assert s2["paddle_serving_tokens_emitted_total"] > \
            s1["paddle_serving_tokens_emitted_total"]
        # histogram sum/count reconcile
        assert s2["paddle_serving_request_latency_seconds_count"] == \
            s2["paddle_serving_requests_finished_total"]

    def test_malformed_lines_rejected(self):
        with pytest.raises(ValueError):
            parse_prometheus("no_type_line 1\n")
        with pytest.raises(ValueError):
            parse_prometheus("# TYPE x widget\nx 1\n")

    def test_runtime_registry(self):
        from paddle_tpu.inference import telemetry as T
        T.runtime_counter("paddle_test_counter_total", 3)
        T.runtime_histogram("paddle_test_latency_seconds").observe(0.01)
        text = "\n".join(T.runtime_prometheus())
        s = parse_prometheus(text + "\n")
        assert s["paddle_test_counter_total"] >= 3
        assert s["paddle_test_latency_seconds_count"] >= 1
        assert "paddle_runtime_restart_generation" in s


# =====================================================================
# Chrome-trace export (the Perfetto acceptance criterion)
# =====================================================================
class TestChromeTrace:
    def test_mixed_prefill_decode_spec_run_exports(self):
        """A mixed prefill/decode/spec run exports valid Chrome-trace
        JSON: >= 1 COMPLETE request span (queued -> finished) and the
        kv_blocks_used counter track, thread metadata for slots, and
        every event structurally sound (validate_chrome_trace)."""
        fmt, embed, head = _model(seed=12)
        rng = np.random.RandomState(8)
        eng = ServingEngine(fmt, embed, head, num_slots=2,
                            max_seq_len=128, decode_chunk=2,
                            prefill_cap=4, spec_k=2)
        for _ in range(3):
            core = _prompt(rng, 6)
            eng.submit(np.tile(core, 3), max_new_tokens=12)
        eng.run()
        path = os.path.join(
            os.environ.get("TMPDIR", "/tmp"),
            f"telemetry_trace_{os.getpid()}.json")
        try:
            export_chrome_tracing(eng, path)
            doc = validate_chrome_trace(path)
            evs = doc["traceEvents"]
            spans = [e for e in evs if e["ph"] == "X"
                     and str(e.get("name", "")).startswith("req ")
                     and "[finished]" in e["name"]]
            assert spans, "no complete queued->finished request span"
            for e in spans:
                assert e["dur"] >= 0 and e["tid"] >= 1
                names = [n for n, _ in e["args"]["events"]]
                assert names[0] == "queued" and names[-1] == "finished"
            kinds = {e["name"] for e in evs if e["ph"] == "X"
                     and e["tid"] == 0}
            # budget scheduling is the default: every dispatch kind on
            # the timeline is a canonical one
            assert kinds <= {"admit", "prefill", "decode", "verify",
                             "budget"}
            assert kinds & {"budget", "decode"}
            counters = {e["name"] for e in evs if e["ph"] == "C"}
            assert "kv_blocks_used" in counters    # paged default
            assert "queue_depth" in counters
            threads = [e for e in evs if e["ph"] == "M"
                       and e["name"] == "thread_name"]
            assert len(threads) == eng.num_slots + 2
        finally:
            if os.path.exists(path):
                os.remove(path)

    def test_export_covers_measured_window_after_reset(self):
        fmt, embed, head = _model(seed=14)
        rng = np.random.RandomState(9)
        eng = ServingEngine(fmt, embed, head, num_slots=1,
                            max_seq_len=64, decode_chunk=2)
        eng.submit(_prompt(rng, 5), max_new_tokens=2)
        eng.run()
        eng.reset_metrics(keep_results=False)    # warmup discarded
        rid = eng.submit(_prompt(rng, 5), max_new_tokens=2)
        eng.run()
        assert [sp.rid for sp in eng.telemetry.spans] == [rid]


# =====================================================================
# Runtime gauges: watchdog heartbeat age (fault-injection harness)
# =====================================================================
class TestWatchdogGauges:
    def test_heartbeat_age_goes_stale_on_dropped_heartbeat(
            self, monkeypatch):
        from paddle_tpu.core.native import (TCPStore, TCPStoreServer,
                                            load_native)
        if load_native() is None:
            pytest.skip("native runtime unavailable")
        from paddle_tpu.distributed.resilience import watchdog as wdm
        from paddle_tpu.distributed.resilience.watchdog import Watchdog
        # ride the existing fault-injection harness: rank 1's publisher
        # goes dark while its process stays alive — rank 0's gauge must
        # age out and cross the failure threshold
        monkeypatch.setenv("PADDLE_FI_DROP_HEARTBEAT", "1")
        srv = TCPStoreServer(0)
        wd0 = wd1 = None
        try:
            def mk(rank):
                return Watchdog(
                    lambda t: TCPStore("127.0.0.1", srv.port,
                                       timeout_s=t),
                    rank, 2, timeout_s=1.0, interval_s=0.1,
                    action="flag")
            wd0 = mk(0).start()
            wd1 = mk(1).start()
            deadline = time.monotonic() + 8.0
            while wd0.failure is None and time.monotonic() < deadline:
                time.sleep(0.05)
            assert wd0.failure is not None, "dropped heartbeat undetected"
            ages = wd0.heartbeat_ages()
            assert set(ages) == {1}
            assert ages[1] > wd0.timeout_s       # stale past threshold
            g = wd0.gauges()
            assert g["peer_failures_total"] == 1
            # rank 1 SEES rank 0 beating: its gauge stays fresh
            assert wd1.heartbeat_ages()[0] < wd0.timeout_s
            # the runtime exposition folds the gauges in
            monkeypatch.setattr(wdm, "_watchdog", [wd0])
            from paddle_tpu.inference.telemetry import (
                parse_prometheus, runtime_prometheus)
            s = parse_prometheus("\n".join(runtime_prometheus()) + "\n")
            key = 'paddle_runtime_watchdog_heartbeat_age_seconds{peer="1"}'
            assert s[key] > wd0.timeout_s
            assert s["paddle_runtime_watchdog_peer_failures_total"] == 1
        finally:
            for wd in (wd0, wd1):
                if wd is not None:
                    wd.stop()
            srv.stop()


# =====================================================================
# Structured JSON-lines runtime log
# =====================================================================
class TestLogJson:
    def test_plain_mode_prints_message_verbatim(self, capsys,
                                                monkeypatch):
        monkeypatch.delenv("PADDLE_LOG_JSON", raising=False)
        from paddle_tpu.distributed.logjson import log_event
        log_event("launch", "restart", message="launch: restarting",
                  backoff_s=1.0)
        log_event("watchdog", "clean_exit")      # message-less: silent
        out = capsys.readouterr().out
        assert out == "launch: restarting\n"

    def test_json_mode_one_object_per_line(self, capsys, monkeypatch):
        monkeypatch.setenv("PADDLE_LOG_JSON", "1")
        monkeypatch.setenv("PADDLE_TRAINER_ID", "3")
        monkeypatch.setenv("PADDLE_RESTART_COUNT", "2")
        from paddle_tpu.distributed.logjson import log_event
        t0 = time.monotonic()
        log_event("watchdog", "peer_failure",
                  message="paddle_tpu watchdog: rank 1 stale",
                  ranks=[1], timeout_s=1.0)
        log_event("launch", "gang_start", world=2)
        lines = capsys.readouterr().out.strip().splitlines()
        assert len(lines) == 2
        recs = [json.loads(ln) for ln in lines]
        assert recs[0]["component"] == "watchdog"
        assert recs[0]["event"] == "peer_failure"
        assert recs[0]["rank"] == 3 and recs[0]["generation"] == 2
        assert recs[0]["ranks"] == [1]
        assert recs[0]["t_mono"] >= t0 - 1.0
        assert abs(recs[0]["t_wall"] - time.time()) < 60.0
        assert recs[1]["event"] == "gang_start" and recs[1]["world"] == 2

    def test_watchdog_failure_emits_json(self, capsys, monkeypatch):
        monkeypatch.setenv("PADDLE_LOG_JSON", "1")
        from paddle_tpu.distributed.resilience.watchdog import (
            PeerFailureError, Watchdog)
        wd = Watchdog(lambda t: None, 0, 2, timeout_s=1.0,
                      interval_s=0.1, action="flag")
        wd._fail(PeerFailureError("rank 1 gone", ranks=(1,)))
        recs = [json.loads(ln) for ln in
                capsys.readouterr().out.strip().splitlines()]
        ev = [r for r in recs if r.get("event") == "peer_failure"]
        assert ev and ev[0]["ranks"] == [1]
        assert wd.peer_failures == 1


# =====================================================================
# rpc call-latency histogram
# =====================================================================
def _rpc_probe(x):
    return x * 2


class TestRpcLatency:
    def test_rpc_call_records_latency(self):
        from paddle_tpu.core.native import load_native
        if load_native() is None:
            pytest.skip("native runtime unavailable")
        from paddle_tpu.distributed import rpc
        from paddle_tpu.inference import telemetry as T
        h = T.runtime_histogram("paddle_rpc_call_latency_seconds")
        c0 = T.runtime_counter("paddle_rpc_calls_total", 0)
        n0 = h.count
        rpc.init_rpc("tele_worker0", rank=0, world_size=1,
                     master_endpoint="127.0.0.1:0")
        try:
            assert rpc.rpc_sync("tele_worker0", _rpc_probe,
                                args=(21,)) == 42
            assert h.count == n0 + 1
            assert T.runtime_counter("paddle_rpc_calls_total", 0) == \
                c0 + 1
            text = "\n".join(T.runtime_prometheus()) + "\n"
            s = parse_prometheus(text)
            assert s["paddle_rpc_call_latency_seconds_count"] >= 1
        finally:
            rpc.shutdown()


# =====================================================================
# tools/check_metrics_surface.py as a tier-1 test
# =====================================================================
def test_metrics_surface_fully_covered(capsys):
    """Every metrics() key is covered by reset_metrics, the conftest
    reconciliation, AND the Prometheus exposition — the PR 4 reset-
    metrics bug class, made structural."""
    spec = importlib.util.spec_from_file_location(
        "check_metrics_surface",
        os.path.join(REPO_ROOT, "tools", "check_metrics_surface.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rc = mod.main()
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "ok" in out
