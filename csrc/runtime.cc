// paddle_tpu native runtime: TCPStore rendezvous, host trace collector,
// bounded MPMC queue (DataLoader prefetch backbone).
//
// Capability parity (TPU-native re-implementations, not ports):
//  - TCPStore / MasterDaemon:  paddle/fluid/distributed/store/tcp_store.cc
//    (master listens, ranks set/get/add/wait over a tiny length-prefixed
//    protocol on loopback/DCN; bootstrap KV for multi-host rendezvous).
//  - Host tracer:              paddle/fluid/platform/profiler/ (RecordEvent
//    host instrumentation -> chrome trace). Device timing comes from XLA's
//    own profiler; this collects host-side spans with ns precision and no
//    Python-object overhead in the hot path.
//  - Bounded blocking queue:   the native prefetch core of the reference's
//    DataLoader (paddle/fluid/operators/reader/buffered_reader.cc-class
//    machinery) — Python workers enqueue opaque handles; consumers block in
//    C (GIL released) instead of spinning a Python queue.
//
// Exposed as a plain C ABI for ctypes (pybind11 is not available in this
// image — see repo build notes).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

int64_t now_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             Clock::now().time_since_epoch())
      .count();
}

// ---------------------------------------------------------------------------
// Length-prefixed framing helpers
// ---------------------------------------------------------------------------

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_frame(int fd, uint8_t op, const std::string& key,
                const std::string& val) {
  uint32_t kl = htonl(static_cast<uint32_t>(key.size()));
  uint32_t vl = htonl(static_cast<uint32_t>(val.size()));
  return send_all(fd, &op, 1) && send_all(fd, &kl, 4) &&
         send_all(fd, key.data(), key.size()) && send_all(fd, &vl, 4) &&
         send_all(fd, val.data(), val.size());
}

bool recv_frame(int fd, uint8_t* op, std::string* key, std::string* val) {
  uint32_t kl = 0, vl = 0;
  if (!recv_all(fd, op, 1) || !recv_all(fd, &kl, 4)) return false;
  kl = ntohl(kl);
  if (kl > (64u << 10)) return false;
  key->resize(kl);
  if (kl && !recv_all(fd, key->data(), kl)) return false;
  if (!recv_all(fd, &vl, 4)) return false;
  vl = ntohl(vl);
  if (vl > (64u << 20)) return false;
  val->resize(vl);
  if (vl && !recv_all(fd, val->data(), vl)) return false;
  return true;
}

// ops
enum : uint8_t { OP_SET = 1, OP_GET = 2, OP_ADD = 3, OP_WAIT = 4, OP_OK = 5,
                 OP_MISS = 6 };

// ---------------------------------------------------------------------------
// MasterDaemon: the store server
// ---------------------------------------------------------------------------

struct Master {
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stop{false};
  std::thread accept_thread;
  std::vector<std::thread> handlers;
  std::mutex fds_mu;
  std::vector<int> client_fds;
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::string> kv;

  void handle(int fd) {
    uint8_t op;
    std::string key, val;
    while (!stop.load() && recv_frame(fd, &op, &key, &val)) {
      switch (op) {
        case OP_SET: {
          {
            std::lock_guard<std::mutex> l(mu);
            kv[key] = val;
          }
          cv.notify_all();
          if (!send_frame(fd, OP_OK, key, "")) goto done;
          break;
        }
        case OP_GET: {
          std::unique_lock<std::mutex> l(mu);
          auto it = kv.find(key);
          if (it == kv.end()) {
            l.unlock();
            if (!send_frame(fd, OP_MISS, key, "")) goto done;
          } else {
            std::string v = it->second;
            l.unlock();
            if (!send_frame(fd, OP_OK, key, v)) goto done;
          }
          break;
        }
        case OP_ADD: {
          int64_t delta = 0;
          std::memcpy(&delta, val.data(),
                      std::min(val.size(), sizeof(delta)));
          int64_t cur;
          {
            std::lock_guard<std::mutex> l(mu);
            auto it = kv.find(key);
            cur = 0;
            if (it != kv.end() && it->second.size() == 8)
              std::memcpy(&cur, it->second.data(), 8);
            cur += delta;
            std::string v(8, '\0');
            std::memcpy(v.data(), &cur, 8);
            kv[key] = v;
          }
          cv.notify_all();
          std::string v(8, '\0');
          std::memcpy(v.data(), &cur, 8);
          if (!send_frame(fd, OP_OK, key, v)) goto done;
          break;
        }
        case OP_WAIT: {
          // val = 4-byte timeout ms (network order)
          uint32_t tmo = 0;
          if (val.size() == 4) {
            std::memcpy(&tmo, val.data(), 4);
            tmo = ntohl(tmo);
          }
          std::unique_lock<std::mutex> l(mu);
          bool ok = cv.wait_for(l, std::chrono::milliseconds(tmo ? tmo : 1),
                                [&] {
                                  return kv.count(key) > 0 || stop.load();
                                }) && !stop.load();
          l.unlock();
          if (!send_frame(fd, ok ? OP_OK : OP_MISS, key, "")) goto done;
          break;
        }
        default:
          goto done;
      }
    }
  done:
    {
      // deregister before closing: stop() shutdown()s every fd still in
      // client_fds, and the OS may have reassigned a closed fd number to
      // an unrelated descriptor in this process
      std::lock_guard<std::mutex> l(fds_mu);
      client_fds.erase(std::remove(client_fds.begin(), client_fds.end(), fd),
                       client_fds.end());
    }
    ::close(fd);
  }

  void run() {
    while (!stop.load()) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (stop.load()) break;
        continue;
      }
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      {
        std::lock_guard<std::mutex> l(fds_mu);
        client_fds.push_back(fd);
      }
      handlers.emplace_back([this, fd] { handle(fd); });
    }
  }
};

// ---------------------------------------------------------------------------
// Client
// ---------------------------------------------------------------------------

struct Client {
  int fd = -1;
  std::mutex mu;  // one request in flight per client
};

// ---------------------------------------------------------------------------
// Trace collector
// ---------------------------------------------------------------------------

struct TraceEvent {
  std::string name;
  int64_t begin_ns;
  int64_t end_ns;
  uint64_t tid;
};

struct Tracer {
  std::mutex mu;
  std::vector<TraceEvent> events;
  bool enabled = false;
};

Tracer g_tracer;

thread_local std::vector<std::pair<std::string, int64_t>> tl_stack;

uint64_t tid_hash() {
  return std::hash<std::thread::id>{}(std::this_thread::get_id());
}

// ---------------------------------------------------------------------------
// Bounded MPMC queue of opaque pointers
// ---------------------------------------------------------------------------

struct Queue {
  std::mutex mu;
  std::condition_variable not_full, not_empty;
  std::deque<void*> items;
  size_t cap;
  std::atomic<bool> closed{false};
  explicit Queue(size_t c) : cap(c) {}
};

}  // namespace

extern "C" {

// ------------------------------- store -------------------------------------

void* pd_store_master_start(int port) {
  auto* m = new Master();
  m->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (m->listen_fd < 0) {
    delete m;
    return nullptr;
  }
  int one = 1;
  setsockopt(m->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(m->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
          0 ||
      ::listen(m->listen_fd, 128) < 0) {
    ::close(m->listen_fd);
    delete m;
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  getsockname(m->listen_fd, reinterpret_cast<sockaddr*>(&addr), &len);
  m->port = ntohs(addr.sin_port);
  m->accept_thread = std::thread([m] { m->run(); });
  return m;
}

int pd_store_master_port(void* h) { return static_cast<Master*>(h)->port; }

void pd_store_master_stop(void* h) {
  auto* m = static_cast<Master*>(h);
  m->stop.store(true);
  ::shutdown(m->listen_fd, SHUT_RDWR);
  ::close(m->listen_fd);
  if (m->accept_thread.joinable()) m->accept_thread.join();
  {
    // unblock every handler stuck in recv_frame, then join — no thread may
    // outlive the Master it dereferences
    std::lock_guard<std::mutex> l(m->fds_mu);
    for (int fd : m->client_fds) ::shutdown(fd, SHUT_RDWR);
  }
  m->cv.notify_all();
  for (auto& t : m->handlers)
    if (t.joinable()) t.join();
  delete m;
}

void* pd_store_client_connect(const char* host, int port, int timeout_ms) {
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) return nullptr;
  auto deadline = Clock::now() + std::chrono::milliseconds(timeout_ms);
  int fd = -1;
  for (;;) {
    // fresh socket per attempt: after a failed connect the fd is left in
    // an error state and every further connect on it fails immediately,
    // which used to turn the retry window into a single shot — a client
    // racing the master's bind could then never get in at all
    fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return nullptr;
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0)
      break;
    ::close(fd);
    if (Clock::now() > deadline) return nullptr;
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto* c = new Client();
  c->fd = fd;
  return c;
}

void pd_store_client_close(void* h) {
  auto* c = static_cast<Client*>(h);
  ::close(c->fd);
  delete c;
}

int pd_store_set(void* h, const char* key, const uint8_t* data, int len) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> l(c->mu);
  if (!send_frame(c->fd, OP_SET, key,
                  std::string(reinterpret_cast<const char*>(data), len)))
    return -1;
  uint8_t op;
  std::string k, v;
  return recv_frame(c->fd, &op, &k, &v) && op == OP_OK ? 0 : -1;
}

// returns value length, or -1 on miss/error; copies min(cap, len) bytes
int pd_store_get(void* h, const char* key, uint8_t* out, int cap) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> l(c->mu);
  if (!send_frame(c->fd, OP_GET, key, "")) return -1;
  uint8_t op;
  std::string k, v;
  if (!recv_frame(c->fd, &op, &k, &v) || op != OP_OK) return -1;
  int n = static_cast<int>(v.size());
  std::memcpy(out, v.data(), std::min(n, cap));
  return n;
}

int pd_store_add(void* h, const char* key, long long delta, long long* out) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> l(c->mu);
  std::string payload(8, '\0');
  int64_t d = delta;
  std::memcpy(payload.data(), &d, 8);
  if (!send_frame(c->fd, OP_ADD, key, payload)) return -1;
  uint8_t op;
  std::string k, v;
  if (!recv_frame(c->fd, &op, &k, &v) || op != OP_OK || v.size() != 8)
    return -1;
  int64_t r;
  std::memcpy(&r, v.data(), 8);
  *out = r;
  return 0;
}

int pd_store_wait(void* h, const char* key, int timeout_ms) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> l(c->mu);
  uint32_t tmo = htonl(static_cast<uint32_t>(timeout_ms));
  std::string payload(4, '\0');
  std::memcpy(payload.data(), &tmo, 4);
  if (!send_frame(c->fd, OP_WAIT, key, payload)) return -1;
  uint8_t op;
  std::string k, v;
  return recv_frame(c->fd, &op, &k, &v) && op == OP_OK ? 0 : -1;
}

// ------------------------------- tracer ------------------------------------

void pd_trace_enable(int on) {
  std::lock_guard<std::mutex> l(g_tracer.mu);
  g_tracer.enabled = on != 0;
  if (on) g_tracer.events.clear();
}

void pd_trace_begin(const char* name) {
  if (!g_tracer.enabled) return;
  tl_stack.emplace_back(name, now_ns());
}

void pd_trace_end() {
  if (!g_tracer.enabled || tl_stack.empty()) return;
  auto [name, begin] = tl_stack.back();
  tl_stack.pop_back();
  std::lock_guard<std::mutex> l(g_tracer.mu);
  g_tracer.events.push_back({std::move(name), begin, now_ns(), tid_hash()});
}

int pd_trace_count() {
  std::lock_guard<std::mutex> l(g_tracer.mu);
  return static_cast<int>(g_tracer.events.size());
}

// chrome trace (catapult) JSON
int pd_trace_dump(const char* path) {
  std::lock_guard<std::mutex> l(g_tracer.mu);
  FILE* f = std::fopen(path, "w");
  if (!f) return -1;
  auto json_escape = [](const std::string& s) {
    std::string out;
    out.reserve(s.size() + 8);
    for (unsigned char c : s) {
      switch (c) {
        case '"': out += "\\\""; break;
        case '\\': out += "\\\\"; break;
        case '\n': out += "\\n"; break;
        case '\t': out += "\\t"; break;
        case '\r': out += "\\r"; break;
        default:
          if (c < 0x20) {
            char buf[8];
            std::snprintf(buf, sizeof(buf), "\\u%04x", c);
            out += buf;
          } else {
            out += static_cast<char>(c);
          }
      }
    }
    return out;
  };
  std::fputs("{\"traceEvents\":[", f);
  bool first = true;
  for (const auto& e : g_tracer.events) {
    if (!first) std::fputc(',', f);
    first = false;
    std::fprintf(f,
                 "{\"name\":\"%s\",\"ph\":\"X\",\"ts\":%.3f,\"dur\":%.3f,"
                 "\"pid\":0,\"tid\":%llu,\"cat\":\"host\"}",
                 json_escape(e.name).c_str(), e.begin_ns / 1e3,
                 (e.end_ns - e.begin_ns) / 1e3,
                 static_cast<unsigned long long>(e.tid % 100000));
  }
  std::fputs("]}", f);
  std::fclose(f);
  return 0;
}

// ------------------------------- queue -------------------------------------

void* pd_queue_new(int capacity) { return new Queue(capacity); }

void pd_queue_close(void* h) {
  auto* q = static_cast<Queue*>(h);
  q->closed.store(true);
  q->not_empty.notify_all();
  q->not_full.notify_all();
}

void pd_queue_free(void* h) { delete static_cast<Queue*>(h); }

// item is an opaque non-null pointer (Python passes an integer token).
// returns 0 ok, -1 timeout/closed
int pd_queue_put(void* h, void* item, int timeout_ms) {
  auto* q = static_cast<Queue*>(h);
  std::unique_lock<std::mutex> l(q->mu);
  if (!q->not_full.wait_for(l, std::chrono::milliseconds(timeout_ms), [&] {
        return q->items.size() < q->cap || q->closed.load();
      }))
    return -1;
  if (q->closed.load()) return -1;
  q->items.push_back(item);
  l.unlock();
  q->not_empty.notify_one();
  return 0;
}

// returns item or nullptr on timeout/closed-and-empty
void* pd_queue_get(void* h, int timeout_ms) {
  auto* q = static_cast<Queue*>(h);
  std::unique_lock<std::mutex> l(q->mu);
  if (!q->not_empty.wait_for(l, std::chrono::milliseconds(timeout_ms), [&] {
        return !q->items.empty() || q->closed.load();
      }))
    return nullptr;
  if (q->items.empty()) return nullptr;
  void* it = q->items.front();
  q->items.pop_front();
  l.unlock();
  q->not_full.notify_one();
  return it;
}

int pd_queue_size(void* h) {
  auto* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> l(q->mu);
  return static_cast<int>(q->items.size());
}

}  // extern "C"
