#!/bin/bash
# Round-3 second-window TPU session. Priorities (value/minute):
#   1. headline re-measure with the new CE + rbg PRNG (donated default)
#   2. scan-steps A/B (run_steps(8): per-dispatch RPC amortization)
#   3. per-op trace profiles: gpt2 + bert (names the next bottleneck)
#   4. flash block sweep (reduced grid)
#   5. decode ratchet, MoE isolated (wedge risk contained)
# Each phase timeboxed; BENCH_partial.json checkpoints inside bench.py.
set -u
OUT=${1:-/tmp/tpu_session2}
mkdir -p "$OUT"
cd /root/repo

run() {  # run <name> <timeout_s> <cmd...>
  local name=$1 to=$2; shift 2
  echo "=== $name (timeout ${to}s) $(date +%H:%M:%S) ===" | tee -a "$OUT/session.log"
  timeout "$to" "$@" > "$OUT/$name.log" 2>&1
  echo "exit=$? $(tail -c 400 "$OUT/$name.log" | tr '\n' ' ')" | tee -a "$OUT/session.log"
}

# 1. headline + bert + llama + vit (new CE/rbg); moe EXCLUDED (isolated at 6)
run bench_main 1800 env BENCH_BUDGET_S=1200 BENCH_SKIP=moe python bench.py
cp BENCH_partial.json "$OUT/bench_main.json" 2>/dev/null

# 2. scan A/B on the headline config
run bench_scan 700 env BENCH_SCAN=8 BENCH_ONLY=none BENCH_STEPS=24 python bench.py

# 3. trace profiles (per-op table to stderr→log; summary.json per target)
run prof_gpt2 700 env PROF_STEPS=10 PROF_MODE=trace python tools/tpu_profile.py "$OUT/prof_gpt2"
run prof_bert 700 env PROF_MODEL=bert PROF_STEPS=10 PROF_MODE=trace python tools/tpu_profile.py "$OUT/prof_bert"

# 4. flash block sweep (reduced: diagonal + the two asymmetric best-bets)
for pt in "256 256" "512 512" "1024 1024" "512 1024" "256 512"; do
  set -- $pt
  run "sweep_$1x$2" 420 env PADDLE_TPU_FLASH_BQ=$1 PADDLE_TPU_FLASH_BK=$2 \
      BENCH_DONATE_PROBE=0 BENCH_ONLY=none BENCH_STEPS=30 python bench.py
done

# 5. decode ratchet
run bench_decode 900 python bench_decode.py

# 6. MoE isolated (wedged last session when the tunnel dropped mid-compile)
run bench_moe 900 env BENCH_ONLY=moe BENCH_DONATE_PROBE=0 python bench.py

echo "session complete $(date +%H:%M:%S); grep -h tokens_per_sec $OUT/*.log" | tee -a "$OUT/session.log"
