"""Promote a flap-stranded BENCH_partial.json into a BENCH_tpu.json
window record.

bench.py only appends a window record when a run reaches its end; a run
killed mid-config (tunnel flap, wedged config) leaves its measured rows
ONLY in the partial. The session's last phase runs this so a window that
never managed a clean bench_all still publishes everything it measured,
honestly marked partial_window=true.

No-op (exit 0) when there is no partial, the partial lacks TPU
provenance, or it holds no measured rows.
"""
from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    partial_path = os.path.join(REPO, "BENCH_partial.json")
    if not os.path.exists(partial_path):
        print("publish_partial: no partial on disk; nothing to do")
        return 0
    try:
        with open(partial_path) as f:
            partial = json.load(f)
    except ValueError as e:
        print(f"publish_partial: unreadable partial ({e}); leaving it")
        return 0
    if partial.get("on_tpu") is not True:
        print("publish_partial: partial lacks TPU provenance; refusing")
        return 0
    # same 6 h freshness gate as bench.py's resume: a day-old partial
    # promoted with window_utc=now would misdate the ratchet log
    import time
    age = time.time() - os.path.getmtime(partial_path)
    if age > 6 * 3600:
        print(f"publish_partial: partial is {age / 3600:.1f} h old "
              "(> 6 h); refusing to stamp it as this window")
        return 0
    headline = partial.get("headline") or {}
    configs = [r for r in partial.get("configs") or []
               if isinstance(r, dict) and r.get("value") is not None
               and "error" not in r]
    if headline.get("value") is None and not configs:
        print("publish_partial: no measured rows; nothing to publish")
        return 0

    sys.path.insert(0, REPO)
    from bench import _append_tpu_window

    record = dict(headline)
    record["configs"] = partial.get("configs") or []
    record["partial_window"] = True
    record["source"] = ("flap-stranded BENCH_partial.json promoted by "
                        "tools/publish_partial.py — the run that measured "
                        "these rows never reached bench.py's own append")
    if not _append_tpu_window(record):
        # append failed (disk/permissions): the partial is the ONLY copy
        # of these measurements — keep it
        print("publish_partial: append FAILED; partial kept for retry")
        return 1
    os.remove(partial_path)
    n = len(configs) + (1 if headline.get("value") is not None else 0)
    print(f"publish_partial: appended partial window ({n} measured rows) "
          "to BENCH_tpu.json and removed the partial")
    return 0


if __name__ == "__main__":
    sys.exit(main())
