"""Per-op time breakdown of the headline GPT-2 train step.

Runs the bench-identical step under jax.profiler.trace and aggregates the
device-track op durations from the perfetto JSON the profiler writes, so
kernel work (matmul fusions, attention, copies, collectives) can be ranked
by per-step cost. Falls back to ablation timing (variants of the step with
parts removed) when the backend produces no usable trace.

Usage:  python tools/tpu_profile.py [outdir]
Env:    PROF_STEPS (default 10), PROF_MODE=trace|ablate|both (default both),
        PROF_MODEL=gpt2|tiny|bert|llama (default gpt2),
        BENCH_BATCH/BENCH_SEQ, BENCH_BERT_BATCH/BENCH_BERT_SEQ as in
        bench.py, PROF_CPU=1 to force the CPU backend.
"""
from __future__ import annotations

import glob
import gzip
import json
import os
import sys
import time
from collections import defaultdict

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("PROF_CPU") == "1":
    # The container bakes JAX_PLATFORMS=axon in and sitecustomize registers
    # the tunnel plugin; only the jax.config override reliably wins. Must
    # happen before any backend init or the tool steals the exclusive TPU
    # grant from a concurrently-running bench.
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=1").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")


def _build_parts():
    """Bench-identical pieces for PROF_MODEL ∈ {gpt2 (default), tiny,
    bert, llama}, shared by the trace and ablate modes:
    (model, opt, args, loss_call, body_call, tokens_per_step) where
    loss_call(*args) returns the full loss (heads + CE) and
    body_call(*args) a scalar over the backbone only (no heads/CE)."""
    import paddle_tpu as paddle

    target = os.environ.get("PROF_MODEL", "gpt2")
    paddle.seed(0)
    rng = np.random.RandomState(0)
    if target == "bert":
        from paddle_tpu.models.bert import BertForPretraining, bert_base
        from paddle_tpu.distributed.sharding import group_sharded_parallel
        batch = int(os.environ.get("BENCH_BERT_BATCH", "16"))
        seq = int(os.environ.get("BENCH_BERT_SEQ", "512"))
        # bench-identical: vocab padded 30522 -> 30720 (240x128 MXU
        # lanes) with ids sampled from the REAL vocab (bench.py bert)
        model = BertForPretraining(bert_base(vocab_size=30720))
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())
        model, opt = paddle.amp.decorate(model, opt, level="O2",
                                         dtype="bfloat16")
        model, opt, _ = group_sharded_parallel(model, opt, level="os_g")
        ids = rng.randint(0, 30522, (batch, seq)).astype(np.int32)
        labels = ids.copy()
        labels[rng.rand(*labels.shape) > 0.15] = -100
        args = (paddle.to_tensor(ids), paddle.to_tensor(labels),
                paddle.to_tensor(rng.randint(0, 2, (batch,)).astype(np.int32)))

        def loss_call(x, y, nsp):
            with paddle.amp.auto_cast(level="O2", dtype="bfloat16"):
                return model(x, masked_lm_labels=y,
                             next_sentence_labels=nsp)

        def body_call(x, y, nsp):
            inner = getattr(model, "_layers", model)
            with paddle.amp.auto_cast(level="O2", dtype="bfloat16"):
                seq_out, _pooled = inner.bert(x)
            return seq_out.sum()
    elif target == "vit":
        # bench-identical ViT-L/16 (bench.py bench_vit): b32x224 bf16,
        # granular remat via BENCH_VIT_REMAT, AdamW fp32 masters
        from paddle_tpu.models.vit import vit_l_16
        batch = int(os.environ.get("BENCH_VIT_BATCH", "32"))
        seq = 224
        model = vit_l_16(
            recompute=int(os.environ.get("BENCH_VIT_REMAT", "1")))
        model.bfloat16()
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters(),
                                     multi_precision=True)
        x_np = rng.randn(batch, 3, seq, seq).astype(np.float32)
        y_np = rng.randint(0, 1000, (batch,)).astype(np.int32)
        args = (paddle.to_tensor(x_np), paddle.to_tensor(y_np))

        def loss_call(x, y):
            import paddle_tpu.nn.functional as F
            return F.cross_entropy(model(x), y)

        def body_call(x, y):
            # backbone without the classifier head/CE: reuse the model's
            # own forward with the head detached is invasive; the head is
            # one [D, 1000] matmul — time it via fwd minus fwd_no_head
            head = model.head
            model.head = None
            try:
                out = model(x)
            finally:
                model.head = head
            return out.sum()

        # tokens/step analogue: patches per image
        return model, opt, args, loss_call, body_call, batch * 197
    else:
        if target == "llama":
            from paddle_tpu.models.llama import (LlamaConfig,
                                                 LlamaForCausalLM)
            c = LlamaConfig(vocab_size=32000, hidden_size=1024,
                            num_layers=16, num_heads=16,
                            intermediate_size=2816, max_position=1024)
            batch, seq = 8, 1024
            model = LlamaForCausalLM(c)
            vocab = c.vocab_size
            body = "llama"
            stage3 = True
        else:
            from paddle_tpu.models.gpt import gpt2_124m, gpt2_tiny
            batch = int(os.environ.get("BENCH_BATCH", "8"))
            seq = int(os.environ.get("BENCH_SEQ", "1024"))
            model = gpt2_tiny() if target == "tiny" else gpt2_124m()
            # bench-identical id range; gpt2_tiny's vocab is far smaller
            # than 50000 and out-of-range ids profile a clamped workload
            vocab = min(model.config.vocab_size, 50000)
            body = "gpt"
            stage3 = False
        model.bfloat16()
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters(),
                                     multi_precision=True)
        if stage3:
            # bench-identical: bench_llama wraps stage-3 sharding (1-dev
            # collapse on a single chip, but step() goes through the
            # sharded optimizer path being profiled)
            from paddle_tpu.distributed.sharding import (
                group_sharded_parallel)
            model, opt, _ = group_sharded_parallel(model, opt,
                                                   level="p_g_os")
        ids = rng.randint(0, vocab, (batch, seq + 1)).astype(np.int32)
        args = (paddle.to_tensor(ids[:, :-1]), paddle.to_tensor(ids[:, 1:]))

        def loss_call(x, y):
            return model(x, labels=y)

        def body_call(x, y):
            inner = getattr(model, "_layers", model)
            return getattr(inner, body)(x).sum()

    return model, opt, args, loss_call, body_call, batch * seq


def _build_step(donate):
    """Bench-identical train step; returns (step, args, tokens/step)."""
    import paddle_tpu as paddle
    model, opt, args, loss_call, _body, tokens = _build_parts()

    def _step(*a):
        loss = loss_call(*a)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = paddle.jit.to_static(_step, donate_state=donate)
    return step, args, tokens


def _drain(loss):
    return float(np.asarray(loss._data))


def profile_trace(outdir, steps):
    import jax
    step, args, _ = _build_step(donate=os.environ.get(
        "PADDLE_TPU_DONATE", "1") == "1")
    for _ in range(3):
        loss = step(*args)
    _drain(loss)
    t0 = time.perf_counter()
    with jax.profiler.trace(outdir):
        for _ in range(steps):
            loss = step(*args)
        _drain(loss)
    wall = (time.perf_counter() - t0) / steps
    print(f"profiled {steps} steps, {wall * 1e3:.1f} ms/step wall",
          file=sys.stderr)

    paths = glob.glob(os.path.join(
        outdir, "**", "*.trace.json.gz"), recursive=True)
    if not paths:
        print("no trace json produced", file=sys.stderr)
        return None
    path = max(paths, key=os.path.getmtime)
    with gzip.open(path, "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])

    # device-track pids: process_name metadata containing TPU/device
    dev_pids = set()
    names = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            nm = ev.get("args", {}).get("name", "")
            names[ev.get("pid")] = nm
            if any(k in nm.lower() for k in ("tpu", "device")):
                dev_pids.add(ev.get("pid"))
    by_cat = defaultdict(lambda: [0.0, 0.0, 0.0])  # ms, flops, bytes
    by_op = defaultdict(lambda: [0.0, 0.0, "", ""])  # ms, flops, tf_op, src
    total = 0.0
    for ev in events:
        if ev.get("ph") != "X" or ev.get("pid") not in dev_pids:
            continue
        a = ev.get("args", {})
        dur = ev.get("dur", 0) / 1e3  # us -> ms
        cat = a.get("hlo_category", "?")
        fl = float(a.get("model_flops", 0) or 0)
        by_cat[cat][0] += dur
        by_cat[cat][1] += fl
        by_cat[cat][2] += float(a.get("raw_bytes_accessed", 0) or 0)
        # strip trailing .N so repeated instances of one HLO aggregate
        base = ev.get("name", "?").rsplit(".", 1)[0]
        rec = by_op[base]
        rec[0] += dur
        rec[1] += fl
        if not rec[2]:
            rec[2] = a.get("tf_op", "")
            rec[3] = a.get("source", "")
        total += dur
    print(f"\n== by hlo_category over {steps} steps "
          f"(tracks: {sorted(names[p] for p in dev_pids)}) ==")
    for cat, (ms, fl, by) in sorted(by_cat.items(), key=lambda kv: -kv[1][0]):
        tf = fl / (ms * 1e-3) / 1e12 if ms else 0
        gb = by / (ms * 1e-3) / 1e9 if ms else 0
        print(f"{ms / steps:9.3f} ms/step {ms / max(total, 1e-9) * 100:5.1f}%"
              f"  {tf:7.1f} TF/s {gb:8.1f} GB/s  {cat}")
    print(f"{total / steps:9.3f} ms/step  TOTAL device time")
    print(f"\n== top ops ==")
    rows = sorted(by_op.items(), key=lambda kv: -kv[1][0])[:25]
    for name, (ms, fl, tf_op, src) in rows:
        tfs = fl / (ms * 1e-3) / 1e12 if ms else 0
        print(f"{ms / steps:9.3f} ms/step {tfs:7.1f} TF/s  {name[:40]:40s}"
              f" {tf_op[:60]:60s} {src.replace('/root/repo/', '')[:50]}")
    return {"wall_ms": wall * 1e3, "device_ms": total / steps,
            "by_cat": {c: {"ms_per_step": vals[0] / steps,
                           "flops_per_step": vals[1] / steps,
                           "bytes_per_step": vals[2] / steps}
                       for c, vals in by_cat.items()},
            "top": [[n, v[0] / steps, v[2], v[3]] for n, v in rows]}


def profile_ablate(steps):
    """Ablation timing for PROF_MODEL (gpt2 default; bert/llama are the
    MFU laggards this mode exists for): build step variants with pieces
    disabled and diff the medians. Robust when the profiler can't see the
    tunnel device."""
    import paddle_tpu as paddle

    def timed(variant):
        # fresh build per variant: donation off, optimizer state fresh
        model, opt, args, loss_call, body_call, _tok = _build_parts()

        def full(*a):
            loss = loss_call(*a)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        def no_opt(*a):          # fwd+bwd only
            loss = loss_call(*a)
            loss.backward()
            return loss

        def fwd(*a):
            return loss_call(*a)

        def fwd_no_head(*a):     # backbone without heads + CE
            return body_call(*a)

        def id_attn(*a):
            # attention ablated to identity (out = q): isolates the full
            # fwd+bwd cost of the flash kernels inside the real train
            # step — every model family routes through F.sdpa
            from paddle_tpu.nn import functional as F
            real = F.scaled_dot_product_attention
            F.scaled_dot_product_attention = lambda q, *r, **kw: q
            try:
                loss = loss_call(*a)
            finally:
                F.scaled_dot_product_attention = real
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        def no_drop(*a):
            model.eval()         # dropout off; still runs backward+opt
            loss = loss_call(*a)
            model.train()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        fn = {"full": full, "fwd+bwd": no_opt, "fwd": fwd,
              "fwd_no_head": fwd_no_head, "full_id_attn": id_attn,
              "full_no_drop": no_drop}[variant]
        step = paddle.jit.to_static(fn, donate_state=False)
        for _ in range(3):
            loss = step(*args)
        _drain(loss)
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(steps):
                loss = step(*args)
            _drain(loss)
            ts.append((time.perf_counter() - t0) / steps)
        return float(np.median(ts)) * 1e3

    out = {}
    for name in ("full", "fwd+bwd", "fwd", "fwd_no_head",
                 "full_id_attn", "full_no_drop"):
        out[name] = timed(name)
        print(f"{name:12s} {out[name]:8.2f} ms/step", file=sys.stderr)
    print(f"\n== ablation deltas (PROF_MODEL="
          f"{os.environ.get('PROF_MODEL', 'gpt2')}) ==")
    print(f"optimizer+writeback : {out['full'] - out['fwd+bwd']:8.2f} ms")
    print(f"backward            : {out['fwd+bwd'] - out['fwd']:8.2f} ms")
    print(f"heads + CE (fwd)    : {out['fwd'] - out['fwd_no_head']:8.2f} ms")
    print(f"body fwd            : {out['fwd_no_head']:8.2f} ms")
    print(f"attention fwd+bwd   : {out['full'] - out['full_id_attn']:8.2f} ms")
    print(f"all dropout         : {out['full'] - out['full_no_drop']:8.2f} ms")
    print(f"full step           : {out['full']:8.2f} ms")
    return out


def main():
    outdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/paddle_tpu_prof"
    os.makedirs(outdir, exist_ok=True)
    steps = int(os.environ.get("PROF_STEPS", "10"))
    mode = os.environ.get("PROF_MODE", "both")
    rec = {}
    if mode in ("trace", "both"):
        try:
            rec["trace"] = profile_trace(outdir, steps)
        except Exception as e:
            import traceback
            traceback.print_exc()
            print(f"trace profiling failed: {e}", file=sys.stderr)
    if mode in ("ablate", "both"):
        rec["ablate"] = profile_ablate(steps)
    with open(os.path.join(outdir, "summary.json"), "w") as f:
        json.dump(rec, f, indent=1)


if __name__ == "__main__":
    main()
