"""ViT-L/16 step ablation: localize the r3 11.2%-MFU laggard.

Times bench-shaped ViT-L variants (UNDONATED by default — set
PROF_DONATE=1 for bench's donated stepping; a donation hang here would
eat the window slot) and diffs chunk-medians:
  full          train step (fwd+bwd+AdamW), remat ON (bench config)
  no_remat      same without recompute (memory-permitting at this batch)
  no_opt        fwd+bwd only
  fwd           forward only
  full_remat_convpatch   full step with the patch CONV forced
                (PADDLE_TPU_PATCH_CONV=1) — the A/B against the new
                space-to-depth matmul default
Prints one JSON line. Run on the chip:  python tools/vit_profile.py
Env: PROF_STEPS (default 8), BENCH_VIT_BATCH (default 32).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from bench import _init_devices
    jax, dev, tpu_unavailable = _init_devices()
    on_tpu = dev.platform in ("tpu", "axon")
    import paddle_tpu as paddle
    from paddle_tpu.models.vit import vit_l_16, vit_tiny

    steps = int(os.environ.get("PROF_STEPS", "8" if on_tpu else "2"))
    batch = int(os.environ.get("BENCH_VIT_BATCH", "32")) if on_tpu else 2
    size = 224 if on_tpu else 32
    rng = np.random.RandomState(0)
    x_np = rng.randn(batch, 3, size, size).astype(np.float32)
    y_np = rng.randint(0, 10, (batch,)).astype(np.int32)

    def build(recompute=True):
        paddle.seed(0)
        m = vit_l_16(recompute=recompute) if on_tpu else vit_tiny()
        if on_tpu:
            m.bfloat16()
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=m.parameters(),
                                     multi_precision=on_tpu)
        x = paddle.to_tensor(x_np)
        if on_tpu:
            x = x.astype("bfloat16")
        return m, opt, x, paddle.to_tensor(y_np)

    donate = os.environ.get("PROF_DONATE") == "1"

    def timed(make_step, recompute=True):
        # EVERYTHING inside the try: a variant that fails to build (e.g.
        # no_remat OOM — it killed the tunnel chip twice in r3) must
        # yield None, not lose the already-measured variants
        try:
            m, opt, x, y = build(recompute)
            step = paddle.jit.to_static(make_step(m, opt),
                                        donate_state=donate)
            for _ in range(2):
                out = step(x, y)
            float(np.asarray(out._data).sum())
            ts = []
            chunk = max(steps // 3, 1)
            for _ in range(3):          # median of chunks, like bench.py
                t0 = time.perf_counter()
                for _ in range(chunk):
                    out = step(x, y)
                float(np.asarray(out._data).sum())
                ts.append((time.perf_counter() - t0) / chunk)
            return round(float(np.median(ts)) * 1e3, 2)
        except Exception as e:
            print(f"vit_profile: variant failed: {e}", file=sys.stderr)
            return None

    def full(m, opt):
        def f(x, y):
            loss = paddle.nn.functional.cross_entropy(m(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss
        return f

    def no_opt(m, opt):
        def f(x, y):
            loss = paddle.nn.functional.cross_entropy(m(x), y)
            loss.backward()
            return loss
        return f

    def fwd(m, opt):
        def f(x, y):
            return paddle.nn.functional.cross_entropy(m(x), y)
        return f

    rec = {"metric": "vit_l16_step_ablation_ms", "batch": batch,
           "device": str(dev)}
    rec["full_remat"] = timed(full, recompute=True)
    rec["no_opt"] = timed(no_opt, recompute=True)
    rec["fwd"] = timed(fwd, recompute=True)
    rec["full_no_remat"] = timed(full, recompute=False)
    # patch-embed A/B inside the full step: conv vs space-to-depth matmul
    os.environ["PADDLE_TPU_PATCH_CONV"] = "1"
    rec["full_remat_convpatch"] = timed(full, recompute=True)
    os.environ.pop("PADDLE_TPU_PATCH_CONV", None)
    if tpu_unavailable:
        rec["tpu_unavailable"] = True
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
