#!/usr/bin/env python
"""Collective-instrumentation coverage check (runnable standalone AND
as a tier-1 test via tests/test_flight_recorder.py).

The flight recorder only earns its cross-rank diagnosis if EVERY public
collective routes through its one choke point
(``flight_recorder.instrumented`` / ``record_span``) — a collective that
bypasses it desynchronizes the per-group seq numbers the diagnosis
aligns on, silently. Same discipline as tools/check_metrics_surface.py:
make the bug class structural instead of trusting review.

Checks (AST over the source, no heavy imports):

  1. every module-level function in ``communication/ops.py``'s and
     ``communication/all_reduce.py``'s ``__all__`` is decorated with
     ``@_instrumented(...)`` (non-collective entries are allowlisted
     with a reason);
  2. every ProcessGroupXLA collective method in
     ``communication/group.py`` is decorated;
  3. ``parallel.py::all_reduce_gradients`` is decorated;
  4. ``rpc.py``'s call path and ``watchdog.monitored_barrier`` route
     through ``record_span``.

Usage: python tools/check_collective_surface.py   (exit 0 = covered)
"""
from __future__ import annotations

import ast
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COMM = os.path.join(REPO_ROOT, "paddle_tpu", "distributed",
                    "communication")

# __all__ entries that are NOT collective entry points (each with the
# reason it is exempt — anything new added to __all__ without either a
# decorator or a line here fails tier-1)
OPS_ALLOWLIST = {
    "P2POp": "descriptor class; executed by batch_isend_irecv",
    "get_backend": "pure metadata query, no communication",
    "stream": "namespace re-exporting already-instrumented functions",
}

PG_METHODS = ("allreduce", "allgather", "reducescatter", "broadcast",
              "alltoall", "permute", "barrier")


def _decorator_names(node):
    names = []
    for dec in node.decorator_list:
        d = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(d, ast.Attribute):
            names.append(d.attr)
        elif isinstance(d, ast.Name):
            names.append(d.id)
    return names


def _module_all(tree):
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "__all__":
                    return [ast.literal_eval(e) for e in node.value.elts]
    return []


def _check_ops_module(path, failures):
    with open(path) as f:
        tree = ast.parse(f.read())
    exported = set(_module_all(tree))
    fns = {n.name: n for n in tree.body
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for name in sorted(exported):
        if name in OPS_ALLOWLIST:
            continue
        node = fns.get(name)
        if node is None:
            # exported but not a module-level function (class/namespace):
            # must be allowlisted explicitly
            failures.append(
                f"{os.path.basename(path)}: __all__ entry {name!r} is "
                "not a module-level function and not in OPS_ALLOWLIST — "
                "add it with a reason, or instrument it")
            continue
        if "_instrumented" not in _decorator_names(node) and \
                "instrumented" not in _decorator_names(node):
            failures.append(
                f"{os.path.basename(path)}: public collective {name!r} "
                "bypasses the flight-recorder choke point — decorate it "
                "with @_instrumented(...) (or allowlist it with a "
                "reason in tools/check_collective_surface.py)")


def _check_pg_methods(failures):
    path = os.path.join(COMM, "group.py")
    with open(path) as f:
        tree = ast.parse(f.read())
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "ProcessGroupXLA":
            meths = {n.name: n for n in node.body
                     if isinstance(n, ast.FunctionDef)}
            for m in PG_METHODS:
                if m not in meths:
                    failures.append(f"group.py: ProcessGroupXLA.{m} "
                                    "disappeared")
                elif "_instrumented" not in _decorator_names(meths[m]):
                    failures.append(
                        f"group.py: ProcessGroupXLA.{m} bypasses the "
                        "flight-recorder choke point — decorate it")
            return
    failures.append("group.py: ProcessGroupXLA class not found")


def _check_source_mentions(failures):
    """The non-ops call sites named by the ISSUE: grad sync, the rpc
    transport, the monitored barrier."""
    spots = [
        (os.path.join(REPO_ROOT, "paddle_tpu", "distributed",
                      "parallel.py"),
         "def all_reduce_gradients", ("_fr_instrumented",
                                      "instrumented")),
        (os.path.join(REPO_ROOT, "paddle_tpu", "distributed", "rpc.py"),
         "def call", ("record_span",)),
        (os.path.join(REPO_ROOT, "paddle_tpu", "distributed",
                      "resilience", "watchdog.py"),
         "def monitored_barrier", ("record_span",)),
    ]
    for path, anchor, needles in spots:
        with open(path) as f:
            src = f.read()
        if anchor not in src:
            failures.append(f"{os.path.basename(path)}: {anchor!r} not "
                            "found (refactor moved it? update the check)")
            continue
        if not any(n in src for n in needles):
            failures.append(
                f"{os.path.basename(path)}: {anchor.split()[-1]} no "
                f"longer routes through the flight-recorder choke point "
                f"(expected one of {needles})")


def main(argv=None):
    failures: list = []
    _check_ops_module(os.path.join(COMM, "ops.py"), failures)
    _check_ops_module(os.path.join(COMM, "all_reduce.py"), failures)
    _check_pg_methods(failures)
    _check_source_mentions(failures)
    if failures:
        print("check_collective_surface: FAILED")
        for f_ in failures:
            print(f"  - {f_}")
        return 1
    print("check_collective_surface: ok (every public collective routes "
          "through the flight-recorder choke point)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
