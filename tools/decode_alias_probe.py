"""Compile-only probe: does XLA:TPU alias the scan-carried KV-cache
update in place, or does it copy the full cache per layer step?

The CPU backend's copy-insertion differs from TPU's, so the 2026-08-01
CPU HLO findings (two full-cache copies per layer with the old
double-operand kernel, one residual copy with the single-operand one)
need on-chip ground truth before investing in an in-kernel cache write
(pallas input_output_aliases + dynamic store). This compiles four tiny
scan bodies on the real backend — no step is executed, so it costs only
compile time — and counts cache-shaped copies in the optimized HLO:

  dus_only    : carry = DUS(carry)                  (aliasing baseline)
  dus_dense   : carry = DUS(carry); read dense      (the dense fallback)
  dus_kernel1 : carry = DUS(carry); pallas(carry)   (current design)
  dus_kernel2 : carry = DUS(carry); pallas(c, c)    (pre-r5s2 design)

Prints one JSON line. Run:  python tools/decode_alias_probe.py
"""
from __future__ import annotations

import functools
import json
import os
import re
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from bench import _init_devices
    jax, dev, tpu_unavailable = _init_devices()
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    L, B, H, S, D = 3, 2, 4, 256, 32
    shape = (L, 2, B, H, S, D)
    # every carry-buffer shape whose copies would defeat the design: the
    # fp cache, the int8 cache, AND the i8 mode's fp32 scales buffer
    # (the second aliased output — its aliasing is the riskier half)
    def _shape_re(prefix, dims):
        return re.compile(prefix + r"\[" + ",".join(str(d) for d in dims)
                          + r"\][^\n]*copy\(")
    carry_res = [_shape_re("f32", shape), _shape_re("s8", shape),
                 _shape_re("f32", shape[:4] + (1, shape[4]))]
    interpret = jax.default_backend() != "tpu"

    def kern1(kv_ref, o_ref):
        o_ref[...] = kv_ref[0, 0] + kv_ref[0, 1]

    def pallas1(buf):
        return pl.pallas_call(
            kern1,
            grid=(B,),
            in_specs=[pl.BlockSpec((1, 2, 1, 1, S, D),
                                   lambda b: (0, 0, b, 0, 0, 0))],
            out_specs=pl.BlockSpec((1, 1, S, D), lambda b: (b, 0, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((B, 1, S, D), jnp.float32),
            interpret=interpret)(buf)

    def kern2(k_ref, v_ref, o_ref):
        o_ref[...] = k_ref[0, 0] + v_ref[0, 0]

    def pallas2(buf):
        return pl.pallas_call(
            kern2,
            grid=(B,),
            in_specs=[pl.BlockSpec((1, 1, 1, 1, S, D),
                                   lambda b: (0, 0, b, 0, 0, 0)),
                      pl.BlockSpec((1, 1, 1, 1, S, D),
                                   lambda b: (0, 1, b, 0, 0, 0))],
            out_specs=pl.BlockSpec((1, 1, S, D), lambda b: (b, 0, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((B, 1, S, D), jnp.float32),
            interpret=interpret)(buf, buf)

    def dus(buf, i):
        return jax.lax.dynamic_update_slice(
            buf, jnp.ones((1, 1, B, 1, 1, D)), (i, 0, 0, 0, 5, 0))

    def body_only(buf, i):
        buf = dus(buf, i)
        return buf, jnp.float32(0)

    def body_dense(buf, i):
        buf = dus(buf, i)
        o = jax.lax.dynamic_index_in_dim(buf, i, 0, keepdims=False)
        return buf, o.sum()

    def body_k1(buf, i):
        buf = dus(buf, i)
        return buf, pallas1(buf).sum()

    def body_k2(buf, i):
        buf = dus(buf, i)
        return buf, pallas2(buf).sum()

    # the production fused write+attend kernel (input_output_aliases, no
    # XLA-side DUS at all) — compiling it here also front-runs its first
    # Mosaic compile (dynamic-offset store, aliased output) before the
    # bench phase spends minutes on it
    from paddle_tpu.ops.pallas.decode_attention import (
        decode_attention_stacked_write)
    q = jnp.zeros((B, H, 1, D), jnp.float32)
    kvn = jnp.zeros((2, B, H, 1, D), jnp.float32)
    lens = jnp.full((B,), 7, jnp.int32)

    def body_kw(buf, i):
        buf, o = decode_attention_stacked_write(q, kvn, buf, i, lens)
        return buf, o.sum()

    from paddle_tpu.ops.pallas.decode_attention import (
        decode_attention_stacked_i8_write)
    buf_i8 = jnp.zeros(shape, jnp.int8)
    buf_sc = jnp.zeros(shape[:4] + (1, shape[4]), jnp.float32)

    def body_kw_i8(carry, i):
        ci, sc = carry
        ci, sc, o = decode_attention_stacked_i8_write(q, kvn, ci, sc, i,
                                                      lens)
        return (ci, sc), o.sum()

    out = {"device": str(dev), "tpu_unavailable": bool(tpu_unavailable),
           "cache_bytes": int(np.prod(shape)) * 4}
    for name, body, init in (
            ("dus_only", body_only, None), ("dus_dense", body_dense, None),
            ("dus_kernel1", body_k1, None), ("dus_kernel2", body_k2, None),
            ("kernel_write", body_kw, None),
            ("kernel_write_i8", body_kw_i8, (buf_i8, buf_sc))):
        try:
            fn = jax.jit(functools.partial(jax.lax.scan, body,
                                           xs=jnp.arange(L)))
            txt = fn.lower(init if init is not None
                           else jnp.zeros(shape, jnp.float32)
                           ).compile().as_text()
            out[name] = {"full_cache_copies":
                         sum(len(r.findall(txt)) for r in carry_res)}
        except Exception as e:  # a compile failure is itself a finding
            out[name] = {"error": f"{type(e).__name__}: {e}"[:200]}
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
