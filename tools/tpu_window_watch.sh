#!/bin/bash
# Probe the axon tunnel hang-safely every ~4 min; whenever it answers,
# run the queued measurement session (tools/tpu_session5.sh). Re-arming:
# if the session dies mid-window (tunnel flap, kill), the watcher goes
# back to probing and the NEXT window runs only the remaining phases
# (session5 skips its done/ markers). Exits only when session5 reports
# full completion ($OUT/done/ALL) — partial windows are the norm.
# The exclusive grant is a kernel flock on /tmp/tpu_window_active.flock
# owned by session5 (auto-released on any death — staleness-free); the
# watcher flock-probes it to avoid probing during someone else's window.
set -u
LOG=${1:-/tmp/tpu_watch.log}
OUT=${2:-/tmp/tpu_session5}
LOCK=/tmp/tpu_window_active
PIDFILE=/tmp/tpu_watch.pid

# single-watcher guard: a second copy exits instead of double-probing
if [ -f "$PIDFILE" ]; then
  old=$(cat "$PIDFILE" 2>/dev/null)
  if [ -n "$old" ] && [ "$old" != "$$" ] && kill -0 "$old" 2>/dev/null; then
    echo "$(date -u +%FT%TZ) watcher pid $old already running; exiting" >> "$LOG"
    exit 0
  fi
fi
echo $$ > "$PIDFILE"
# remove only OUR pidfile — an exiting stale watcher must not delete the
# pidfile a newer instance has already written over it
trap '[ "$(cat "$PIDFILE" 2>/dev/null)" = "$$" ] && rm -f "$PIDFILE"' EXIT INT TERM

echo "$(date -u +%FT%TZ) watcher start (pid $$)" >> "$LOG"
while :; do
  if [ -f "$OUT/done/ALL" ]; then
    echo "$(date -u +%FT%TZ) session5 fully complete — watcher exiting" >> "$LOG"
    break
  fi
  # the true mutex is the kernel flock (auto-released on holder death —
  # no staleness possible); probe it non-destructively. The presence
  # file $LOCK is informational only.
  if ! flock -n "$LOCK.flock" -c true 2>/dev/null; then
    sleep 240; continue
  fi
  if timeout 75 python -c "import jax; d=jax.devices()[0]; print(d.platform)" 2>/dev/null | grep -qE "tpu|axon"; then
    echo "$(date -u +%FT%TZ) TUNNEL UP -> running session5" >> "$LOG"
    rm -f /tmp/paddle_tpu_probe_down
    bash /root/repo/tools/tpu_session5.sh "$OUT" >> "$LOG" 2>&1
    rc=$?
    echo "$(date -u +%FT%TZ) session5 exited rc=$rc" >> "$LOG"
    # fall through: loop re-checks done/ALL, else re-arms for the rest
    sleep 60; continue
  fi
  echo "$(date -u +%FT%TZ) down" >> "$LOG"
  sleep 240
done
