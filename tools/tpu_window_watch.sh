#!/bin/bash
# Probe the axon tunnel hang-safely every ~4 min; the moment it answers,
# run the queued r4 measurement session (tools/tpu_session5.sh) ONCE and
# exit. Writes /tmp/tpu_window_active while the session runs so other
# processes don't contend for the exclusive TPU grant.
set -u
LOG=${1:-/tmp/tpu_watch.log}
echo "$(date -u +%FT%TZ) watcher start" >> "$LOG"
while :; do
  if [ -f /tmp/tpu_window_active ]; then
    sleep 240; continue
  fi
  if timeout 75 python -c "import jax; d=jax.devices()[0]; print(d.platform)" 2>/dev/null | grep -qE "tpu|axon"; then
    echo "$(date -u +%FT%TZ) TUNNEL UP -> running session5" >> "$LOG"
    touch /tmp/tpu_window_active
    rm -f /tmp/paddle_tpu_probe_down
    bash /root/repo/tools/tpu_session5.sh /tmp/tpu_session5 >> "$LOG" 2>&1
    rm -f /tmp/tpu_window_active
    echo "$(date -u +%FT%TZ) session5 complete" >> "$LOG"
    break
  fi
  echo "$(date -u +%FT%TZ) down" >> "$LOG"
  sleep 240
done
