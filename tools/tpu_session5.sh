#!/bin/bash
# r5 TPU window plan — flap-proof edition. Partial windows are the NORM
# (3 of 4 rounds lost a window mid-session), so the machinery assumes it
# will be killed mid-phase and engineered to resume:
#   - the exclusive-grant lock (/tmp/tpu_window_active) holds OUR PID and
#     is trap-cleaned on any exit; a dead-PID lock is stale and cleared,
#     so kill -9 can never wedge future runs;
#   - every phase writes $OUT/done/<name> on success and is SKIPPED on
#     re-entry; phases that failed twice are given up (not retried
#     forever, which would burn scarce window minutes);
#   - every phase commits its artifacts to the repo IMMEDIATELY (log copy
#     under tpu_windows/ + any repo-side JSON the phase appended), so a
#     tunnel flap at phase 3 still lands phases 1-2 durably;
#   - a mid-window probe failure exits the session; the (re-arming)
#     watcher resumes the REMAINING phases at the next window.
# Run order is value-per-minute. $OUT/done/ALL marks full completion.
set -u
OUT=${1:-/tmp/tpu_session5}
# TPU_WINDOW_LOCK override: CPU rehearsals take their own lock so a
# live-window launch is never blocked by a rehearsal holding the mutex
LOCK=${TPU_WINDOW_LOCK:-/tmp/tpu_window_active}
mkdir -p "$OUT" "$OUT/done"
cd /root/repo
mkdir -p tpu_windows

# --- exclusive-grant lock: kernel flock, zero staleness ----------------
# The TRUE mutex is a kernel flock on $LOCK.flock: acquisition is atomic,
# and the kernel releases it on ANY process death (kill -9 included), so
# stale locks cannot exist and no clear-by-name race is possible. The
# legacy presence file $LOCK (holder PID) is kept purely for human
# observers ("is a window active?"); machinery must test the flock, not
# the file. Phase children inherit the lock fd: if THIS shell is
# kill -9'd mid-phase, the grant stays locked until the orphaned phase
# process (which may still be using the TPU) exits — every phase runs
# under `timeout`, so that hold is bounded and correct.
exec 200>"$LOCK.flock"
if ! flock -n 200; then
  echo "window holder still active (flock busy); aborting" | tee -a "$OUT/session.log"
  exit 2
fi
echo $$ > "$LOCK"
# remove only OUR presence file — a late-exiting older session must not
# delete one a newer holder has since written
trap '[ "$(cat "$LOCK" 2>/dev/null)" = "$$" ] && rm -f "$LOCK"' EXIT INT TERM

PHASES=""   # registry, filled by run(); used for the ALL marker

commit_phase() {  # commit_phase <name> [extra repo paths...]
  local name=$1; shift
  # CPU rehearsals must never publish "tpu window" commits
  [ "${BENCH_TPU_UNAVAILABLE:-0}" = "1" ] && return 0
  # only commit for a phase that EXECUTED in this pass — a done-skipped
  # phase must not sweep up a stale BENCH_RESULT.json some later
  # interrupted phase left dirty (mislabeled artifact in history)
  [ "$(cat "$OUT/ran_$name" 2>/dev/null)" = "$$" ] || return 0
  local paths=()
  if [ -f "$OUT/$name.log" ]; then
    cp "$OUT/$name.log" "tpu_windows/$name.log" && paths+=("tpu_windows/$name.log")
  fi
  for p in "$@"; do [ -e "$p" ] && paths+=("$p"); done
  [ ${#paths[@]} -eq 0 ] && return 0
  # nothing of OURS changed? (never inspect/commit the whole index — the
  # builder session stages its own files concurrently)
  [ -z "$(git status --porcelain -- "${paths[@]}" 2>/dev/null)" ] && return 0
  # the builder session may be committing concurrently — retry index lock;
  # pathspec-limited commit so we never sweep the builder's staged files
  for i in 1 2 3 4 5; do
    if git add -- "${paths[@]}" >> "$OUT/session.log" 2>&1 &&
       git commit -m "tpu window: $name results" -- "${paths[@]}" >> "$OUT/session.log" 2>&1; then
      return 0
    fi
    sleep $((i*3))
  done
  echo "WARN: commit of $name artifacts failed (kept in $OUT)" | tee -a "$OUT/session.log"
}

run() {  # run <name> <timeout_s> <cmd...>  — then caller commit_phase's
  local name=$1 to=$2; shift 2
  PHASES="$PHASES $name"
  if [ -f "$OUT/done/$name" ]; then
    echo "=== $name done earlier; skip ===" | tee -a "$OUT/session.log"
    return 0
  fi
  local att=0
  [ -f "$OUT/att_$name" ] && att=$(cat "$OUT/att_$name" 2>/dev/null || echo 0)
  if [ "$att" -ge 2 ]; then
    echo "=== $name gave up after $att attempts; skip ===" | tee -a "$OUT/session.log"
    return 0
  fi
  # mid-window tunnel-death guard: a dead tunnel makes every later phase
  # hang to its full timeout — probe (~10 s when up) and exit instead;
  # completed phases are preserved and the watcher re-arms for the rest.
  # Skipped when BENCH_TPU_UNAVAILABLE=1 (CPU rehearsal mode).
  if [ "${BENCH_TPU_UNAVAILABLE:-0}" != "1" ]; then
    if ! timeout 70 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
      echo "=== $name: tunnel lost mid-window; stopping (done phases kept) ===" | tee -a "$OUT/session.log"
      exit 1
    fi
  fi
  echo $((att+1)) > "$OUT/att_$name"
  echo $$ > "$OUT/ran_$name"   # pass-scoped: unlocks commit_phase
  echo "=== $name (timeout ${to}s, attempt $((att+1))) ===" | tee -a "$OUT/session.log"
  timeout "$to" "$@" > "$OUT/$name.log" 2>&1
  local rc=$?
  echo "exit=$rc $(tail -c 300 "$OUT/$name.log" | tr '\n' ' ')" | tee -a "$OUT/session.log"
  if [ $rc -eq 0 ]; then
    touch "$OUT/done/$name"
  elif [ "${BENCH_TPU_UNAVAILABLE:-0}" != "1" ]; then
    # A failure while the tunnel is DEAD is an infrastructure kill, not a
    # phase bug — refund the attempt so two flap-kills can't permanently
    # give up the longest (highest-value) phases, and stop the session.
    if ! timeout 70 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
      echo $att > "$OUT/att_$name"
      echo "=== $name: tunnel died during phase; attempt refunded; stopping ===" | tee -a "$OUT/session.log"
      exit 1
    fi
  fi
  return 0
}

# 1. Ring-chunk kernel on-chip validation (carried from r3 s4; quick,
#    and the first Mosaic compile of the kernel family de-risks the rest).
run ring_kernel 600 python - <<'XEOF'
import numpy as np, jax, jax.numpy as jnp
from paddle_tpu.ops.pallas.ring_chunk_attention import ring_chunk_attention
B,H,Hk,S,D = 2,8,4,512,64
rng = np.random.RandomState(0)
q = jnp.asarray(rng.randn(B,H,S,D), jnp.bfloat16)
k = jnp.asarray(rng.randn(B,Hk,S,D), jnp.bfloat16)
v = jnp.asarray(rng.randn(B,Hk,S,D), jnp.bfloat16)
for off in (S, 0, -S//2):
    o, lse = ring_chunk_attention(q, k, v, off)
    g = jax.grad(lambda *a: jnp.sum(ring_chunk_attention(*a, off)[0].astype(jnp.float32)), (0,1,2))(q, k, v)
    print("off", off, "o_norm", float(jnp.linalg.norm(o.astype(jnp.float32))),
          "dq_norm", float(jnp.linalg.norm(g[0].astype(jnp.float32))))
print("RING_KERNEL_OK")
XEOF
commit_phase ring_kernel

# 1b. Compile-only aliasing ground truth (~1 min): does XLA:TPU copy the
#     scan-carried cache per layer? Decides whether the in-kernel cache
#     write is worth building. No TPU execution — compile time only.
run alias_probe 600 python tools/decode_alias_probe.py
commit_phase alias_probe

# 2. Decode ratchet with the in-place KV cache (scan-carried stacked
#    buffer + scalar-prefetch kernel). r3 ratchet: 418 tok/s; target 2x.
run bench_decode 900 python bench_decode.py
commit_phase bench_decode BENCH_tpu.json

# 3. Full 5-config bench — the MFU-spread scoreboard; appends the window
#    record to BENCH_tpu.json. Early: short windows must land this.
run bench_all 2400 env BENCH_BUDGET_S=1500 BENCH_RESUME=1 python bench.py
cp BENCH_partial.json "$OUT/" 2>/dev/null
commit_phase bench_all BENCH_tpu.json BENCH_RESULT.json

# 3b. Decode attention-path A/B: the stacked kernel measured BELOW the
#     r3 dense ratchet (399 vs 418 tok/s) — measure the dense fallback
#     in the same build to localize whether the kernel or something else
#     (e.g. the in-place scan cache) regressed.
run bench_decode_dense 900 env PADDLE_TPU_STACKED_KERNEL=0 python bench_decode.py
commit_phase bench_decode_dense BENCH_tpu.json

# 3c. Fused write+attend kernel (in-place cache via input_output_aliases,
#     zero XLA-side DUS on the carry) — the copy-elimination A/B.
run bench_decode_kw 900 env PADDLE_TPU_KERNEL_CACHE_WRITE=1 python bench_decode.py
commit_phase bench_decode_kw BENCH_tpu.json
# 3d. int8 cache + write kernel: in-kernel quantization, both buffers
#     aliased — the best-bandwidth decode mode without the DUS hazard.
run bench_decode_i8kw 900 env PADDLE_TPU_KERNEL_CACHE_WRITE=1 PADDLE_TPU_DECODE_INT8_CACHE=1 python bench_decode.py
commit_phase bench_decode_i8kw BENCH_tpu.json

# 4. int8 decode ladder: cache (halves KV stream), weights (halves the
#    dominant ~250 MB/token weight stream), full stack incl. LM head.
run bench_decode_i8 900 env PADDLE_TPU_DECODE_INT8_CACHE=1 python bench_decode.py
commit_phase bench_decode_i8 BENCH_tpu.json
run bench_decode_w8 900 env PADDLE_TPU_DECODE_INT8_WEIGHTS=1 python bench_decode.py
commit_phase bench_decode_w8 BENCH_tpu.json
run bench_decode_full8 900 env PADDLE_TPU_DECODE_INT8_WEIGHTS=1 PADDLE_TPU_DECODE_INT8_CACHE=1 PADDLE_TPU_DECODE_INT8_HEAD=1 python bench_decode.py
commit_phase bench_decode_full8 BENCH_tpu.json

# 5. 1B single-chip: Adafactor (analytic ~7 GB state — expected to FIT,
#    the >=1B single-chip row), then AdamW (expected RESOURCE_EXHAUSTED,
#    recorded as the OOM half of verdict #7).
run llama_1b_adafactor 2400 env BENCH_PROBE_ONESHOT=1 python tools/llama_1b.py --tpu --adafactor
commit_phase llama_1b_adafactor LLAMA1B_tpu.json
run llama_1b_adamw 1500 env BENCH_PROBE_ONESHOT=1 python tools/llama_1b.py --tpu
commit_phase llama_1b_adamw LLAMA1B_tpu.json

# 6. Long-context flash ratchet S=8k/16k (verdict missing #4).
run longctx 900 python tools/longctx_bench.py
commit_phase longctx

# 7. Fused-FFN A/B at the headline shape: composite vs fwd-kernel vs
#    fwd+bwd kernels (r5), scan off for clean per-step time.
run ffn_ab_composite 1200 env BENCH_ONLY=none BENCH_SCAN=0 BENCH_STEPS=10 python bench.py
commit_phase ffn_ab_composite BENCH_RESULT.json
run ffn_ab_fused 1200 env PADDLE_TPU_FUSED_FFN=1 BENCH_ONLY=none BENCH_SCAN=0 BENCH_STEPS=10 python bench.py
commit_phase ffn_ab_fused BENCH_RESULT.json
run ffn_ab_fwdbwd 1200 env PADDLE_TPU_FUSED_FFN=1 PADDLE_TPU_FUSED_FFN_BWD=1 BENCH_ONLY=none BENCH_SCAN=0 BENCH_STEPS=10 python bench.py
commit_phase ffn_ab_fwdbwd BENCH_RESULT.json

# 8. ViT A/B: space-to-depth patch matmul (new default) vs strided conv.
run vit_matmul 1200 env BENCH_HEADLINE=0 BENCH_ONLY=vit python bench.py
commit_phase vit_matmul BENCH_RESULT.json
run vit_conv 1200 env BENCH_HEADLINE=0 PADDLE_TPU_PATCH_CONV=1 BENCH_ONLY=vit python bench.py
commit_phase vit_conv BENCH_RESULT.json
# 8b. Granular-remat A/B: every-2nd-block, then none (OOM risk accepted —
#     RESOURCE_EXHAUSTED here is itself the measurement; r3s4's HBM
#     hygiene may have cured the original b32 OOM)
run vit_remat2 1200 env BENCH_HEADLINE=0 BENCH_VIT_REMAT=2 BENCH_ONLY=vit python bench.py
commit_phase vit_remat2 BENCH_RESULT.json
run vit_remat0 1200 env BENCH_HEADLINE=0 BENCH_VIT_REMAT=0 BENCH_ONLY=vit python bench.py
commit_phase vit_remat0 BENCH_RESULT.json

# 9. Remaining decode ratchets: cache-backed beam search + w8c8 combo.
#    (TP-sharded kernel decode cannot A/B here: mp>=2 needs >1 chip.)
run bench_decode_beam 900 env BENCH_BEAMS=4 BENCH_PROMPT=256 python bench_decode.py
commit_phase bench_decode_beam BENCH_tpu.json
# 9b. Bulk-prefill A/B at prompt=256 (timed region includes prefill):
#     per-token scan prefill vs whole-prompt causal-flash prefill.
run bench_decode_p256 900 env BENCH_PROMPT=256 python bench_decode.py
commit_phase bench_decode_p256 BENCH_tpu.json
run bench_decode_p256_bulk 900 env BENCH_PROMPT=256 PADDLE_TPU_BULK_PREFILL=1 python bench_decode.py
commit_phase bench_decode_p256_bulk BENCH_tpu.json
run bench_decode_w8c8 900 env PADDLE_TPU_DECODE_INT8_WEIGHTS=1 PADDLE_TPU_DECODE_INT8_CACHE=1 python bench_decode.py
commit_phase bench_decode_w8c8 BENCH_tpu.json
# 9d. Serving-batch row (b32 amortizes the ~250 MB/token weight stream
#     4x over the b8 ratchet) and the all-levers-on best-mode row.
run bench_decode_b32 900 env BENCH_BATCH=32 python bench_decode.py
commit_phase bench_decode_b32 BENCH_tpu.json
run bench_decode_best 900 env BENCH_BATCH=32 PADDLE_TPU_KERNEL_CACHE_WRITE=1 PADDLE_TPU_DECODE_INT8_WEIGHTS=1 PADDLE_TPU_DECODE_INT8_CACHE=1 PADDLE_TPU_DECODE_INT8_HEAD=1 python bench_decode.py
commit_phase bench_decode_best BENCH_tpu.json

# 9c. Wrapper-overhead A/B: the laggard configs run their sharding
#     wrappers at world=1 — measure each config bare to see if the
#     machinery itself costs step time on one chip.
run llama_plain 1200 env BENCH_HEADLINE=0 BENCH_ONLY=llama BENCH_LLAMA_PLAIN=1 python bench.py
commit_phase llama_plain BENCH_RESULT.json
run bert_plain 1200 env BENCH_HEADLINE=0 BENCH_ONLY=bert BENCH_BERT_PLAIN=1 python bench.py
commit_phase bert_plain BENCH_RESULT.json

# 10. Laggard-config profiles: where do BERT's (24.6%) and llama's
#     (42.1%) steps actually go? Ablation mode ranks fwd/bwd/opt parts.
run prof_bert 1200 env PROF_MODEL=bert PROF_MODE=ablate python tools/tpu_profile.py
commit_phase prof_bert
run prof_llama 1200 env PROF_MODEL=llama PROF_MODE=ablate python tools/tpu_profile.py
commit_phase prof_llama
run prof_vit 1500 python tools/vit_profile.py
commit_phase prof_vit
# hlo_category breakdown of the ViT step (device-track perfetto trace):
# names the actual time sinks (conv layout? small-seq attention? remat?)
run prof_vit_trace 1200 env PROF_MODEL=vit PROF_MODE=trace python tools/tpu_profile.py /tmp/vit_trace
commit_phase prof_vit_trace

# 11. Decode cost localization.
run decode_profile 1500 python tools/decode_profile.py
commit_phase decode_profile

# --- promote a flap-stranded bench partial ----------------------------
# Reaching here means every phase ran or gave up; if bench_all never
# published (gave up after 2 attempts), its measured rows are stranded in
# BENCH_partial.json — promote them to a partial_window record so the
# window still lands what it measured. No-op when bench_all succeeded.
ba_att=$(cat "$OUT/att_bench_all" 2>/dev/null || echo 0)
if [ ! -f "$OUT/done/bench_all" ] && [ "$ba_att" -ge 2 ] \
    && [ "${BENCH_TPU_UNAVAILABLE:-0}" != "1" ]; then
  timeout 120 python tools/publish_partial.py >> "$OUT/session.log" 2>&1
  if [ -n "$(git status --porcelain -- BENCH_tpu.json 2>/dev/null)" ]; then
    for i in 1 2 3 4 5; do   # same index-lock retry as commit_phase
      if git add -- BENCH_tpu.json >> "$OUT/session.log" 2>&1 &&
         git commit -m "tpu window: partial bench rows promoted" \
           -- BENCH_tpu.json >> "$OUT/session.log" 2>&1; then
        break
      fi
      sleep $((i*3))
    done
  fi
fi

# --- completion marker -------------------------------------------------
all=1
for p in $PHASES; do
  if [ ! -f "$OUT/done/$p" ]; then
    att=$(cat "$OUT/att_$p" 2>/dev/null || echo 0)
    [ "$att" -ge 2 ] || all=0
  fi
done
if [ "$all" = "1" ]; then
  touch "$OUT/done/ALL"
  echo "session COMPLETE (every phase done or given up)" | tee -a "$OUT/session.log"
else
  echo "session pass finished; some phases remain (watcher will re-arm)" | tee -a "$OUT/session.log"
fi
echo "REMEMBER: paste ratchet rows into BASELINE.md" | tee -a "$OUT/session.log"
