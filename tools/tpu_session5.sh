#!/bin/bash
# r4 TPU window plan. Run when the tunnel is up; phases ordered by
# value-per-minute, individually timeboxed. Results land in $OUT.
# After a full run: commit BENCH_tpu.json (auto-appended by bench.py),
# BENCH_decode JSON, and paste the A/B rows into BASELINE.md.
set -u
OUT=${1:-/tmp/tpu_session5}
mkdir -p "$OUT"
cd /root/repo

run() {  # run <name> <timeout_s> <cmd...>
  local name=$1 to=$2; shift 2
  # mid-window tunnel-death guard: a dead tunnel makes every later phase
  # hang to its full timeout — probe (~10 s when up) and stop the session
  # instead, so the driver/operator sees the partial results immediately.
  # Skipped when BENCH_TPU_UNAVAILABLE=1 (CPU rehearsal mode).
  if [ "${BENCH_TPU_UNAVAILABLE:-0}" != "1" ]; then
    if ! timeout 70 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
      echo "=== $name SKIPPED: tunnel lost mid-window; stopping session ===" | tee -a "$OUT/session.log"
      exit 1
    fi
  fi
  echo "=== $name (timeout ${to}s) ===" | tee -a "$OUT/session.log"
  timeout "$to" "$@" > "$OUT/$name.log" 2>&1
  echo "exit=$? $(tail -c 300 "$OUT/$name.log" | tr '\n' ' ')" | tee -a "$OUT/session.log"
}

# 1. Ring-chunk kernel first on-chip validation (carried over from r3 s4;
#    still never Mosaic-compiled).
run ring_kernel 600 python - <<'EOF'
import numpy as np, jax, jax.numpy as jnp
from paddle_tpu.ops.pallas.ring_chunk_attention import ring_chunk_attention
B,H,Hk,S,D = 2,8,4,512,64
rng = np.random.RandomState(0)
q = jnp.asarray(rng.randn(B,H,S,D), jnp.bfloat16)
k = jnp.asarray(rng.randn(B,Hk,S,D), jnp.bfloat16)
v = jnp.asarray(rng.randn(B,Hk,S,D), jnp.bfloat16)
for off in (S, 0, -S//2):
    o, lse = ring_chunk_attention(q, k, v, off)
    g = jax.grad(lambda *a: jnp.sum(ring_chunk_attention(*a, off)[0].astype(jnp.float32)), (0,1,2))(q, k, v)
    print("off", off, "o_norm", float(jnp.linalg.norm(o.astype(jnp.float32))),
          "dq_norm", float(jnp.linalg.norm(g[0].astype(jnp.float32))))
print("RING_KERNEL_OK")
EOF

# 2. Decode ratchet with the NEW in-place KV cache (scan-carried stacked
#    buffer + scalar-prefetch kernel). r3 ratchet: 418 tok/s; target 2x.
run bench_decode 900 python bench_decode.py
cp "$OUT/bench_decode.log" "$OUT/BENCH_decode_candidate.json" 2>/dev/null

# 2b. int8-cache decode A/B (halves cache bytes/token — the bandwidth
#     floor itself). Token parity with fp is CPU-asserted already.
run bench_decode_i8 900 env PADDLE_TPU_DECODE_INT8_CACHE=1 python bench_decode.py

# 3. Fused-FFN A/B at the headline shape (PADDLE_TPU_FUSED_FFN): kernel
#    vs XLA composite, few steps each, scan off for clean per-step time.
run ffn_ab_composite 1200 env BENCH_ONLY=none BENCH_SCAN=0 BENCH_STEPS=10 python bench.py
run ffn_ab_fused 1200 env PADDLE_TPU_FUSED_FFN=1 BENCH_ONLY=none BENCH_SCAN=0 BENCH_STEPS=10 python bench.py

# 4. ViT A/B: space-to-depth patch matmul (new default) vs strided conv.
run vit_matmul 1200 env BENCH_ONLY=vit python bench.py
run vit_conv 1200 env PADDLE_TPU_PATCH_CONV=1 BENCH_ONLY=vit python bench.py

# 5. Full 5-config bench — appends the window record to BENCH_tpu.json
#    (commit it!). MoE now reports MFU + gate/dispatch decomposition.
run bench_all 2400 env BENCH_BUDGET_S=1500 python bench.py
cp BENCH_partial.json "$OUT/" 2>/dev/null

# 6. Long-context flash ratchet S=8k/16k.
run longctx 900 python tools/longctx_bench.py

# 6b. Laggard-config profiles: where do BERT's (24.6%) and llama's
#     (42.1%) steps actually go? Ablation mode ranks fwd/bwd/opt parts.
run prof_bert 1200 env PROF_MODEL=bert PROF_MODE=ablate python tools/tpu_profile.py
run prof_llama 1200 env PROF_MODEL=llama PROF_MODE=ablate python tools/tpu_profile.py
run prof_vit 1500 python tools/vit_profile.py

# 7. Decode cost localization (only if the window is still alive).
run decode_profile 1500 python tools/decode_profile.py

# 8. 1B single-chip: Adafactor first (analytic ~7 GB state — expected to
#    FIT and produce the >=1B single-chip row), then the AdamW attempt
#    (analytic 16.45 GB — expected RESOURCE_EXHAUSTED, recorded as the
#    OOM half of VERDICT #7).
run llama_1b_adafactor 2400 python tools/llama_1b.py --tpu --adafactor
run llama_1b_adamw 1500 python tools/llama_1b.py --tpu

echo "session complete" | tee -a "$OUT/session.log"
echo "REMEMBER: git add BENCH_tpu.json + paste ratchet rows into BASELINE.md" | tee -a "$OUT/session.log"
