"""Summarize TPU window results for BASELINE.md.

Reads BENCH_tpu.json (the append-only machine ratchet log bench.py and
bench_decode.py write on every real-TPU run) plus any tpu_windows/*.log
phase artifacts, and prints:
  * a compact per-entry table (metric, value, provenance) for entries
    newer than --since (ISO date or 'r5' = 2026-08-01),
  * a ready-to-paste BASELINE.md ratchet-row skeleton per NEW window.

Run after a window:  python tools/harvest_window.py [--since 2026-08-01]
"""
from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main():
    since = "2026-08-01"
    if "--since" in sys.argv:
        since = sys.argv[sys.argv.index("--since") + 1]
    if since == "r5":
        since = "2026-08-01"
    path = os.path.join(REPO, "BENCH_tpu.json")
    if not os.path.exists(path):
        print("no BENCH_tpu.json — no TPU window has appended yet")
        return
    with open(path) as f:
        entries = json.load(f)
    print(f"{len(entries)} total entries in BENCH_tpu.json")
    fresh = [e for e in entries
             if str(e.get("date", e.get("ts", ""))) >= since]
    if not fresh:
        print(f"none newer than {since}; latest entry:")
        fresh = entries[-1:]
    for e in fresh:
        metric = e.get("metric", "?")
        val = e.get("value")
        bits = [f"{metric} = {val} {e.get('unit', '')}"]
        for k in ("mfu", "cache_mode", "weight_mode", "head_mode",
                  "num_beams", "prompt_len", "attention_path", "donated",
                  "scan_steps", "date", "ts"):
            if k in e:
                bits.append(f"{k}={e[k]}")
        print("  " + "  ".join(str(b) for b in bits))
        for c in e.get("configs", []) or []:
            print(f"    - {c.get('metric')}: {c.get('value')} "
                  f"{c.get('unit', '')}  mfu={c.get('mfu')}")
    logs = sorted(os.listdir(os.path.join(REPO, "tpu_windows"))) \
        if os.path.isdir(os.path.join(REPO, "tpu_windows")) else []
    if logs:
        print(f"\nphase logs in tpu_windows/: {', '.join(logs)}")
    print("\nBASELINE.md row skeleton:\n"
          "| <date> (r5 window) | <config + lever A/B'd> | <tok/s> | "
          "<MFU> | <what changed vs the prior ratchet, lever named> |")


if __name__ == "__main__":
    main()
