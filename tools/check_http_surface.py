#!/usr/bin/env python
"""HTTP wire-surface coverage check (runnable standalone AND as a
tier-1 test via tests/test_serving_cluster.py).

Boots a REAL gateway (asyncio HTTP server, in-process LocalReplicas)
and asserts every endpoint's response field set and every error-code
mapping against ``serving_cluster/protocol.py`` — over actual sockets,
not by inspecting handler code. The OpenAI-compat surface then cannot
drift silently: renaming a response field, dropping the SSE
terminator, or remapping an error status fails tier-1, the same
discipline ``check_metrics_surface.py`` applies to the Prometheus
surface.

Pinned end-to-end:
  * POST /v1/completions — COMPLETION_FIELDS / CHOICE_FIELDS /
    USAGE_FIELDS exactly; SSE chunks carry STREAM_CHUNK_FIELDS and the
    stream ends with ``data: [DONE]``.
  * TRACE CONTEXT ECHO: every response carries ``X-Request-Id``
    (protocol.TRACE_HEADER); an inbound id is honored verbatim in the
    header, the JSON ``trace_id`` field, and every SSE chunk — the
    wire contract the merged cluster trace joins on.
  * GET /v1/models, /healthz — field sets; /metrics — text exposition
    with per-replica labels + gateway gauges + gateway HTTP latency
    histograms + router decision counters.
  * The elastic admin surface: GET /admin/scale (SCALE_FIELDS —
    identical shape with or without an autoscaler), POST /admin/drain
    (a REAL graceful drain of one replica: DRAIN_FIELDS response, the
    replica leaves /healthz counts), POST /admin/scale without an
    autoscaler → 409, draining the last replica → 409, draining an
    unknown replica → 404.
  * Error mapping (ERROR_STATUS rows, each triggered for real):
    bad_request→400, unknown_model→404, not_found→404,
    deadline_exceeded→504, admission_full→429 (Retry-After computed
    from the measured drain rate — pinned to the documented
    [RETRY_AFTER_S, RETRY_AFTER_MAX_S] bounds), rate_limited→429,
    quota_exceeded→429, no_replica→503, conflict→409.
    ``internal``(500) is the only untriggered row — reaching it
    requires a bug by definition.
  * QoS / multi-tenant surface: every 429 body carries the
    machine-readable ``reason`` field (ERROR_BODY_FIELDS_429 /
    REASON_FOR_429 — overload vs rate_limited vs quota_exceeded, so
    clients can distinguish "cluster busy" from "you specifically are
    throttled"); the ``X-Priority`` header threads the class through
    to the engine's per-class counters (invalid classes → 400); a
    tenant's 429 Retry-After comes from ITS OWN token bucket (above
    the drain-rate floor other 429s use) and tenants are isolated —
    one throttled tenant never 429s another.

Usage: python tools/check_http_surface.py   (exit 0 = surface pinned)
"""
from __future__ import annotations

import http.client
import json
import os
import socket
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build_engine(num_slots=2, **kw):
    import paddle_tpu as paddle
    from paddle_tpu.incubate.nn import FusedMultiTransformer
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.nn.layer.common import Embedding, Linear

    V, E, H, FF, L = 67, 32, 4, 64, 1
    paddle.seed(11)
    embed = Embedding(V, E)
    fmt = FusedMultiTransformer(E, H, FF, num_layers=L,
                                normalize_before=True)
    head = Linear(E, V, bias_attr=False)
    fmt.eval()
    return ServingEngine(fmt, embed, head, num_slots=num_slots,
                         max_seq_len=64, prefill_cap=4, **kw)


def _req(port, method, path, body=None, timeout=60, headers=None):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    c.request(method, path,
              body=None if body is None else json.dumps(body),
              headers=dict({"Content-Type": "application/json"},
                           **(headers or {})))
    r = c.getresponse()
    data = r.read()
    c.close()
    return r.status, {k.lower(): v for k, v in r.getheaders()}, data


def _sse(port, body, timeout=120, trace_id=None):
    """Raw-socket SSE read: returns (status_line+headers, data lines)."""
    payload = json.dumps(body).encode()
    hdr = (b"" if trace_id is None
           else b"X-Request-Id: %s\r\n" % trace_id.encode())
    s = socket.create_connection(("127.0.0.1", port), timeout=timeout)
    s.sendall(b"POST /v1/completions HTTP/1.1\r\nHost: x\r\n"
              b"Content-Type: application/json\r\n" + hdr +
              b"Content-Length: %d\r\n\r\n%s" % (len(payload), payload))
    buf = b""
    while True:
        chunk = s.recv(65536)
        if not chunk:
            break
        buf += chunk
    s.close()
    head, _, rest = buf.partition(b"\r\n\r\n")
    lines = [ln.strip()[6:] for ln in rest.split(b"\n")
             if ln.strip().startswith(b"data: ")]
    return head.decode("latin-1"), lines


def main(argv=None):
    import numpy as np

    from paddle_tpu.inference.serving import AdmissionFull
    from paddle_tpu.serving_cluster import (Gateway, LocalReplica,
                                            Router)
    from paddle_tpu.serving_cluster import protocol as P

    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    rng = np.random.RandomState(5)
    prompt = [int(t) for t in rng.randint(1, 67, (6,))]

    # ---------------- cluster A: happy path + 400/404/504 ------------
    reps = [LocalReplica(f"replica{i}", _build_engine())
            for i in range(2)]
    router = Router(reps, policy="prefix_affinity")
    gw = Gateway(router, model_id="paddle_tpu", port=0,
                 hb_s=0.1).start_background()
    try:
        st, hd, data = _req(gw.port, "POST", "/v1/completions",
                            {"prompt": prompt, "max_tokens": 4,
                             "stop_token_id": 2})
        obj = json.loads(data)
        check(st == 200, f"completions status {st}")
        check(set(obj) == set(P.COMPLETION_FIELDS),
              f"completion fields {sorted(obj)} != "
              f"{sorted(P.COMPLETION_FIELDS)}")
        ch = obj.get("choices", [{}])[0]
        check(set(ch) == set(P.CHOICE_FIELDS),
              f"choice fields {sorted(ch)} != {sorted(P.CHOICE_FIELDS)}")
        check(set(obj.get("usage", {})) == set(P.USAGE_FIELDS),
              f"usage fields {sorted(obj.get('usage', {}))}")
        check(ch.get("finish_reason") in ("stop", "length"),
              f"finish_reason {ch.get('finish_reason')!r}")
        check(ch.get("text") == " ".join(str(t) for t in ch["tokens"]),
              "text is not the space-joined token ids")
        # trace context echo: a minted id arrives in BOTH the header
        # and the body, and they agree
        check(hd.get(P.TRACE_HEADER.lower()) == obj.get("trace_id")
              and obj.get("trace_id"),
              f"trace echo broken: header "
              f"{hd.get(P.TRACE_HEADER.lower())!r} vs body "
              f"{obj.get('trace_id')!r}")
        # ... and an INBOUND id is honored verbatim end-to-end
        st, hd, data = _req(gw.port, "POST", "/v1/completions",
                            {"prompt": prompt, "max_tokens": 2},
                            headers={P.TRACE_HEADER: "pin-trace-7"})
        obj = json.loads(data)
        check(st == 200 and obj.get("trace_id") == "pin-trace-7"
              and hd.get(P.TRACE_HEADER.lower()) == "pin-trace-7",
              f"inbound {P.TRACE_HEADER} not honored: {st} "
              f"{obj.get('trace_id')!r} {hd.get(P.TRACE_HEADER.lower())!r}")

        head, lines = _sse(gw.port, {"prompt": prompt, "max_tokens": 4,
                                     "stream": True},
                           trace_id="pin-sse-9")
        check("200 OK" in head and "text/event-stream" in head,
              f"SSE head {head!r}")
        check(f"{P.TRACE_HEADER}: pin-sse-9" in head,
              f"SSE head lacks the trace header echo: {head!r}")
        check(all(json.loads(ln).get("trace_id") == "pin-sse-9"
                  for ln in lines[:-1]),
              "SSE chunks lost the trace_id field")
        check(lines and lines[-1] == b"[DONE]",
              "SSE stream does not end with data: [DONE]")
        for ln in lines[:-1]:
            chunk = json.loads(ln)
            check(set(chunk) == set(P.STREAM_CHUNK_FIELDS),
                  f"stream chunk fields {sorted(chunk)}")
            cch = chunk["choices"][0]
            check(set(cch) == set(P.CHOICE_FIELDS),
                  f"stream choice fields {sorted(cch)}")
        reasons = [json.loads(ln)["choices"][0]["finish_reason"]
                   for ln in lines[:-1]]
        check(reasons[-1] in ("stop", "length") and
              all(r is None for r in reasons[:-1]),
              f"finish_reason placement {reasons}")

        st, _, data = _req(gw.port, "GET", "/v1/models")
        obj = json.loads(data)
        check(st == 200 and set(obj) == set(P.MODELS_FIELDS),
              f"/v1/models {st} fields {sorted(obj)}")
        entry = obj.get("data", [{}])[0]
        check(set(entry) == set(P.MODEL_ENTRY_FIELDS),
              f"model entry fields {sorted(entry)}")

        st, _, data = _req(gw.port, "GET", "/healthz")
        obj = json.loads(data)
        check(st == 200 and set(obj) == set(P.HEALTHZ_FIELDS),
              f"/healthz {st} fields {sorted(obj)}")
        check(obj.get("status") == "ok", f"healthz status {obj}")
        hz = obj.get("replicas") or {}
        check(len(hz) == obj.get("replicas_total") and all(
            set(e) == set(P.HEALTHZ_REPLICA_FIELDS)
            for e in hz.values()),
              f"healthz replica entries {hz}")
        check(all(e["verdict"] in ("healthy", "suspect", "degraded")
                  and e["breaker"] in ("closed", "open", "half_open")
                  for e in hz.values()),
              f"healthz replica vocab {hz}")

        st, hd, data = _req(gw.port, "GET", "/metrics")
        check(st == 200 and hd.get("content-type", "").startswith(
            "text/plain"), f"/metrics {st} {hd.get('content-type')}")
        text = data.decode()
        check('replica="replica0"' in text
              and 'replica="replica1"' in text,
              "/metrics lacks per-replica labels")
        check("paddle_gateway_replicas_alive" in text
              and "paddle_gateway_failovers_total" in text,
              "/metrics lacks gateway gauges")
        check('paddle_gateway_route_decisions_total{reason="'
              in text, "/metrics lacks router decision counters")
        check("paddle_gateway_http_request_seconds_completions_200"
              in text and 'replica="gateway"' in text,
              "/metrics lacks the gateway HTTP latency histograms")

        # ---- error rows, each triggered for real ----
        seen = {}

        def err(st, data, hd=None):
            obj = json.loads(data)
            # 429s grow the machine-readable `reason` field — pinned
            # to the code→reason map so clients can tell cluster
            # overload from tenant-specific throttling
            want_fields = (P.ERROR_BODY_FIELDS_429 if st == 429
                           else P.ERROR_BODY_FIELDS)
            check(set(obj) == {"error"} and
                  set(obj["error"]) == set(want_fields),
                  f"error envelope {obj}")
            code = obj["error"]["code"]
            check(P.ERROR_STATUS.get(code) == st,
                  f"code {code!r} arrived with status {st} != "
                  f"{P.ERROR_STATUS.get(code)}")
            if st == 429:
                check(obj["error"].get("reason")
                      == P.REASON_FOR_429.get(code),
                      f"429 reason {obj['error'].get('reason')!r} != "
                      f"{P.REASON_FOR_429.get(code)!r} for {code!r}")
            seen[code] = st
            return obj

        err(*_req(gw.port, "POST", "/v1/completions",
                  {"prompt": "not token ids"})[::2])
        # engine-side validation is ALSO bad_request, not 500: prompt +
        # max_tokens exceeds the replicas' ring capacity (max_seq_len
        # rounds up to Smax=128, so 120 + 20 violates it)
        err(*_req(gw.port, "POST", "/v1/completions",
                  {"prompt": list(range(1, 121)), "max_tokens": 20})[::2])
        # an explicit JSON null takes the default, never a None that
        # reaches the engine's integer comparisons
        st, _, data = _req(gw.port, "POST", "/v1/completions",
                           {"prompt": prompt, "max_tokens": None})
        check(st == 200 and len(json.loads(data)["choices"][0]["tokens"])
              == 16, f"max_tokens:null did not default to 16 ({st})")
        err(*_req(gw.port, "POST", "/v1/completions",
                  {"model": "gpt-4", "prompt": prompt})[::2])
        err(*_req(gw.port, "GET", "/v1/nope")[::2])
        err(*_req(gw.port, "POST", "/v1/completions",
                  {"prompt": prompt, "max_tokens": 4,
                   "deadline_s": 0})[::2])

        # ---- elastic admin surface ----
        st, _, data = _req(gw.port, "GET", "/admin/scale")
        obj = json.loads(data)
        check(st == 200 and set(obj) == set(P.SCALE_FIELDS),
              f"/admin/scale {st} fields {sorted(obj)} != "
              f"{sorted(P.SCALE_FIELDS)}")
        check(obj.get("autoscaler") is False
              and obj.get("min_replicas") is None,
              f"autoscaler-less scale status wrong: {obj}")
        # manual scale without an autoscaler is an honest 409 (the
        # spawn hook lives there), not a 500
        err(*_req(gw.port, "POST", "/admin/scale", {"replicas": 3})[::2])
        # draining an unknown replica -> 404 (the not_found row again)
        err(*_req(gw.port, "POST", "/admin/drain",
                  {"replica": "ghost"})[::2])
        # a REAL drain: replica1 retires gracefully (no in-flight work
        # here, so the summary is all zeros) and leaves the counts
        st, _, data = _req(gw.port, "POST", "/admin/drain",
                           {"replica": "replica1"})
        obj = json.loads(data)
        check(st == 200 and set(obj) == set(P.DRAIN_FIELDS),
              f"/admin/drain {st} fields {sorted(obj)} != "
              f"{sorted(P.DRAIN_FIELDS)}")
        st, _, data = _req(gw.port, "GET", "/healthz")
        obj = json.loads(data)
        check(st == 200 and obj.get("replicas_total") == 1,
              f"drained replica still counted: {obj}")
        # draining the LAST placeable replica is refused (409): its
        # sessions would have nowhere to migrate
        err(*_req(gw.port, "POST", "/admin/drain",
                  {"replica": "replica0"})[::2])
        st, _, data = _req(gw.port, "GET", "/admin/scale")
        obj = json.loads(data)
        check(json.loads(data).get("scale_events_down") == 1,
              f"drain did not count as a scale-down event: {obj}")
    finally:
        gw.stop()
        for r in reps:
            r.close()

    # ---------------- cluster B: 429 backpressure + 503 death --------
    # threaded=False: nothing drains the engine, so the saturation below
    # cannot race the HTTP round-trip — the 429 is deterministic
    tiny = LocalReplica("tiny", _build_engine(num_slots=1,
                                              max_pending=1),
                        threaded=False)
    router_b = Router([tiny], policy="least_loaded")
    gw_b = Gateway(router_b, port=0, hb_s=0.05).start_background()
    try:
        # saturate the only replica: slot + the 1-deep pending queue
        long_prompt = np.asarray(prompt * 4, np.int32)
        for _ in range(4):
            try:
                tiny.submit(long_prompt, max_new_tokens=40)
            except AdmissionFull:
                break
        st, hd, data = _req(gw_b.port, "POST", "/v1/completions",
                            {"prompt": prompt, "max_tokens": 2})
        obj = err(st, data)               # envelope + reason=overload
        check(obj["error"]["code"] == "admission_full",
              f"backpressure {st} {data[:120]!r}")
        # Retry-After is COMPUTED from the measured queue drain rate,
        # so its exact value depends on timing — the wire contract is
        # the documented floor/cap bounds
        ra = hd.get("retry-after", "")
        check(ra.isdigit()
              and P.RETRY_AFTER_S <= int(ra) <= P.RETRY_AFTER_MAX_S,
              f"429 Retry-After {ra!r} outside "
              f"[{P.RETRY_AFTER_S}, {P.RETRY_AFTER_MAX_S}]: {hd}")

        tiny.kill()
        deadline = time.monotonic() + 10
        while router_b.alive_names() and time.monotonic() < deadline:
            time.sleep(0.05)              # the gateway health loop
        check(not router_b.alive_names(),
              "health loop never marked the killed replica dead")
        st, _, data = _req(gw_b.port, "POST", "/v1/completions",
                           {"prompt": prompt, "max_tokens": 2})
        obj = json.loads(data)
        check(st == 503 and obj["error"]["code"] == "no_replica",
              f"dead cluster {st} {data[:120]!r}")
        seen["no_replica"] = st
        st, _, data = _req(gw_b.port, "GET", "/healthz")
        check(st == 503 and json.loads(data)["status"] == "down",
              f"dead healthz {st} {data!r}")
    finally:
        gw_b.stop()
        tiny.close()

    # ---------------- cluster C: tenant QoS admission ----------------
    # a refill rate of 0.01/s with burst 1 makes the bucket effectively
    # one-shot on the check's timescale: the second request is
    # rate-limited no matter how long the first one's compile took
    rep_c = LocalReplica("qos0", _build_engine())
    router_c = Router([rep_c], policy="least_loaded")
    gw_c = Gateway(router_c, port=0, hb_s=0.2, tenant_rate=0.01,
                   tenant_burst=1, tenant_quota=1).start_background()
    try:
        # X-Priority threads the class through gateway -> router ->
        # engine: the per-class admission counter is the proof the
        # header reached the scheduler, not just the parser
        st, _, data = _req(gw_c.port, "POST", "/v1/completions",
                           {"prompt": prompt, "max_tokens": 2},
                           headers={P.PRIORITY_HEADER: "high",
                                    P.TENANT_HEADER: "acme"})
        check(st == 200, f"priority-tagged completion failed: {st}")
        check(rep_c.engine.metrics()["requests_admitted_high"] == 1,
              "X-Priority: high never reached the engine's per-class "
              "admission counter")
        # an invalid class is the client's 400, not a silent default
        err(*_req(gw_c.port, "POST", "/v1/completions",
                  {"prompt": prompt, "priority": "platinum"})[::2])
        # acme's bucket is now empty -> 429 rate_limited, Retry-After
        # from ACME'S OWN refill time: ceil(~1/0.01) clamped to the
        # cap — strictly above the drain-rate floor an idle cluster
        # would report, which is the whole point of the tenant path
        st, hd, data = _req(gw_c.port, "POST", "/v1/completions",
                            {"prompt": prompt, "max_tokens": 2},
                            headers={P.TENANT_HEADER: "acme"})
        obj = err(st, data)
        check(obj["error"]["code"] == "rate_limited",
              f"empty bucket gave {obj['error']['code']!r}, expected "
              "rate_limited")
        ra = hd.get("retry-after", "")
        check(ra.isdigit()
              and P.RETRY_AFTER_S < int(ra) <= P.RETRY_AFTER_MAX_S,
              f"tenant 429 Retry-After {ra!r} not bucket-derived "
              f"(must be > drain floor {P.RETRY_AFTER_S}, <= cap "
              f"{P.RETRY_AFTER_MAX_S})")
        # tenant isolation: acme being throttled must not 429 anyone
        # else — and untagged requests bypass tenant admission entirely
        st, _, _ = _req(gw_c.port, "POST", "/v1/completions",
                        {"prompt": prompt, "max_tokens": 2},
                        headers={P.TENANT_HEADER: "other"})
        check(st == 200, f"tenant isolation broke: 'other' got {st}")
        st, _, _ = _req(gw_c.port, "POST", "/v1/completions",
                        {"prompt": prompt, "max_tokens": 2})
        check(st == 200, f"untagged request hit tenant limits: {st}")
        # live-request quota: while one 'bulk' request is in flight the
        # second is refused quota_exceeded (checked BEFORE the bucket,
        # so it burns no rate allowance)
        import threading as _threading
        t = _threading.Thread(
            target=_req, args=(gw_c.port, "POST", "/v1/completions",
                               {"prompt": prompt, "max_tokens": 40}),
            kwargs={"headers": {P.TENANT_HEADER: "bulk"}}, daemon=True)
        t.start()
        deadline = time.monotonic() + 30
        while not gw_c._tenant_live.get("bulk") \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        check(gw_c._tenant_live.get("bulk") == 1,
              "quota accounting never saw the in-flight request")
        st, hd, data = _req(gw_c.port, "POST", "/v1/completions",
                            {"prompt": prompt, "max_tokens": 2},
                            headers={P.TENANT_HEADER: "bulk"})
        obj = err(st, data)
        check(obj["error"]["code"] == "quota_exceeded",
              f"over-quota gave {obj['error']['code']!r}")
        check(hd.get("retry-after", "").isdigit(),
              f"quota 429 lost Retry-After: {hd}")
        t.join(timeout=60)
        # the quota admission is released when its request finishes
        deadline = time.monotonic() + 10
        while gw_c._tenant_live.get("bulk") \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        check("bulk" not in gw_c._tenant_live,
              f"quota leak: {gw_c._tenant_live}")
    finally:
        gw_c.stop()
        rep_c.close()

    # every mapped error code except `internal` must have been
    # triggered over the wire (internal == a bug path by definition)
    want = set(P.ERROR_STATUS) - {"internal"}
    check(set(seen) == want,
          f"error rows exercised {sorted(seen)} != {sorted(want)}")

    if failures:
        print("check_http_surface: FAILED")
        for f_ in failures:
            print(f"  - {f_}")
        return 1
    print(f"check_http_surface: ok ({len(P.ENDPOINTS)} endpoints, "
          f"{len(seen)} error rows pinned over live HTTP)")
    return 0


if __name__ == "__main__":
    sys.path.insert(0, REPO_ROOT)
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.exit(main())
