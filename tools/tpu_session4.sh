#!/bin/bash
# r3 session-4+ TPU window plan. Run when the tunnel is up; phases ordered
# by value-per-minute and individually timeboxed so a mid-window outage
# can't wedge anything. Results land in $OUT.
set -u
OUT=${1:-/tmp/tpu_session4}
mkdir -p "$OUT"
cd /root/repo

run() {  # run <name> <timeout_s> <cmd...>
  local name=$1 to=$2; shift 2
  echo "=== $name (timeout ${to}s) ===" | tee -a "$OUT/session.log"
  timeout "$to" "$@" > "$OUT/$name.log" 2>&1
  echo "exit=$? $(tail -c 300 "$OUT/$name.log" | tr '\n' ' ')" | tee -a "$OUT/session.log"
}

# 1. Ring-chunk kernel first on-chip validation (never Mosaic-compiled yet:
#    traced SMEM offset + vjp). Small shapes; seconds once compiled.
run ring_kernel 600 python - <<'EOF'
import numpy as np, jax, jax.numpy as jnp
from paddle_tpu.ops.pallas.ring_chunk_attention import ring_chunk_attention
B,H,Hk,S,D = 2,8,4,512,64
rng = np.random.RandomState(0)
q = jnp.asarray(rng.randn(B,H,S,D), jnp.bfloat16)
k = jnp.asarray(rng.randn(B,Hk,S,D), jnp.bfloat16)
v = jnp.asarray(rng.randn(B,Hk,S,D), jnp.bfloat16)
for off in (S, 0, -S//2):
    o, lse = ring_chunk_attention(q, k, v, off)
    g = jax.grad(lambda *a: jnp.sum(ring_chunk_attention(*a, off)[0].astype(jnp.float32)), (0,1,2))(q, k, v)
    print("off", off, "o_norm", float(jnp.linalg.norm(o.astype(jnp.float32))),
          "dq_norm", float(jnp.linalg.norm(g[0].astype(jnp.float32))))
print("RING_KERNEL_OK")
EOF

# 2. Full 5-config bench (validates scan-in-all-configs + vocab-padded
#    BERT + memory release under the new code; writes BENCH_partial.json)
run bench_all 2400 env BENCH_BUDGET_S=1500 python bench.py
cp BENCH_partial.json "$OUT/" 2>/dev/null

# 3. Decode cost localization (full / dense-attend / two-layer / short)
run decode_profile 1500 python tools/decode_profile.py

# 4. Decode ratchet refresh
run bench_decode 900 python bench_decode.py

echo "session complete" | tee -a "$OUT/session.log"
