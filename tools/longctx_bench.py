"""Long-context flash-attention ratchet (VERDICT r4 #5).

Single chip: time the Pallas flash kernel fwd+bwd at S=8k/16k (GPT-2-like
heads, bf16) and print one JSON line with ms/layer + achieved TFLOP/s.
Attention FLOPs: causal fwd 2*2*S^2*D*H*B/2; bwd ~2.5x fwd (5 dots of the
same shape vs 2).

Run on the real chip:  python tools/longctx_bench.py
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from bench import _init_devices
    jax, dev, tpu_unavailable = _init_devices()
    on_tpu = dev.platform in ("tpu", "axon")
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas import flash_attention as fa

    b, h, d = (1, 12, 64)
    seqs = [8192, 16384] if on_tpu else [512]
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    rows = []
    for s in seqs:
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(b, s, h, d), dtype)
        k = jnp.asarray(rng.randn(b, s, h, d), dtype)
        v = jnp.asarray(rng.randn(b, s, h, d), dtype)

        def loss(q, k, v):
            return jnp.sum(fa.flash_attention(
                q, k, v, causal=True).astype(jnp.float32) * 1e-3)
        g = jax.jit(jax.grad(loss, (0, 1, 2)))
        out = g(q, k, v)                       # compile + warm
        float(np.asarray(out[0]).reshape(-1)[0])
        reps = 5 if on_tpu else 2
        t0 = time.perf_counter()
        for _ in range(reps):
            out = g(q, k, v)
        float(np.asarray(out[0]).reshape(-1)[0])   # host fetch = barrier
        ms = (time.perf_counter() - t0) / reps * 1000
        # causal fwd+bwd flops (fwd 2 dots + bwd 5 dots, causal half)
        flops = 0.5 * 7 * 2 * s * s * d * h * b
        rows.append({"seq": s, "fwd_bwd_ms": round(ms, 2),
                     "tflops": round(flops / (ms / 1000) / 1e12, 1)})
        print(f"longctx: S={s} {ms:.1f} ms  "
              f"{rows[-1]['tflops']} TFLOP/s", file=sys.stderr)
    record = {"metric": "flash_attention_longctx_fwd_bwd",
              "unit": "ms/layer", "batch": b, "heads": h, "head_dim": d,
              "rows": rows, "device": str(dev)}
    if tpu_unavailable:
        record["tpu_unavailable"] = True
    print(json.dumps(record))


if __name__ == "__main__":
    main()
