"""Flash-attention tiling microbench: fwd+bwd time at the GPT-2 headline
shape per (BQ, BK) tiling, plus the composite (non-Pallas) reference.

Times ONLY the attention op (value_and_grad of a scalar readout), so a
sweep point costs seconds, not a full bench.py compile. Run when the
tunnel is up:

    python tools/attn_sweep.py            # default point grid
    PADDLE_TPU_FLASH_BQ=.. single point via env (bench.py parity)

Prints one JSON line per point to stdout; progress to stderr.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from bench import _init_devices
    jax, dev, tpu_unavailable = _init_devices()
    import jax.numpy as jnp

    b, h, s, d = (int(os.environ.get("SWEEP_B", "8")),
                  int(os.environ.get("SWEEP_H", "12")),
                  int(os.environ.get("SWEEP_S", "1024")),
                  int(os.environ.get("SWEEP_D", "64")))
    dropout_p = float(os.environ.get("SWEEP_DROPOUT", "0.1"))
    steps = int(os.environ.get("SWEEP_STEPS", "30"))

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.bfloat16)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.bfloat16)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.bfloat16)

    # fwd+bwd attention FLOPs (causal ~halves): 2 fwd dots + ~7 bwd-dot
    # equivalents over the s^2 x d volume
    full_dots = 2 + 7
    flops = full_dots * 2 * b * h * s * s * d * 0.5

    points = [(256, 256), (256, 512), (512, 256), (512, 512),
              (512, 1024), (1024, 512), (1024, 1024), (128, 512)]
    if os.environ.get("SWEEP_POINTS"):
        points = [tuple(int(x) for x in p.split("x"))
                  for p in os.environ["SWEEP_POINTS"].split(",")]

    for bq, bk in points:
        os.environ["PADDLE_TPU_FLASH_BQ"] = str(bq)
        os.environ["PADDLE_TPU_FLASH_BK"] = str(bk)
        # block sizes are read from env at TRACE time (_padded_sizes), and
        # jit caches key on function identity — loss_fn/grad_fn MUST be
        # rebuilt inside this loop so each point retraces and picks up the
        # new env. Hoisting them out would silently pin every point to the
        # first tiling.
        from paddle_tpu.ops.pallas import flash_attention as fa

        def loss_fn(q, k, v, seed):
            o = fa.flash_attention(q, k, v, causal=True,
                                   dropout_p=dropout_p, dropout_seed=seed)
            return jnp.sum(o.astype(jnp.float32))

        grad_fn = jax.jit(jax.value_and_grad(loss_fn, argnums=(0, 1, 2)),
                          static_argnums=())
        seed = jnp.zeros((), jnp.int32)
        try:
            t_c0 = time.perf_counter()
            val, grads = grad_fn(q, k, v, seed)
            float(np.asarray(val))
            compile_s = time.perf_counter() - t_c0
            t0 = time.perf_counter()
            for _ in range(steps):
                val, grads = grad_fn(q, k, v, seed)
            float(np.asarray(val))  # host fetch drains the tunnel pipeline
            dt = (time.perf_counter() - t0) / steps
            print(json.dumps({
                "bq": bq, "bk": bk, "ms": round(dt * 1e3, 3),
                "tflops_eff": round(flops / dt / 1e12, 1),
                "compile_s": round(compile_s, 1),
                "dropout": dropout_p,
            }))
        except Exception as e:
            print(json.dumps({"bq": bq, "bk": bk,
                              "error": f"{type(e).__name__}: {e}"[:200]}))
        sys.stdout.flush()
        print(f"sweep: {bq}x{bk} done", file=sys.stderr)


if __name__ == "__main__":
    main()
