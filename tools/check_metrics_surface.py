#!/usr/bin/env python
"""Metrics-surface coverage check (runnable standalone AND as a tier-1
test via tests/test_telemetry.py).

Every key ``ServingEngine.metrics()`` can emit must be covered by all
three of:

  1. ``reset_metrics`` — after a reset the key must read like a fresh
     engine's (or be on ``telemetry.RESET_EXEMPT_KEYS``: the trace spy
     and allocator state, which legitimately survive a window reset);
  2. the conftest reconciliation — ``check_serving_metrics`` in
     tests/conftest.py must mention the key (every serving test then
     exercises its invariant);
  3. the Prometheus exposition — ``telemetry.PROMETHEUS_NAMES`` must
     map the key to a stable name (or list it in
     ``telemetry.PROMETHEUS_EXEMPT_KEYS``), and the mapped name must
     actually appear in ``metrics_prometheus()`` output whenever the
     key has a value.

This makes the PR 4 bug class (a new counter silently skipping
reset_metrics) STRUCTURAL: adding a metrics key without wiring all
three surfaces fails tier-1.

Additionally pinned here: the ``telemetry_snapshot()`` SCHEMA — the
cluster router's wire payload (``SNAPSHOT_REQUIRED_KEYS`` /
``SNAPSHOT_OPTIONAL_KEYS`` / ``SNAPSHOT_SCHEMA_VERSION`` in
telemetry.py). Key drift without a version bump fails tier-1, because
the router scores replicas off this payload over rpc.

Usage: python tools/check_metrics_surface.py   (exit 0 = covered)
"""
from __future__ import annotations

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build_engine(**kw):
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.incubate.nn import FusedMultiTransformer
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.nn.layer.common import Embedding, Linear

    V, E, H, FF, L = 67, 32, 4, 64, 1
    paddle.seed(11)
    embed = Embedding(V, E)
    fmt = FusedMultiTransformer(E, H, FF, num_layers=L,
                                normalize_before=True)
    head = Linear(E, V, bias_attr=False)
    fmt.eval()
    # prefix cache ON (paged default): the widest metrics surface —
    # every key the engine can emit is present in this configuration
    rng = np.random.RandomState(5)
    args = dict(num_slots=2, max_seq_len=64, decode_chunk=2,
                prefill_cap=4, prefix_cache_blocks=8)
    args.update(kw)
    eng = ServingEngine(fmt, embed, head, **args)
    return eng, rng, V


def main(argv=None):
    from paddle_tpu.inference.telemetry import (PROMETHEUS_EXEMPT_KEYS,
                                                PROMETHEUS_NAMES,
                                                RESET_EXEMPT_KEYS)
    import numpy as np

    failures = []
    eng, rng, V = _build_engine()
    fresh = eng.metrics()
    keys = set(fresh)

    # ---- drive real traffic so every counter that CAN move has moved
    for n in (5, 9):
        eng.submit(rng.randint(1, V, (n,)).astype(np.int32),
                   max_new_tokens=3)
    eng.run()
    moved = eng.metrics()
    # exposition captured on the ACTIVE window (post-reset, derived
    # gauges like tokens_per_sec legitimately report None and vanish)
    text = eng.metrics_prometheus()

    # ---- 1. reset coverage
    eng.reset_metrics(keep_results=False)
    after = eng.metrics()
    for k in sorted(keys):
        if k in RESET_EXEMPT_KEYS:
            continue
        if after[k] != fresh[k]:
            failures.append(
                f"reset_metrics does not restore {k!r}: fresh "
                f"{fresh[k]!r} vs post-reset {after[k]!r} (cover it in "
                "reset_metrics or document it in "
                "telemetry.RESET_EXEMPT_KEYS)")

    # ---- 2. conftest reconciliation coverage (textual: the key must
    # be asserted on in check_serving_metrics)
    conftest_path = os.path.join(REPO_ROOT, "tests", "conftest.py")
    with open(conftest_path) as f:
        src = f.read()
    body = src.split("def check_serving_metrics", 1)
    if len(body) != 2:
        failures.append("tests/conftest.py lost check_serving_metrics")
        body = ["", src]
    for k in sorted(keys):
        if f'"{k}"' not in body[1]:
            failures.append(
                f"check_serving_metrics (tests/conftest.py) never "
                f"touches metrics key {k!r} — add a reconciliation or "
                "sanity assert for it")

    # ---- 3. Prometheus exposition coverage
    for k in sorted(keys):
        if k in PROMETHEUS_EXEMPT_KEYS:
            continue
        if k not in PROMETHEUS_NAMES:
            failures.append(
                f"metrics key {k!r} has no telemetry.PROMETHEUS_NAMES "
                "entry (map it to a stable name, or add it to "
                "PROMETHEUS_EXEMPT_KEYS with a reason)")
            continue
        name, typ = PROMETHEUS_NAMES[k]
        probe = f"{name}_bucket" if typ == "histogram" else name
        # a gauge currently reporting None may legitimately be absent;
        # anything the engine HAS a value for must be in the text.
        # `moved` (pre-reset) is the window where values existed.
        if moved.get(k) is not None and probe not in text:
            failures.append(
                f"metrics key {k!r} maps to {name!r} ({typ}) but the "
                "exposition does not contain it")

    # ---- 4. telemetry_snapshot() schema coverage: the snapshot is the
    # cluster router's WIRE payload (serving_cluster/router.py scores
    # replicas off it over rpc), so its key set is pinned structurally:
    # required keys all present, nothing outside required+optional, a
    # version stamp the router refuses to misread, and the whole thing
    # JSON-serializable (it crosses process boundaries)
    _check_snapshot_schema(failures, eng)

    # ---- 5. distributed-runtime registry coverage: every op kind the
    # flight recorder instruments must surface its wait-time histogram
    # under a stable name in runtime_prometheus() (and in the registry
    # snapshot flight dumps embed) once an event completes — a renamed
    # histogram would silently vanish from the rank-level exposition
    n_ops = _check_runtime_registry(failures)

    # ---- 6. SLO counter names + router decision-audit counters: the
    # goodput surface the autoscaling item will consume — dashboards
    # key on these exact strings, so they are pinned BY VALUE, not just
    # by the mapping-exists rule of section 3
    _check_slo_and_audit_surface(failures)

    # ---- 7. dispatch-kind coverage: every compiled executable the
    # serving engines actually dispatch must name itself in
    # generation.DISPATCH_KINDS — a new jit-key family without an
    # entry would silently fall through to an "unknown" label in the
    # telemetry step timeline instead of failing tier-1
    n_kinds = _check_dispatch_kinds(failures, eng)

    # ---- 8. mesh shard-gauge coverage: an mp=2 head-sharded paged
    # engine must reconcile its kv_shard_* gauges against the actual
    # pool layout, expose them in Prometheus, and dispatch ONLY
    # executable families already in DISPATCH_KINDS (the mesh reuses
    # the existing jit keys — a new family here means someone forked
    # the dispatch without registering it)
    _check_mesh_shard_surface(failures)

    # ---- 9. QoS surface: the per-class counter names (class-labeled
    # Prometheus series) are pinned BY VALUE — QoS dashboards and the
    # bench gates key on these exact strings — the class-label series
    # exist zero-valued BEFORE any traffic (the label set is
    # discoverable up front), and the v4 snapshot carries the per-class
    # queue depths + violation split the shed/autoscale paths read
    _check_qos_surface(failures)

    # ---- 10. disaggregated-serving surface: the role label (snapshot
    # + Prometheus info gauge), the handoff counters
    # kv_blocks_shipped/adopted, and the transfer-bytes histogram —
    # what the --disagg bench gates and the per-pool dashboards key
    # on; the v5 snapshot stamp keeps pre-role routers refusing the
    # payload instead of misreading it
    _check_role_surface(failures)

    if failures:
        print("check_metrics_surface: FAILED")
        for f_ in failures:
            print(f"  - {f_}")
        return 1
    print(f"check_metrics_surface: ok ({len(keys)} metrics keys covered "
          "by reset_metrics + conftest reconciliation + Prometheus "
          "exposition; snapshot schema pinned; "
          f"{n_ops} flight-recorder op histograms in the "
          "runtime registry; SLO + router-audit counter names pinned; "
          f"{n_kinds} dispatched executable families covered by "
          "generation.DISPATCH_KINDS; mp=2 shard gauges reconcile; "
          "QoS per-class series pinned + zero-initialized; disagg "
          "role/handoff surface pinned end-to-end)")
    return 0


def _check_mesh_shard_surface(failures):
    """Mesh engine probe: drive a real mp=2 head-sharded paged engine
    and reconcile the kv_shard_* gauges against the pool it actually
    allocated. Runs in-process as a tier-1 test, so fleet topology
    state is saved and restored around the probe."""
    import numpy as np

    from paddle_tpu.distributed.fleet import _fleet_state
    from paddle_tpu.distributed.fleet.base.topology import _HYBRID_GROUP
    from paddle_tpu.inference import generation
    from paddle_tpu.inference.telemetry import PROMETHEUS_NAMES
    from paddle_tpu.parallel import init_serving_mesh

    prior_hcg = _HYBRID_GROUP[0]
    prior_fleet = dict(_fleet_state)
    try:
        _HYBRID_GROUP[0] = None
        _fleet_state.update(strategy=None, hcg=None, initialized=False)
        init_serving_mesh(2)
        eng, rng, V = _build_engine()
        for n in (5, 9):
            eng.submit(rng.randint(1, V, (n,)).astype(np.int32),
                       max_new_tokens=3)
        eng.run()
        m = eng.metrics()
        if m.get("kv_shard_count") != 2:
            failures.append(
                f"mp=2 mesh engine reports kv_shard_count="
                f"{m.get('kv_shard_count')!r}, expected 2")
            return
        heads = eng.dec.fmt.num_heads
        if m["kv_shard_heads"] * m["kv_shard_count"] != heads:
            failures.append(
                f"mesh shard gauges do not reconcile: kv_shard_heads="
                f"{m['kv_shard_heads']} x kv_shard_count="
                f"{m['kv_shard_count']} != num_heads={heads}")
        pool_bytes = int(eng._caches["kv"].nbytes)
        if "sc" in eng._caches:
            pool_bytes += int(eng._caches["sc"].nbytes)
        if m["kv_shard_pool_bytes"] * m["kv_shard_count"] != pool_bytes:
            failures.append(
                f"mesh shard gauges do not reconcile: "
                f"kv_shard_pool_bytes={m['kv_shard_pool_bytes']} x "
                f"{m['kv_shard_count']} != pool bytes {pool_bytes} — "
                "per-device residency must be the dense pool / mp")
        # weight-placement gauges: this model's head/FFN axes divide
        # mp=2, so the stacks must ACTUALLY shard (per-device < dense,
        # replicated strictly smaller) and the byte identity must
        # recover the dense total computed from the arrays themselves
        import math
        if m.get("weight_shard_count") != 2:
            failures.append(
                f"mp=2 mesh engine reports weight_shard_count="
                f"{m.get('weight_shard_count')!r}, expected 2 — the "
                "stacked weights are no longer mesh-placed")
        else:
            dense_w = sum(math.prod(a.shape) * a.dtype.itemsize
                          for a in eng._weight_arrays())
            per_dev = m["weight_bytes_per_device"]
            repl = m["weight_bytes_replicated"]
            if (per_dev - repl) * 2 + repl != dense_w:
                failures.append(
                    f"weight byte identity broke: (per_device="
                    f"{per_dev} - replicated={repl}) x 2 + {repl} != "
                    f"dense {dense_w}")
            if not 0 <= repl < per_dev < dense_w:
                failures.append(
                    f"mp=2 mesh engine shards no weight bytes: "
                    f"per_device={per_dev} replicated={repl} "
                    f"dense={dense_w} — expected replicated < "
                    "per_device < dense")
            stk = eng.dec._stacked()
            qshard = stk["qkv_w"].sharding.shard_shape(
                tuple(stk["qkv_w"].shape))
            if qshard[1] * 2 != stk["qkv_w"].shape[1]:
                failures.append(
                    f"stacked qkv_w is not head-sharded on device: "
                    f"local shard {qshard} vs full "
                    f"{tuple(stk['qkv_w'].shape)}")
        # v8 quant honesty under the mesh: an int4+int8 engine's
        # gauges must report PACKED/quantized bytes (the arrays the
        # step actually dispatches), the byte identity must still
        # recover ITS dense total, and the snapshot weights block must
        # carry the quant modes the capacity planner keys on
        eng4, _rng4, _V4 = _build_engine(weight_quant="int4",
                                         kv_quant="int8")
        m4 = eng4.metrics()
        stk4 = eng4.dec._stacked()
        e_dim = int(eng4.dec.fmt.qkv_weights[0]._data.shape[-1])
        if str(stk4["f2_w"].dtype) != "int8" or \
                stk4["qkv_w"].shape[-1] * 2 != e_dim:
            failures.append(
                f"int4 engine's stacked qkv_w is not nibble-packed: "
                f"dtype={stk4['qkv_w'].dtype}, contracted axis "
                f"{stk4['qkv_w'].shape[-1]} (expected {e_dim // 2})")
        dense4 = sum(math.prod(a.shape) * a.dtype.itemsize
                     for a in eng4._weight_arrays())
        n4 = m4["weight_shard_count"]
        pd4, rp4 = (m4["weight_bytes_per_device"],
                    m4["weight_bytes_replicated"])
        if (pd4 - rp4) * n4 + rp4 != dense4:
            failures.append(
                f"int4 weight byte identity broke: (per_device={pd4} "
                f"- replicated={rp4}) x {n4} + {rp4} != quantized "
                f"dense {dense4}")
        snap4 = eng4.telemetry_snapshot()
        w4 = snap4.get("weights") or {}
        if (w4.get("weight_quant"), w4.get("kv_quant")) != \
                ("int4", "int8"):
            failures.append(
                f"v8 snapshot weights block misreports quant modes: "
                f"weight_quant={w4.get('weight_quant')!r} "
                f"kv_quant={w4.get('kv_quant')!r}, expected "
                "('int4', 'int8')")
        text = eng.metrics_prometheus()
        for k in ("kv_shard_count", "kv_shard_heads",
                  "kv_shard_pool_bytes", "weight_shard_count",
                  "weight_bytes_per_device", "weight_bytes_replicated"):
            name, _typ = PROMETHEUS_NAMES[k]
            if name not in text:
                failures.append(
                    f"mesh engine exposition lost {name!r} (metrics key "
                    f"{k!r} has a value under the mesh)")
        for fam in sorted(set(k[0] for k in eng._jit_cache), key=str):
            if fam not in generation.DISPATCH_KINDS:
                failures.append(
                    f"mesh engine dispatched executable family {fam!r} "
                    "with no generation.DISPATCH_KINDS entry — the "
                    "sharded step must reuse registered jit keys")
    finally:
        _HYBRID_GROUP[0] = prior_hcg
        _fleet_state.clear()
        _fleet_state.update(prior_fleet)


def _check_dispatch_kinds(failures, budget_eng):
    """Drive every scheduler flavor (row-aligned budget — the engine
    already driven above —, FLAT budget, legacy phase incl. the spec
    verify step) and assert each executable family that actually got
    dispatched has a DISPATCH_KINDS entry. Structural: a future PR
    adding an executable kind without registering it fails here, not
    as a silent 'unknown' timeline label."""
    import numpy as np

    from paddle_tpu.inference import generation

    seen = set(k[0] for k in budget_eng._jit_cache)
    # flat budget: the token-flattened [T] dispatch
    eng_f, rng, V = _build_engine(flat_budget=True,
                                  prefix_cache_blocks=0)
    for n in (5, 9):
        eng_f.submit(rng.randint(1, V, (n,)).astype(np.int32),
                     max_new_tokens=3)
    eng_f.run()
    seen |= set(k[0] for k in eng_f._jit_cache)
    if not any(k[0] == "flat_budget" for k in eng_f._jit_cache):
        failures.append(
            "the flat-budget engine never dispatched a 'flat_budget' "
            "executable — the dispatch-kind probe lost its flat "
            "coverage")
    # legacy phase scheduler + spec verify: bulk_admit / prefill /
    # admit_sample / decode / verify
    eng_p, rng, V = _build_engine(token_budget=0, spec_k=2,
                                  prefix_cache_blocks=0)
    for _ in range(2):
        core = rng.randint(1, V, (4,)).astype(np.int32)
        eng_p.submit(np.tile(core, 3), max_new_tokens=8)
    eng_p.run()
    seen |= set(k[0] for k in eng_p._jit_cache)
    for fam in sorted(seen, key=str):
        if fam not in generation.DISPATCH_KINDS:
            failures.append(
                f"dispatched executable family {fam!r} has no "
                "generation.DISPATCH_KINDS entry — its step-timeline "
                "kind falls through to an unknown label (register it "
                "next to the core builder)")
    for fam in ("budget", "flat_budget", "decode"):
        if fam not in seen:
            failures.append(
                f"dispatch-kind probe no longer exercises the {fam!r} "
                "executable family — it can no longer catch an "
                "unregistered kind there")
    return len(seen)


def _check_slo_and_audit_surface(failures):
    from paddle_tpu.inference.telemetry import PROMETHEUS_NAMES
    from paddle_tpu.serving_cluster.router import AUDIT_REASONS, Router

    pinned = {
        "slo_ok": ("paddle_serving_slo_ok_total", "counter"),
        "slo_violated_queue": (
            "paddle_serving_slo_violated_queue_total", "counter"),
        "slo_violated_service": (
            "paddle_serving_slo_violated_service_total", "counter"),
        "queue_p50_s": ("paddle_serving_queue_time_seconds",
                        "histogram"),
        "service_p50_s": ("paddle_serving_service_time_seconds",
                          "histogram"),
    }
    for k, want in pinned.items():
        got = PROMETHEUS_NAMES.get(k)
        if got != want:
            failures.append(
                f"SLO metrics key {k!r} maps to {got!r}, pinned "
                f"{want!r} — the goodput surface must not drift")
    # migration counters join the pinned-by-value set: the engine's
    # migrated_in/out totals are what the scale drill's zero-reprefill
    # gate and the drain dashboards key on
    mig_pinned = {
        "requests_migrated_in": (
            "paddle_serving_requests_migrated_in_total", "counter"),
        "requests_migrated_out": (
            "paddle_serving_requests_migrated_out_total", "counter"),
    }
    for k, want in mig_pinned.items():
        got = PROMETHEUS_NAMES.get(k)
        if got != want:
            failures.append(
                f"migration metrics key {k!r} maps to {got!r}, pinned "
                f"{want!r}")
    want_reasons = {"affinity_hit", "least_loaded", "round_robin",
                    "spill", "failover", "orphaned", "migrated",
                    "scale_up", "scale_down", "hedge"}
    if set(AUDIT_REASONS) != want_reasons:
        failures.append(
            f"router AUDIT_REASONS drifted: {sorted(AUDIT_REASONS)} != "
            f"{sorted(want_reasons)} (dashboards key on the reason "
            "label values)")
    # an EMPTY router still exposes every reason counter (zero-valued):
    # the label set is discoverable before any traffic flows
    text = Router([]).metrics_prometheus()
    for reason in want_reasons:
        probe = (f'paddle_gateway_route_decisions_total'
                 f'{{reason="{reason}"}}')
        if probe not in text:
            failures.append(
                f"router exposition lost the {reason!r} decision "
                f"counter ({probe} not found)")
    # ... and every elastic control-plane counter, zero-valued before
    # any scale event (migrations, aborts, per-direction scale events)
    for probe in ("paddle_gateway_migrations_total 0",
                  "paddle_gateway_migration_aborts_total 0",
                  'paddle_gateway_scale_events_total{direction="up"} 0',
                  'paddle_gateway_scale_events_total{direction="down"}'
                  " 0"):
        if probe not in text:
            failures.append(
                f"empty-router exposition lost the elastic counter "
                f"{probe.split()[0]!r}")
    # ... and the gray-failure defense surface: breaker transition
    # counters (per target state), hedge/retry-budget counters, and
    # the bucket-level gauge — all zero/full on an idle router, so the
    # chaos-drill dashboards discover the series before any failure
    for probe in ('paddle_gateway_breaker_transitions_total{to="open"}'
                  " 0",
                  'paddle_gateway_breaker_transitions_total'
                  '{to="half_open"} 0',
                  'paddle_gateway_breaker_transitions_total'
                  '{to="closed"} 0',
                  "paddle_gateway_hedges_total 0",
                  "paddle_gateway_hedge_wins_total 0",
                  "paddle_gateway_retry_budget_exhausted_total 0",
                  "paddle_gateway_retry_budget_tokens "):
        if probe not in text:
            failures.append(
                f"empty-router exposition lost the gray-failure "
                f"series {probe.split()[0]!r}")


def _check_qos_surface(failures):
    from paddle_tpu.inference.telemetry import (PROMETHEUS_NAMES,
                                                QOS_CLASSES, QOS_DEFAULT,
                                                QOS_RANK,
                                                SNAPSHOT_REQUIRED_KEYS)

    # the class vocabulary itself is wire surface: MIGRATION_FMT state
    # dicts, X-Priority values, and the label values below all use it
    if QOS_CLASSES != ("high", "normal", "low") \
            or QOS_DEFAULT != "normal":
        failures.append(
            f"QoS class vocabulary drifted: {QOS_CLASSES!r} default "
            f"{QOS_DEFAULT!r} — pinned ('high', 'normal', 'low') / "
            "'normal' (headers, parked state dicts, and label values "
            "all carry these strings)")
    if [QOS_RANK[c] for c in QOS_CLASSES] != [0, 1, 2]:
        failures.append(f"QOS_RANK no longer orders QOS_CLASSES: "
                        f"{QOS_RANK!r}")
    pinned = {
        "requests_preempted": (
            "paddle_serving_requests_preempted_total", "counter"),
        "requests_resumed": (
            "paddle_serving_requests_resumed_total", "counter"),
        "requests_parked": ("paddle_serving_requests_parked", "gauge"),
    }
    for c in QOS_CLASSES:
        pinned[f"requests_admitted_{c}"] = (
            'paddle_serving_class_requests_admitted_total'
            f'{{class="{c}"}}', "counter")
        pinned[f"tokens_emitted_{c}"] = (
            'paddle_serving_class_tokens_emitted_total'
            f'{{class="{c}"}}', "counter")
    for k, want in pinned.items():
        got = PROMETHEUS_NAMES.get(k)
        if got != want:
            failures.append(
                f"QoS metrics key {k!r} maps to {got!r}, pinned "
                f"{want!r} — the per-class surface must not drift")
    # a FRESH engine already exposes every class-labeled series,
    # zero-valued: dashboards discover the label set before traffic
    eng, _rng, _V = _build_engine()
    text = eng.metrics_prometheus()
    for k, (name, _typ) in pinned.items():
        probe = f"{name} 0"
        if probe not in text:
            failures.append(
                f"fresh-engine exposition missing zero-valued QoS "
                f"series {name!r} (metrics key {k!r})")
    # v4 snapshot: per-class queue depths (the weighted-fair / shed
    # inputs) are REQUIRED, and the slo block carries the per-class
    # queue-violation split the autoscaler scales on
    if "queue_depths" not in SNAPSHOT_REQUIRED_KEYS:
        failures.append(
            "SNAPSHOT_REQUIRED_KEYS lost 'queue_depths' — the v4 "
            "per-class backlog signal")
    snap = eng.telemetry_snapshot()
    qd = snap.get("queue_depths")
    if qd is None or set(qd) != set(QOS_CLASSES):
        failures.append(
            f"snapshot queue_depths keys {sorted(qd or ())} != "
            f"QOS_CLASSES {sorted(QOS_CLASSES)}")
    by_cls = (snap.get("slo") or {}).get("violated_queue_by_class")
    if by_cls is None or set(by_cls) != set(QOS_CLASSES):
        failures.append(
            f"snapshot slo.violated_queue_by_class keys "
            f"{sorted(by_cls or ())} != QOS_CLASSES "
            f"{sorted(QOS_CLASSES)} — the autoscaler scales on "
            "['high'] and the shed path reads the split")
    for blk in ("requests", ):
        r = snap.get(blk) or {}
        for key in ("preempted", "resumed"):
            if key not in r:
                failures.append(
                    f"snapshot {blk!r} block lost {key!r} — the "
                    "preemption accounting the drill gates read")


def _check_role_surface(failures):
    """Disagg surface probe: drive ONE real prefill->decode KV handoff
    (engine-level export/import — the same path the router's
    ``_handoff_one`` rides) and assert every series the --disagg bench
    gates and the per-pool dashboards key on actually moved."""
    import numpy as np

    from paddle_tpu.inference.telemetry import (PROMETHEUS_NAMES,
                                                SNAPSHOT_REQUIRED_KEYS,
                                                SNAPSHOT_SCHEMA_VERSION)
    from paddle_tpu.serving_cluster import protocol as P
    from paddle_tpu.serving_cluster.router import Router

    if SNAPSHOT_SCHEMA_VERSION != 8:
        failures.append(
            f"SNAPSHOT_SCHEMA_VERSION = {SNAPSHOT_SCHEMA_VERSION!r}, "
            "pinned 8 (v8 = quant modes in the weights block — bump "
            "this check deliberately alongside the schema)")
    for key in ("role", "handoff", "do_sample", "health", "weights"):
        if key not in SNAPSHOT_REQUIRED_KEYS:
            failures.append(
                f"SNAPSHOT_REQUIRED_KEYS lost {key!r} — the router's "
                "disagg placement filter, the hedge-safety gate and "
                "the capacity planner read them off the wire")
    pinned = {
        "kv_blocks_shipped": (
            "paddle_serving_kv_blocks_shipped_total", "counter"),
        "kv_blocks_adopted": (
            "paddle_serving_kv_blocks_adopted_total", "counter"),
    }
    for k, want in pinned.items():
        got = PROMETHEUS_NAMES.get(k)
        if got != want:
            failures.append(
                f"handoff metrics key {k!r} maps to {got!r}, pinned "
                f"{want!r} — the --disagg bench zero-recompute gate "
                "keys on it")
    for fld in ("roles", "handoffs_total"):
        if fld not in P.SCALE_FIELDS:
            failures.append(
                f"protocol.SCALE_FIELDS lost {fld!r} — the /scale "
                "control surface no longer reports the disagg pools")
    # one REAL handoff: the prefill-role engine runs the prompt then
    # HOLDS the session (no decode), export/import moves the KV to the
    # decode-role engine, which finishes the generation off it
    eng_p, rng, V = _build_engine(role="prefill")
    eng_d, _rng2, _V2 = _build_engine(role="decode")
    rid = eng_p.submit(rng.randint(1, V, (9,)).astype(np.int32),
                       max_new_tokens=3)
    for _ in range(64):
        if not eng_p.has_work:
            break
        eng_p.step()
    if eng_p.has_work:
        failures.append("prefill-role probe engine never quiesced — "
                        "the prompt-complete hold is broken")
        return
    state = eng_p.export_slot(rid)
    rid2 = eng_d.import_slot(state)
    eng_d.run()
    toks, done, _st = eng_d.harvest_new_tokens(rid2)
    if not done or not toks:
        failures.append(
            "decode-role engine did not finish the adopted session "
            f"(done={done}, {len(toks)} tokens) — the handoff path is "
            "not end-to-end")
    mp, md = eng_p.metrics(), eng_d.metrics()
    if mp.get("role") != "prefill" or md.get("role") != "decode":
        failures.append(
            f"engine role gauges drifted: prefill engine reports "
            f"{mp.get('role')!r}, decode engine {md.get('role')!r}")
    if not mp.get("kv_blocks_shipped"):
        failures.append(
            "prefill engine kv_blocks_shipped did not move on "
            "export_slot — the zero-recompute conservation gate reads "
            "this counter")
    if md.get("kv_blocks_adopted") != mp.get("kv_blocks_shipped"):
        failures.append(
            f"handoff counters do not reconcile: shipped "
            f"{mp.get('kv_blocks_shipped')!r} != adopted "
            f"{md.get('kv_blocks_adopted')!r} on a lossless transfer")
    snap = eng_p.telemetry_snapshot()
    if snap.get("role") != "prefill":
        failures.append(
            f"snapshot role {snap.get('role')!r} != 'prefill' — the "
            "router filters placement on this field")
    ho = snap.get("handoff") or {}
    if ho.get("kv_blocks_shipped") != mp.get("kv_blocks_shipped"):
        failures.append(
            "snapshot handoff block does not mirror the "
            "kv_blocks_shipped counter")
    text_p = eng_p.metrics_prometheus()
    probe = 'paddle_serving_role{role="prefill"} 1'
    if probe not in text_p:
        failures.append(
            f"prefill exposition lost the role info gauge ({probe!r})")
    if "paddle_serving_handoff_bytes_bucket" not in text_p:
        failures.append(
            "exposition lost the paddle_serving_handoff_bytes "
            "transfer-size histogram")
    count = [ln for ln in text_p.splitlines()
             if ln.startswith("paddle_serving_handoff_bytes_count")]
    if not count or count[0].split()[-1] == "0":
        failures.append(
            "paddle_serving_handoff_bytes recorded no observation "
            "after a real export_slot — transfer sizes are not being "
            "observed")
    # an EMPTY router still exposes the gateway handoff counter,
    # zero-valued — discoverable before any disagg traffic flows
    if "paddle_gateway_handoffs_total 0" not in \
            Router([]).metrics_prometheus():
        failures.append(
            "empty-router exposition lost "
            "'paddle_gateway_handoffs_total'")


def _check_snapshot_schema(failures, eng):
    import json

    from paddle_tpu.inference.telemetry import (SNAPSHOT_OPTIONAL_KEYS,
                                                SNAPSHOT_REQUIRED_KEYS,
                                                SNAPSHOT_SCHEMA_VERSION)
    snap = eng.telemetry_snapshot()
    if snap.get("schema_version") != SNAPSHOT_SCHEMA_VERSION:
        failures.append(
            f"telemetry_snapshot()['schema_version'] = "
            f"{snap.get('schema_version')!r} != pinned "
            f"{SNAPSHOT_SCHEMA_VERSION} — the router keys its trust on "
            "this stamp")
    missing = SNAPSHOT_REQUIRED_KEYS - set(snap)
    if missing:
        failures.append(
            f"telemetry_snapshot() lost required keys {sorted(missing)} "
            "(update telemetry.SNAPSHOT_REQUIRED_KEYS AND bump "
            "SNAPSHOT_SCHEMA_VERSION if this is intentional)")
    extra = set(snap) - SNAPSHOT_REQUIRED_KEYS - SNAPSHOT_OPTIONAL_KEYS
    if extra:
        failures.append(
            f"telemetry_snapshot() grew unpinned keys {sorted(extra)} "
            "— add them to SNAPSHOT_REQUIRED_KEYS or "
            "SNAPSHOT_OPTIONAL_KEYS and bump SNAPSHOT_SCHEMA_VERSION")
    if "kv_blocks" not in snap:
        failures.append(
            "the paged default engine's snapshot lost 'kv_blocks' — "
            "the router's pool-headroom signal")
    try:
        json.dumps(snap)
    except (TypeError, ValueError) as e:
        failures.append(f"telemetry_snapshot() is not JSON-serializable:"
                        f" {e} — it is a wire payload")


def _check_runtime_registry(failures):
    """Flight-recorder runtime-registry names: record one event per
    instrumented op kind, then assert each op's histogram appears in
    the Prometheus runtime section AND the registry snapshot."""
    from paddle_tpu.distributed.resilience import flight_recorder
    # importing the call sites registers their op kinds with the choke
    # point (the structural check in tools/check_collective_surface.py
    # asserts the decorators are actually present)
    import paddle_tpu.distributed.communication.ops        # noqa: F401
    import paddle_tpu.distributed.communication.all_reduce  # noqa: F401
    import paddle_tpu.distributed.parallel                  # noqa: F401
    from paddle_tpu.inference.telemetry import (runtime_prometheus,
                                                runtime_registry_snapshot)

    ops = flight_recorder.instrumented_ops()
    if not ops:
        failures.append("flight_recorder.instrumented_ops() is empty — "
                        "the choke-point decorators disappeared")
        return 0
    # the probe must not pollute the PROCESS-GLOBAL registry: this runs
    # in-process as a tier-1 test, and phantom ~0s observations would
    # leak into every later runtime_prometheus() reading. Only probe
    # ops whose histogram doesn't exist yet, and drop those afterwards.
    from paddle_tpu.inference.telemetry import _runtime_hists
    pre = set(_runtime_hists)
    rec = flight_recorder.FlightRecorder(ring=8, rank=0, world=1)
    try:
        for op in ops:
            if flight_recorder.runtime_hist_name(op) not in pre:
                rec.end(rec.start(op, group="default", shape=(1,),
                                  dtype="float32", nbytes=4))
        text = "\n".join(runtime_prometheus())
        snap = runtime_registry_snapshot()
        for op in ops:
            name = flight_recorder.runtime_hist_name(op)
            if f"{name}_bucket" not in text:
                failures.append(
                    f"instrumented op {op!r} has no {name!r} histogram "
                    "in runtime_prometheus() after recording an event")
            if name not in snap["histograms"]:
                failures.append(
                    f"instrumented op {op!r} missing from "
                    "runtime_registry_snapshot()['histograms']")
    finally:
        for name in set(_runtime_hists) - pre:
            del _runtime_hists[name]
    return len(ops)


if __name__ == "__main__":
    sys.path.insert(0, REPO_ROOT)
    # standalone runs must not touch the container's TPU tunnel (same
    # lever as tests/conftest.py: the config override wins over env)
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.exit(main())
