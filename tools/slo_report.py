#!/usr/bin/env python
"""Offline cluster SLO/goodput report.

Renders the trace plane's artifacts — saved ``/metrics`` expositions
and/or a merged cluster Perfetto trace (``export_cluster_trace``) —
into one operator-readable report: per-replica goodput (slo_ok rate),
violation split (queued-too-long vs slow-service — the autoscaler's
"add replicas vs the engine is slow" signal), queue/service time
percentiles estimated from the histogram buckets, router placement
reasons, and per-trace-id request journeys (attempt > 1 = failover).

Usage:
    curl -s localhost:8100/metrics > /tmp/cluster.prom
    python tools/slo_report.py --metrics /tmp/cluster.prom \
        [--trace /tmp/cluster_trace.json] [--bench BENCH_serving.json]

Import-light on purpose (stdlib + numpy via telemetry's parser): the
post-mortem tool must run on a box with no jax. Exit 0 on success, 1
when a given artifact is missing/invalid.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
from collections import defaultdict

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_LBL = re.compile(r'^(?P<fam>[a-zA-Z_:][a-zA-Z0-9_:]*)'
                  r'(?:\{(?P<labels>.*)\})?$')


def _labels(s):
    if not s:
        return {}
    return dict(re.findall(r'(\w+)="([^"]*)"', s))


def _percentile_from_buckets(buckets, q):
    """Histogram percentile estimate from cumulative (le, count) pairs
    — same linear-in-bucket interpolation as telemetry.LogHistogram,
    reconstructed from the text exposition."""
    pts = sorted(((le, c) for le, c in buckets if le != float("inf")))
    total = max((c for _, c in buckets), default=0)
    if not total:
        return None
    target = (q / 100.0) * total
    prev_le, prev_c = 0.0, 0
    for le, c in pts:
        if c >= target:
            span = c - prev_c
            frac = (target - prev_c) / span if span else 1.0
            return prev_le + frac * (le - prev_le)
        prev_le, prev_c = le, c
    return pts[-1][0] if pts else None


def report_metrics(path, out):
    from paddle_tpu.inference.telemetry import parse_prometheus
    try:
        with open(path) as f:
            samples = parse_prometheus(f.read())
    except (OSError, ValueError) as e:
        out.append(f"slo_report: cannot read metrics {path!r}: {e}")
        return 1
    per = defaultdict(dict)          # replica -> key -> value
    hists = defaultdict(list)        # (replica, family) -> [(le, cum)]
    reasons = {}
    for name, value in samples.items():
        m = _LBL.match(name)
        if not m:
            continue
        fam, lb = m.group("fam"), _labels(m.group("labels"))
        rep = lb.get("replica", "-")
        if fam == "paddle_gateway_route_decisions_total":
            reasons[lb.get("reason", "?")] = int(value)
        elif fam.endswith("_bucket") and "le" in lb:
            le = float("inf") if lb["le"] == "+Inf" else float(lb["le"])
            hists[(rep, fam[:-len("_bucket")])].append((le, value))
        elif fam in ("paddle_serving_slo_ok_total",
                     "paddle_serving_slo_violated_queue_total",
                     "paddle_serving_slo_violated_service_total",
                     "paddle_serving_requests_finished_total"):
            per[rep][fam] = int(value)

    out.append(f"== SLO / goodput ({os.path.basename(path)}) ==")
    for rep in sorted(r for r in per if per[r]):
        m = per[rep]
        ok = m.get("paddle_serving_slo_ok_total", 0)
        vq = m.get("paddle_serving_slo_violated_queue_total", 0)
        vs = m.get("paddle_serving_slo_violated_service_total", 0)
        done = ok + vq + vs
        goodput = (100.0 * ok / done) if done else None
        line = (f"  {rep}: goodput "
                + (f"{goodput:.1f}%" if goodput is not None else "n/a")
                + f" ({ok} ok, {vq} queued-too-long, {vs} slow-service"
                f" of {done})")
        # reconcile against the independent finished counter — a
        # mismatch means finished requests escaped SLO classification
        fin = m.get("paddle_serving_requests_finished_total")
        if fin is not None and fin != done:
            line += (f"  [RECONCILIATION BROKE: {done} classified != "
                     f"{fin} finished]")
        for fam, label in (
                ("paddle_serving_queue_time_seconds", "queue"),
                ("paddle_serving_service_time_seconds", "service")):
            b = hists.get((rep, fam))
            if b:
                p50 = _percentile_from_buckets(b, 50)
                p99 = _percentile_from_buckets(b, 99)
                if p50 is not None:
                    line += (f"; {label} p50/p99 "
                             f"{p50 * 1e3:.1f}/{p99 * 1e3:.1f} ms")
        out.append(line)
    if reasons:
        total = sum(reasons.values())
        out.append(f"  router decisions ({total}): " + ", ".join(
            f"{k}={v}" for k, v in sorted(reasons.items()) if v))
    return 0


def report_trace(path, out):
    from paddle_tpu.inference.telemetry import validate_chrome_trace
    try:
        doc = validate_chrome_trace(path)
    except (OSError, ValueError) as e:
        out.append(f"slo_report: invalid cluster trace {path!r}: {e}")
        return 1
    evs = doc["traceEvents"]
    pids = {e["pid"]: e["args"]["name"] for e in evs
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    journeys = defaultdict(lambda: {"spans": 0, "attempts": set(),
                                    "replicas": set(), "http": 0,
                                    "decisions": []})
    for e in evs:
        args = e.get("args") or {}
        tid = args.get("trace_id")
        if tid is None:
            continue
        j = journeys[tid]
        if e.get("pid") == 0:
            if str(e.get("name", "")).startswith("decision"):
                j["decisions"].append(args.get("reason"))
            elif e.get("ph") == "X":
                j["http"] += 1
        elif e.get("ph") == "X" and "attempt" in args:
            j["spans"] += 1
            j["attempts"].add(args["attempt"])
            j["replicas"].add(pids.get(e["pid"], e["pid"]))
    out.append(f"== cluster trace ({os.path.basename(path)}: "
               f"{len(evs)} events, {len(pids)} processes) ==")
    failovers = [t for t, j in journeys.items()
                 if j["attempts"] and max(j["attempts"]) > 1]
    out.append(f"  traced requests: {len(journeys)}; with failover "
               f"re-submits: {len(failovers)}")
    for t in sorted(failovers)[:10]:
        j = journeys[t]
        out.append(f"  {t}: attempts {sorted(j['attempts'])} over "
                   f"{sorted(j['replicas'])}; decisions "
                   f"{j['decisions']}")
    return 0


def report_bench(path, out):
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError) as e:
        out.append(f"slo_report: cannot read bench {path!r}: {e}")
        return 1
    slo = (rec.get("cluster") or {}).get("slo")
    if slo is None:
        out.append(f"slo_report: {path!r} has no cluster 'slo' block "
                   "(run bench_serving.py --cluster first)")
        return 1
    out.append(f"== BENCH cluster slo ({os.path.basename(path)}) ==")
    out.append("  " + json.dumps(slo))
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python tools/slo_report.py",
        description="offline cluster SLO/goodput report")
    ap.add_argument("--metrics", nargs="*", default=[],
                    help="saved /metrics exposition file(s)")
    ap.add_argument("--trace", default=None,
                    help="merged cluster Perfetto trace json")
    ap.add_argument("--bench", default=None,
                    help="BENCH_serving.json (reads the cluster slo "
                         "block)")
    args = ap.parse_args(argv)
    if not args.metrics and args.trace is None and args.bench is None:
        ap.print_help()
        return 1
    out, rc = [], 0
    for p in args.metrics:
        rc |= report_metrics(p, out)
    if args.trace is not None:
        rc |= report_trace(args.trace, out)
    if args.bench is not None:
        rc |= report_bench(args.bench, out)
    print("\n".join(out))
    return rc


if __name__ == "__main__":
    sys.path.insert(0, REPO_ROOT)
    sys.exit(main())
