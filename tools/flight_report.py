#!/usr/bin/env python
"""Offline cross-rank flight-dump diagnosis (runnable standalone AND
importable — the test suite calls ``main()`` in-process).

Given a directory of ``flightdump.<rank>.<generation>.json`` files (the
gang supervisor points ``PADDLE_FLIGHT_DUMP_DIR`` at its log dir, so
after a wedge the dumps sit next to the workerlogs), print the SAME
cross-rank diagnosis the supervisor's failure report emits —
``flight_recorder.diagnose_dir`` is the single shared implementation,
so this output reproduces the supervisor's byte-for-byte.

Usage:
    python tools/flight_report.py <dump_dir> [--generation N]
                                  [--world W] [--json]

Exit codes: 0 = diagnosis printed, 2 = no dumps found in the dir.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None):
    from paddle_tpu.distributed.resilience import flight_recorder

    parser = argparse.ArgumentParser("tools/flight_report.py")
    parser.add_argument("dump_dir",
                        help="directory holding flightdump.*.json "
                             "(the supervisor's log dir)")
    parser.add_argument("--generation", type=int, default=None,
                        help="restart generation to diagnose "
                             "(default: newest present)")
    parser.add_argument("--world", type=int, default=None,
                        help="gang size, to name ranks with missing "
                             "dumps (default: from the dump headers)")
    parser.add_argument("--json", action="store_true",
                        help="print the structured verdict instead of "
                             "the human text")
    args = parser.parse_args(argv)

    text, diag = flight_recorder.diagnose_dir(
        args.dump_dir, world=args.world, generation=args.generation)
    if not diag["ranks_with_dump"] and not diag["missing_dump_errors"]:
        print(f"flight_report: no flight dumps in {args.dump_dir!r} "
              "(recorder disabled, or the gang never wedged?)",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(diag, indent=2, default=str))
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.path.insert(0, REPO_ROOT)
    # standalone runs must not touch the container's TPU tunnel (same
    # lever as tests/conftest.py: the config override wins over env)
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.exit(main())
