"""VERDICT r4 #7: a >=1B-param LLaMA proxy under sharding stage-3.

Modes (combinable flags):
  * default (CPU 8-device mesh): build the 1.26B proxy under
    sharding_degree=8 stage-3 (p_g_os), run ONE tiny train step, and
    assert every parameter and optimizer moment is AT REST 1/8 per
    device — the "stage-3 placement actually works at scale" proof.
    Result: LLAMA1B_cpu_mesh.json (ok=true, 603 tensors, 1.762 GB/dev).
  * --tpu (single real chip): attempt the model single-chip. With AdamW
    the analytic table says state alone is 16.45 GB (> 16 GB v5e HBM) —
    the expected record is the OOM that drives the next fix: pod-slice
    sharding (proven by the default mode) or factored moments.
  * --adafactor: use paddle.optimizer.Adafactor (factored second
    moment) — analytic state ~7 GB, so the --tpu single-chip row is
    expected to FIT. This IS the "next fix" the AdamW OOM drives.

Run:  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
      XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python tools/llama_1b.py
      python tools/llama_1b.py --tpu --adafactor   # on the chip
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def analytic_table(n_params: int) -> dict:
    """Single-chip at-rest optimizer state, bytes: AdamW multi-precision
    vs Adafactor (the factored-moment fix the AdamW OOM drives)."""
    return {
        "adamw": {
            "params_bf16": 2 * n_params,
            "master_fp32": 4 * n_params,
            "moment1_fp32": 4 * n_params,
            "moment2_fp32": 4 * n_params,
            "state_total_gb": round(14 * n_params / 2 ** 30, 2),
        },
        "adafactor": {
            "params_bf16": 2 * n_params,
            "master_fp32": 4 * n_params,
            "row_col_stats": "~KB per matrix (negligible)",
            "state_total_gb": round(6 * n_params / 2 ** 30, 2),
        },
        "hbm_v5e_gb": 16,
    }


def main():
    tpu = "--tpu" in sys.argv
    if tpu:
        # hang-safe init via the bench harness (subprocess probe with a
        # hard timeout): a dead tunnel must fail in seconds, not burn the
        # session phase's full 40-min timeout holding the window lock.
        # This tool never donates and its caller (or a human) wants the
        # fast verdict — default to oneshot mode; an env that explicitly
        # sets it still wins.
        os.environ.setdefault("BENCH_PROBE_ONESHOT", "1")
        from bench import _init_devices
        _jax, dev, unavailable = _init_devices()
        if unavailable or dev.platform not in ("tpu", "axon"):
            print(json.dumps({"ok": False,
                              "error": "tpu_unreachable (probe)"}))
            sys.exit(3)
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.sharding import group_sharded_parallel
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.parallel import apply_shardings, shard_batch

    # TinyLlama-1.1B-shaped proxy (h2048 x 22L x 5632ff, 32k vocab)
    c = LlamaConfig(vocab_size=32000, hidden_size=2048, num_layers=22,
                    num_heads=16, intermediate_size=5632, max_position=512)
    n_dev = 1 if tpu else 8
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": n_dev}
    fleet.init(is_collective=True, strategy=strategy)

    t0 = time.time()
    paddle.seed(0)
    model = LlamaForCausalLM(c)
    if tpu:
        model.bfloat16()
    n_params = sum(p.size for p in model.parameters())
    print(f"model built: {n_params / 1e9:.3f}B params "
          f"({time.time() - t0:.0f}s)", file=sys.stderr)
    assert n_params >= 1e9, "proxy must be >= 1B params"
    # --adafactor: the factored-moment config the OOM analysis drives —
    # on the single chip, AdamW state is 16.45 GB (> HBM) but Adafactor
    # state is ~7 GB, so the 1B single-chip row becomes runnable
    if "--adafactor" in sys.argv:
        opt = paddle.optimizer.Adafactor(learning_rate=1e-4,
                                         parameters=model.parameters(),
                                         multi_precision=tpu)
    else:
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters(),
                                     multi_precision=tpu)
    model, opt, _ = group_sharded_parallel(model, opt, level="p_g_os")

    batch, seq = (1, 256) if tpu else (1, 64)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, c.vocab_size, (batch, seq + 1)).astype(np.int32)
    x = paddle.to_tensor(ids[:, :-1])
    y = paddle.to_tensor(ids[:, 1:])

    @paddle.jit.to_static
    def train_step(x, y):
        loss = model(x, labels=y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    record = {"metric": "llama_1b_stage3", "params": n_params,
              "n_devices": n_dev, "batch": batch, "seq": seq,
              "optimizer": ("Adafactor" if "--adafactor" in sys.argv
                            else "AdamW"),
              "analytic_single_chip": analytic_table(n_params)}
    try:
        train_step(x, y)            # slot-creation trace
        apply_shardings()
        x, y = shard_batch(x), shard_batch(y)
        t1 = time.time()
        loss = train_step(x, y)
        val = float(np.asarray(loss._data))
        record["loss"] = val
        record["step_s"] = round(time.time() - t1, 1)

        # at-rest placement proof: every >=1D param + moment is 1/n_dev
        # per device
        inner = opt._inner if hasattr(opt, "_inner") else opt
        state = [p for p in model.parameters() if p.ndim > 0]
        state += [t for slot in inner._accumulators.values()
                  for t in slot.values() if t.ndim > 0]
        bad, per_dev = 0, 0
        for t in state:
            shards = t._data.addressable_shards
            frac = shards[0].data.size * len({s.device for s in shards}) \
                / t._data.size
            if n_dev > 1 and not (0.99 < frac < 1.01):
                bad += 1
            per_dev += shards[0].data.nbytes
        record["state_tensors"] = len(state)
        record["misplaced"] = bad
        record["per_device_state_gb"] = round(per_dev / 2 ** 30, 3)
        record["ok"] = bool(bad == 0 and np.isfinite(val))
    except Exception as e:
        record["ok"] = False
        record["error"] = f"{type(e).__name__}: {str(e)[:400]}"
    print(json.dumps(record, default=str))


if __name__ == "__main__":
    main()
