#!/usr/bin/env python
"""Stacked-weight PartitionSpec coverage check (runnable standalone AND
as a tier-1 test via tests/test_mesh_serving.py).

The serving step's weights live in ONE stacked pytree
(``FusedDecoder._stacked``) that is placed with ``NamedSharding`` at
stack time per ``generation.STACKED_PARAM_SPECS``. This check makes
that table STRUCTURAL:

  1. key coverage, both directions — every key the stack can emit
     (fp, int8 AND int4-packed weight flavors) has an explicit spec
     entry (sharded or declared-replicated ``P()``), and the table
     carries no dead entries. A new param key without a spec fails
     tier-1 instead of silently replicating a possibly-huge tensor on
     every device.
  2. spec sanity — each entry's sharded axes fit the actual array rank
     and use only the 'mp' mesh axis (the serving mesh's weight axis).
  3. placement truth, probed on a real mp=2 mesh — every stacked array
     lands with EXACTLY its table spec: sharded keys hold 1/mp of the
     bytes per device, declared-replicated keys the full array; the
     int8/int4 scale mirrors of column-parallel weights (qkv_w_s /
     f1_w_s) shard WITH their weight, so a quantized stack cannot
     silently gather full weights on placement. The int4 stack is
     additionally checked STRUCTURALLY: every contracted axis packs to
     half length in int8 bytes, so the row-parallel 'mp' split lands
     on whole bytes (the pack-straddle guard made a tier-1 fact).

Runs in-process as a tier-1 test, so fleet topology state is saved and
restored around the mesh probe.

Usage: python tools/check_sharding_spec.py   (exit 0 = covered)
"""
from __future__ import annotations

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build_decoder():
    import paddle_tpu as paddle
    from paddle_tpu.incubate.nn import FusedMultiTransformer
    from paddle_tpu.inference.generation import FusedDecoder
    from paddle_tpu.nn.layer.common import Embedding, Linear

    V, E, H, FF, L = 64, 32, 4, 64, 2
    paddle.seed(3)
    embed = Embedding(V, E)
    fmt = FusedMultiTransformer(E, H, FF, num_layers=L,
                                normalize_before=True)
    head = Linear(E, V, bias_attr=False)
    fmt.eval()
    return FusedDecoder(fmt, embed, head, max_seq_len=64)


_MODE_VARS = ("PADDLE_TPU_DECODE_INT8_WEIGHTS",
              "PADDLE_TPU_DECODE_INT4_WEIGHTS")


def _stack_keys(dec, mode):
    """Build the decoder's stack in the given weight flavor ('fp',
    'int8' or 'int4') via the env knobs, restoring the prior env."""
    prior = {v: os.environ.get(v) for v in _MODE_VARS}
    try:
        for v in _MODE_VARS:
            os.environ.pop(v, None)
        if mode == "int8":
            os.environ["PADDLE_TPU_DECODE_INT8_WEIGHTS"] = "1"
        elif mode == "int4":
            os.environ["PADDLE_TPU_DECODE_INT4_WEIGHTS"] = "1"
        return dict(dec._stacked())
    finally:
        for v, val in prior.items():
            if val is None:
                os.environ.pop(v, None)
            else:
                os.environ[v] = val


def main(argv=None):
    import math

    from paddle_tpu.inference.generation import STACKED_PARAM_SPECS

    failures = []
    dec = _build_decoder()
    stacks = {"fp": _stack_keys(dec, "fp"),
              "int8": _stack_keys(dec, "int8"),
              "int4": _stack_keys(dec, "int4")}

    # ---- 0. int4 pack structure: two nibbles per byte along every
    # CONTRACTED axis (qkv_w/f1_w pack E, lin_w the concatenated head
    # axis, f2_w the FFN axis) — the halved axes are what make the
    # row-parallel 'mp' split fall on whole bytes, and what the byte
    # gauges' "quartered" claim rests on
    f = dec.fmt
    e_dim = int(f.qkv_weights[0]._data.shape[-1])
    ff_dim = int(f.ffn1_weights[0]._data.shape[-1])
    heads = f.num_heads * f.head_dim
    i4 = stacks["int4"]
    for k, axis, full_len in (("qkv_w", 2, e_dim), ("lin_w", 1, heads),
                              ("f1_w", 1, e_dim), ("f2_w", 1, ff_dim)):
        a = i4[k]
        if str(a.dtype) != "int8":
            failures.append(
                f"int4 stack key {k!r} has dtype {a.dtype}, expected "
                "int8 bytes holding two nibbles")
        if a.shape[axis] * 2 != full_len:
            failures.append(
                f"int4 stack key {k!r} axis {axis} is "
                f"{a.shape[axis]}, expected the packed half of "
                f"{full_len} — the contracted axis did not pack")
    for k in ("qkv_w_s", "lin_w_s", "f1_w_s", "f2_w_s"):
        if k not in i4:
            failures.append(
                f"int4 stack lost its scale mirror {k!r} — dequant "
                "cannot be applied without it")

    # ---- 1. key coverage, both directions
    emitted = set()
    for flavor, stk in stacks.items():
        emitted |= set(stk)
        for k in sorted(stk):
            if k not in STACKED_PARAM_SPECS:
                failures.append(
                    f"stacked key {k!r} ({flavor} flavor) has no "
                    "generation.STACKED_PARAM_SPECS entry — add an "
                    "explicit PartitionSpec (sharded on 'mp' or the "
                    "declared-replicated P()) so placement under a "
                    "mesh stays intentional")
    for k in sorted(set(STACKED_PARAM_SPECS) - emitted):
        failures.append(
            f"STACKED_PARAM_SPECS carries dead entry {k!r} — no weight "
            "flavor emits it; remove it (stale specs hide real "
            "coverage gaps)")

    # ---- 2. spec sanity against the real array ranks
    for flavor, stk in stacks.items():
        for k, a in sorted(stk.items()):
            spec = STACKED_PARAM_SPECS.get(k)
            if spec is None:
                continue
            for dim, names in enumerate(spec):
                if names is None:
                    continue
                if dim >= a.ndim:
                    failures.append(
                        f"spec for {k!r} shards axis {dim} but the "
                        f"{flavor} array has rank {a.ndim} "
                        f"(shape {tuple(a.shape)})")
                names = names if isinstance(names, tuple) else (names,)
                for n in names:
                    if n != "mp":
                        failures.append(
                            f"spec for {k!r} uses mesh axis {n!r} — "
                            "the serving mesh shards weights on 'mp' "
                            "only")

    # ---- 3. placement truth on a real mp=2 mesh
    from paddle_tpu.distributed.fleet import _fleet_state
    from paddle_tpu.distributed.fleet.base.topology import _HYBRID_GROUP
    from paddle_tpu.parallel import init_serving_mesh

    prior_hcg = _HYBRID_GROUP[0]
    prior_fleet = dict(_fleet_state)
    try:
        _HYBRID_GROUP[0] = None
        _fleet_state.update(strategy=None, hcg=None, initialized=False)
        mesh = init_serving_mesh(2)
        sharded_any = {}
        for flavor in ("fp", "int8", "int4"):
            stk = _stack_keys(dec, flavor)
            for k, a in sorted(stk.items()):
                spec = STACKED_PARAM_SPECS.get(k)
                if spec is None:
                    continue     # reported above
                full = tuple(a.shape)
                local = tuple(a.sharding.shard_shape(full))
                want = list(full)
                for dim, names in enumerate(spec):
                    if names is None or dim >= len(want):
                        continue
                    names = (names if isinstance(names, tuple)
                             else (names,))
                    for n in names:
                        want[dim] //= mesh.shape[n]
                if local != tuple(want):
                    failures.append(
                        f"{flavor} stack key {k!r} placed as {local} "
                        f"per device (full {full}) — its spec {spec} "
                        f"demands {tuple(want)}; the table and the "
                        "actual placement have diverged")
                sharded_any.setdefault(k, False)
                if local != full:
                    sharded_any[k] = True
        # the int8/int4 scale mirrors of column-parallel weights must
        # ride their weight's shard (the silent-gather trap)
        for k in ("qkv_w_s", "f1_w_s"):
            if k in sharded_any and not sharded_any[k]:
                failures.append(
                    f"int8 scale mirror {k!r} stayed replicated while "
                    "its column-parallel weight shards — applying it "
                    "would gather the sharded dot result every "
                    "dispatch")
        # per-device weight bytes must actually drop ~1/mp: the whole
        # point of the table
        stk = _stack_keys(dec, "fp")
        dense = sum(math.prod(a.shape) * a.dtype.itemsize
                    for a in stk.values())
        per_dev = sum(
            math.prod(a.sharding.shard_shape(tuple(a.shape)))
            * a.dtype.itemsize for a in stk.values())
        if not per_dev < dense:
            failures.append(
                f"mp=2 placement holds {per_dev} bytes per device of "
                f"a {dense}-byte dense stack — nothing sharded")
    finally:
        _HYBRID_GROUP[0] = prior_hcg
        _fleet_state.clear()
        _fleet_state.update(prior_fleet)

    if failures:
        print(f"check_sharding_spec: {len(failures)} failure(s)")
        for f_ in failures:
            print(f"  - {f_}")
        return 1
    print(
        f"check_sharding_spec: ok ({len(emitted)} stacked keys across "
        "fp+int8+int4 flavors covered by STACKED_PARAM_SPECS; specs "
        "rank-checked; int4 contracted axes pack to whole-byte halves; "
        "mp=2 placement matches the table exactly; column-parallel "
        "quant scale mirrors shard with their weights)")
    return 0


if __name__ == "__main__":
    sys.path.insert(0, REPO_ROOT)
    # standalone runs must not touch the container's TPU tunnel (same
    # lever as tests/conftest.py: the config override wins over env)
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.exit(main())
