#!/bin/bash
# One-shot TPU measurement session. Run when the tunnel is up; every phase is
# timeboxed so a mid-session outage can't wedge the driver. Results land in
# /tmp/tpu_session/. Order is by value-per-minute: headline ratchet first.
set -u
OUT=${1:-/tmp/tpu_session}
mkdir -p "$OUT"
cd /root/repo

run() {  # run <name> <timeout_s> <cmd...>
  local name=$1 to=$2; shift 2
  echo "=== $name (timeout ${to}s) ===" | tee -a "$OUT/session.log"
  timeout "$to" "$@" > "$OUT/$name.log" 2>&1
  echo "exit=$? $(tail -c 400 "$OUT/$name.log" | tr '\n' ' ')" | tee -a "$OUT/session.log"
}

# 1. Headline bench, all five configs (writes BENCH_partial.json as it goes)
run bench_all 2400 env BENCH_BUDGET_S=1500 python bench.py
cp BENCH_partial.json "$OUT/" 2>/dev/null

# 2. Donation A/B on the headline config only (historically hung the tunnel
#    backend — hard 600s timeout; a hang here must not eat the session)
run bench_donate 600 env PADDLE_TPU_DONATE=1 BENCH_ONLY=gpt2 python bench.py

# 3. Flash block sweep (fwd+bwd step time under each tiling).
#    BENCH_DONATE_PROBE=0 pins every point undonated: the 1h verdict cache
#    can expire mid-sweep and a re-probe would eat the point's timeout and
#    flip the A/B mode between tilings.
for bq in 256 512 1024; do for bk in 256 512 1024; do
  run "sweep_${bq}x${bk}" 420 env PADDLE_TPU_FLASH_BQ=$bq PADDLE_TPU_FLASH_BK=$bk \
      BENCH_DONATE_PROBE=0 BENCH_ONLY=gpt2 BENCH_STEPS=30 python bench.py
done; done

# 4. Decode ratchet
run bench_decode 900 python bench_decode.py

echo "session complete; grep tokens_per_sec $OUT/*.log" | tee -a "$OUT/session.log"
