#!/bin/bash
# Round-3 third-window TPU session. Priorities (value/minute):
#   1. full bench: headline (donated, streaming-CE + rbg-PRNG now in) +
#      bert + llama + vit (first ViT number; conv dtype fix landed)
#   2. moe ISOLATED (wedged the tunnel last window — own process + timeout)
#   3. scan-steps A/B (run_steps(8) dispatch amortization, landed unmeasured)
#   4. decode ratchet (bench_decode.py has no recorded number yet)
#   5. per-op trace profile: names the next bottleneck for the MFU push
# Each phase timeboxed; BENCH_partial.json checkpoints inside bench.py.
set -u
OUT=${1:-/tmp/tpu_session3}
mkdir -p "$OUT"
cd /root/repo

run() {  # run <name> <timeout_s> <cmd...>
  local name=$1 to=$2; shift 2
  echo "=== $name (timeout ${to}s) $(date +%H:%M:%S) ===" | tee -a "$OUT/session.log"
  timeout "$to" "$@" > "$OUT/$name.log" 2>&1
  echo "exit=$? $(tail -c 400 "$OUT/$name.log" | tr '\n' ' ')" | tee -a "$OUT/session.log"
}

# 1. headline + bert + llama + vit; moe excluded (isolated at 2)
run bench_main 1800 env BENCH_BUDGET_S=1200 BENCH_SKIP=moe python bench.py
cp BENCH_partial.json "$OUT/bench_main.json" 2>/dev/null

# 2. moe isolated so a compile wedge can't eat the session
run bench_moe 900 env BENCH_ONLY=moe BENCH_DONATE_PROBE=0 python bench.py

# 3. scan A/B on the headline config
run bench_scan 700 env BENCH_SCAN=8 BENCH_ONLY=none BENCH_DONATE_PROBE=0 \
    BENCH_STEPS=24 python bench.py

# 4. decode ratchet
run bench_decode 900 python bench_decode.py

# 5. trace profile (per-op table -> log; summary.json)
run prof_gpt2 700 env PROF_STEPS=10 PROF_MODE=trace python tools/tpu_profile.py "$OUT/prof_gpt2"

echo "session complete; grep tokens_per_sec $OUT/*.log" | tee -a "$OUT/session.log"
