"""Ablation profile of the FusedDecoder per-token decode cost.

bench_decode r3 s4 measured ~0.58 s fixed + ~10 ms/token marginal against
a ~1 ms/token memory floor; this tool isolates where the marginal cost
lives by timing compiled 64-token decode chunks with pieces swapped out:

  full         — the real chunk scan (attend kernel + cache update + head)
  dense_attend — decode-kernel dispatch gate forced off, so attention
                 runs the dense masked einsum fallback; full vs dense
                 isolates the Pallas decode kernel's share
  two_layer    — same model truncated to 2 layers (isolates per-layer
                 cost linearity: cost should be ~L/6 + fixed)
  short        — same run at tokens/8 new tokens (fixed-vs-marginal
                 split; reported as marginal_ms_per_token)

Run on TPU:  python tools/decode_profile.py
Prints one JSON line per variant to stdout; progress to stderr.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build(layers):
    import paddle_tpu as paddle
    from paddle_tpu.incubate.nn import FusedMultiTransformer
    from paddle_tpu.inference.generation import FusedDecoder
    from paddle_tpu.nn.layer.common import Embedding, Linear

    E, H, FF, V = 768, 12, 3072, 50304
    paddle.seed(0)
    embed = Embedding(V, E)
    fmt = FusedMultiTransformer(E, H, FF, num_layers=layers,
                                normalize_before=True)
    head = Linear(E, V, bias_attr=False)
    for lay in (embed, fmt, head):
        lay.bfloat16()
    fmt.eval()
    return FusedDecoder(fmt, embed, head, max_seq_len=1024)


def _time_generate(dec, batch=8, tokens=64, prompt_len=16):
    import paddle_tpu as paddle
    prompt = np.random.RandomState(0).randint(
        1, 50000, (batch, prompt_len)).astype(np.int32)
    out = dec.generate(paddle.to_tensor(prompt), max_new_tokens=tokens)
    float(np.asarray(out._data).sum())          # compile + warm
    t0 = time.perf_counter()
    out = dec.generate(paddle.to_tensor(prompt), max_new_tokens=tokens)
    float(np.asarray(out._data).sum())
    return time.perf_counter() - t0


def main():
    from bench import _init_devices
    jax, dev, tpu_unavailable = _init_devices()

    tokens = int(os.environ.get("PROF_TOKENS", "64"))
    results = {}

    dec = _build(12)
    results["full"] = _time_generate(dec, tokens=tokens)
    print(f"decode_profile: full {results['full']:.3f}s", file=sys.stderr)

    # attend lives in a closure — ablate at the module level: force the
    # decode-kernel dispatch gate off so the dense masked fallback (einsum
    # over the cache) runs instead; full vs dense isolates the Pallas
    # decode kernel's share.
    from paddle_tpu.ops.pallas import decode_attention as da
    orig_sup = da.is_supported
    da.is_supported = lambda *a, **kw: False
    try:
        dec2 = _build(12)
        results["dense_attend"] = _time_generate(dec2, tokens=tokens)
        print(f"decode_profile: dense_attend {results['dense_attend']:.3f}s",
              file=sys.stderr)
    finally:
        da.is_supported = orig_sup

    dec3 = _build(2)
    results["two_layer"] = _time_generate(dec3, tokens=tokens)
    print(f"decode_profile: two_layer {results['two_layer']:.3f}s",
          file=sys.stderr)

    # fixed-vs-marginal split at this chunk size
    short_n = max(tokens // 8, 1)
    results["short"] = _time_generate(_build(12), tokens=short_n)
    per_tok = (results["full"] - results["short"]) / max(tokens - short_n, 1)
    rec = {
        "metric": "decode_profile",
        "tokens": tokens,
        "full_s": round(results["full"], 4),
        "dense_attend_s": round(results["dense_attend"], 4),
        "two_layer_s": round(results["two_layer"], 4),
        "short8_s": round(results["short"], 4),
        "marginal_ms_per_token": round(per_tok * 1e3, 3),
        "device": str(dev),
    }
    if tpu_unavailable:
        rec["tpu_unavailable"] = True
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
