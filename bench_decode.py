"""Decode micro-bench: GPT-2-124M-shaped FusedMultiTransformer, compiled
multi-layer KV-cache decode (FusedDecoder) tokens/s on one chip.

Not the driver's headline bench (that's bench.py); run manually:
    python bench_decode.py
Prints ONE JSON line {"metric", "value", "unit", ...}.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _attention_path(dec, fmt, batch):
    """Label for the attention path the decode step ACTUALLY takes:
    env flags narrow the choice, but the kernels' own support predicates
    (shape/dtype/tiling rules on the real cache shape) decide whether the
    stacked path runs or the dense fallback does."""
    from paddle_tpu.ops.pallas.decode_attention import (
        stacked_i8_is_supported, stacked_is_supported)
    if os.environ.get("PADDLE_TPU_STACKED_KERNEL") == "0":
        return "dense-fallback"
    nh, hd = fmt.num_heads, fmt.head_dim
    dtype = fmt.qkv_weights[0]._data.dtype
    cshape = (fmt.num_layers, 2, batch, nh, dec.smax, hd)
    qshape = (batch, 1, nh, hd)
    int8 = os.environ.get("PADDLE_TPU_DECODE_INT8_CACHE") == "1"
    ok = (stacked_i8_is_supported(qshape, cshape, dtype) if int8
          else stacked_is_supported(qshape, cshape, dtype,
                                    cache_dtype=dtype))
    if not ok:
        return "dense-fallback"
    return ("stacked-write" if os.environ.get(
        "PADDLE_TPU_KERNEL_CACHE_WRITE") == "1" else "stacked")


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    # tunnel-outage-safe init (subprocess probe + CPU fallback): shared
    # with the headline bench
    from bench import _init_devices
    jax, dev, tpu_unavailable = _init_devices()
    on_tpu = dev.platform in ("tpu", "axon")

    import paddle_tpu as paddle
    from paddle_tpu.incubate.nn import FusedMultiTransformer
    from paddle_tpu.inference.generation import FusedDecoder
    from paddle_tpu.nn.layer.common import Embedding, Linear

    E, H, FF, L, V = ((768, 12, 3072, 12, 50304) if on_tpu
                      else (64, 4, 128, 2, 256))
    batch = int(os.environ.get("BENCH_BATCH", "8" if on_tpu else "2"))
    smax = int(os.environ.get("BENCH_SMAX", "1024" if on_tpu else "64"))
    new_tokens = int(os.environ.get("BENCH_TOKENS", "64" if on_tpu else "8"))

    paddle.seed(0)
    embed = Embedding(V, E)
    fmt = FusedMultiTransformer(E, H, FF, num_layers=L, normalize_before=True)
    head = Linear(E, V, bias_attr=False)
    if on_tpu:
        for lay in (embed, fmt, head):
            lay.bfloat16()
    fmt.eval()

    plen = int(os.environ.get("BENCH_PROMPT", "16"))
    # a BENCH_PROMPT longer than the ring (CPU-fallback smax is tiny)
    # must grow the ring, not assert inside generate. FusedDecoder
    # itself rounds max_seq_len up to a 128-multiple (stacked-kernel
    # tiling rule); mirror that here so the record's max_seq and the
    # _attention_path support probe see the ACTUAL ring size, not the
    # requested one (ADVICE r5: mislabeled bench rows)
    smax = max(smax, plen + new_tokens)
    smax = -(-smax // 128) * 128
    dec = FusedDecoder(fmt, embed, head, max_seq_len=smax)
    prompt = np.random.RandomState(0).randint(
        1, V, (batch, plen)).astype(np.int32)
    # BENCH_BEAMS=K times cache-backed beam search instead of greedy
    # (beams share the prefill cache; per-step reorder is one compiled
    # gather — the serving-side beam mode, r5 verdict #4 ratchet row)
    beams = int(os.environ.get("BENCH_BEAMS", "0"))
    gen_kw = dict(num_beams=beams) if beams > 1 else {}

    # warm with the SAME token count as the timed run: the chunked-scan
    # decode compiles one variant per power-of-two chunk size, and a
    # different count in warmup would leave variants to compile inside the
    # timed region. If the stacked kernel's first on-chip Mosaic compile
    # fails, retry once on the dense path instead of losing the window.
    try:
        out = dec.generate(paddle.to_tensor(prompt),
                           max_new_tokens=new_tokens, **gen_kw)
        float(np.asarray(out._data).sum())
    except Exception as e:
        if os.environ.get("PADDLE_TPU_STACKED_KERNEL") == "0":
            raise   # stacked path was already off: not its failure
        print(f"bench_decode: stacked-kernel path failed ({e}); "
              "retrying with PADDLE_TPU_STACKED_KERNEL=0", file=sys.stderr)
        os.environ["PADDLE_TPU_STACKED_KERNEL"] = "0"
        dec = FusedDecoder(fmt, embed, head, max_seq_len=smax)
        out = dec.generate(paddle.to_tensor(prompt),
                           max_new_tokens=new_tokens, **gen_kw)
        float(np.asarray(out._data).sum())

    t0 = time.perf_counter()
    out = dec.generate(paddle.to_tensor(prompt),
                       max_new_tokens=new_tokens, **gen_kw)
    float(np.asarray(out._data).sum())
    dt = time.perf_counter() - t0
    toks = batch * new_tokens * max(beams, 1)
    record = {
        "metric": "fused_decode_tokens_per_sec",
        "value": round(toks / dt, 2),
        "unit": "tokens/s",
        "batch": batch, "new_tokens": new_tokens, "max_seq": smax,
        "prompt_len": plen,
        "layers": L, "hidden": E, "device": str(dev),
        # provenance for the append-only ratchet log: int8-cache windows
        # must never be silently compared against fp-cache windows
        "cache_mode": ("int8" if os.environ.get(
            "PADDLE_TPU_DECODE_INT8_CACHE") == "1" else "fp"),
        "weight_mode": ("int8" if os.environ.get(
            "PADDLE_TPU_DECODE_INT8_WEIGHTS") == "1" else "fp"),
        "head_mode": ("int8" if os.environ.get(
            "PADDLE_TPU_DECODE_INT8_HEAD") == "1" else "fp"),
        # both the fp and int8-cache branches have write-kernel flavors,
        # so the kw flag picks between them — but only when the actual
        # shapes pass the kernel's own support predicate; a failing
        # predicate means the dense fallback ran no matter what the env
        # says (ADVICE r5: env-derived labels mislabeled bench rows)
        "attention_path": _attention_path(dec, fmt, batch),
        "num_beams": max(beams, 1),
        "prefill_mode": ("bulk" if os.environ.get(
            "PADDLE_TPU_BULK_PREFILL") == "1" else "scan"),
    }
    if tpu_unavailable:
        record["tpu_unavailable"] = True
    else:
        # decode windows join the machine-readable ratchet log too
        from bench import _append_tpu_window
        _append_tpu_window(record)
    print(json.dumps(record))


if __name__ == "__main__":
    main()
