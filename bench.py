"""Benchmark: GPT-2 124M causal-LM training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
Self-baseline protocol per BASELINE.md (reference published numbers are
unknown; vs_baseline tracks the last recorded run in bench_baseline.json).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _init_devices():
    """Initialize the JAX backend, surviving transient TPU/axon init flake.

    The axon tunnel backend can fail with UNAVAILABLE on first contact
    (BENCH_r01: rc=1, no number recorded). Retry with backoff; if the
    accelerator never comes up, fall back to CPU via jax.config (which
    wins over the baked-in JAX_PLATFORMS=axon env) so the bench still
    emits its one JSON line instead of dying.
    """
    import jax

    last_err = None
    for attempt in range(4):
        try:
            return jax, jax.devices()[0]
        except Exception as e:  # backend init failure (RuntimeError etc.)
            last_err = e
            if attempt < 3:
                time.sleep(2.0 * (attempt + 1))
    print(f"bench: accelerator init failed after retries ({last_err}); "
          f"falling back to CPU", file=sys.stderr)
    jax.config.update("jax_platforms", "cpu")
    return jax, jax.devices()[0]


def main():
    jax, dev = _init_devices()
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import gpt2_124m

    on_tpu = dev.platform in ("tpu", "axon")
    batch = int(os.environ.get("BENCH_BATCH", "8" if on_tpu else "2"))
    seq = int(os.environ.get("BENCH_SEQ", "1024" if on_tpu else "128"))
    steps = int(os.environ.get("BENCH_STEPS", "20" if on_tpu else "3"))
    warmup = int(os.environ.get("BENCH_WARMUP", "5" if on_tpu else "1"))

    paddle.seed(0)
    model = gpt2_124m()
    if on_tpu:
        model.bfloat16()  # bf16 params; fp32 master weights in AdamW
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=on_tpu)
    n_params = sum(p.size for p in model.parameters())

    rng = np.random.RandomState(0)
    ids = rng.randint(0, 50000, (batch, seq + 1)).astype(np.int32)
    x = paddle.to_tensor(ids[:, :-1])
    y = paddle.to_tensor(ids[:, 1:])

    @paddle.jit.to_static
    def train_step(x, y):
        loss = model(x, labels=y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    # First call traces with slot creation (state superset), second call
    # recompiles into the steady signature — no eager per-op compile storm.
    for _ in range(warmup):
        loss = train_step(x, y)
    float(np.asarray(loss._data))   # host fetch: drains the pipeline

    # NOTE: block_until_ready is NOT a completion barrier on the axon
    # tunnel backend (measured: it returns ~100x early). Time chained
    # chunks (each step depends on the previous via the optimizer state),
    # forcing a device->host fetch per chunk, and take the median chunk
    # rate so a mid-run recompile can't skew the number.
    chunk = max(1, steps // 5)
    chunk_times = []
    final_loss = None
    done = 0
    while done < steps:
        n = min(chunk, steps - done)
        t0 = time.perf_counter()
        for _ in range(n):
            loss = train_step(x, y)
        final_loss = float(np.asarray(loss._data))
        chunk_times.append((time.perf_counter() - t0) / n)
        done += n
    med = float(np.median(chunk_times))
    tokens_per_sec = batch * seq / med

    # MFU: dense-transformer 6·N·tokens estimate + attention term
    cfg = model.config
    flops_per_token = 6 * n_params + 12 * cfg.num_layers * cfg.hidden_size * seq
    peak_tflops = float(os.environ.get("BENCH_PEAK_TFLOPS",
                                       "197" if on_tpu else "1"))
    mfu = (flops_per_token * tokens_per_sec) / (peak_tflops * 1e12)

    baseline_path = os.path.join(os.path.dirname(__file__),
                                 "bench_baseline.json")
    vs_baseline = None
    try:
        with open(baseline_path) as f:
            prev = json.load(f).get("value")
        if prev:
            vs_baseline = round(tokens_per_sec / prev, 4)
    except Exception:
        pass

    print(json.dumps({
        "metric": "gpt2_124m_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s",
        "vs_baseline": vs_baseline,
        "mfu": round(mfu, 4),
        "median_step_s": round(med, 5),
        "batch": batch, "seq": seq, "params": n_params,
        "device": str(dev), "loss": final_loss,
    }))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:
        # Last-resort: keep the one-JSON-line contract even on an
        # unexpected failure so the driver records what went wrong
        # instead of a bare traceback with parsed=null.
        import traceback
        traceback.print_exc()
        print(json.dumps({
            "metric": "gpt2_124m_train_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/s", "vs_baseline": None,
            "error": f"{type(e).__name__}: {e}",
        }))
        sys.exit(0)
