"""Benchmark: all five BASELINE configs on one TPU chip.

Prints ONE JSON line. The top-level fields are the headline config
(GPT-2 124M train tokens/s/chip, the standing ratchet); the other four
BASELINE configs (BERT DP+AMP-O2+stage2, LLaMA-proxy mp·pp·stage3,
ViT-L/16, ERNIE-MoE EP) ride in the "configs" array of the same line,
each with its own metric/value/unit. Self-baseline protocol per
BASELINE.md (reference published numbers are unknown; vs_baseline tracks
bench_baseline.json). Per-config progress goes to stderr.

Time-budgeted BETWEEN configs: BENCH_BUDGET_S (default 1500 TPU /
420 CPU) gates whether each extra config STARTS (per-config cost
estimates); a started config runs to completion, so driver timeouts
should budget BENCH_BUDGET_S plus one config overrun. Completed results
are checkpointed to BENCH_partial.json after every config so a timeout
kill cannot lose the finished numbers.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

_T0 = time.monotonic()


def _probe_tpu(timeout_s: float) -> tuple[bool, str]:
    """Touch the TPU backend in a SUBPROCESS with a hard timeout.

    Two observed failure modes (2026-07-30) make an in-process probe
    unsafe: (a) jax.devices() can BLOCK forever when the tunnel is
    wedged, and — worse — (b) a process stuck mid-init holds the
    exclusive TPU grant, deadlocking every later attempt in any process.
    Uses Popen + poll (not subprocess.run): a child wedged in
    uninterruptible device I/O survives SIGKILL, and run()'s timeout path
    would then block in wait() forever — poll with a deadline and ABANDON
    an unreapable child instead.

    Returns (ok, kind): kind distinguishes a TIMEOUT (tunnel wedged —
    likely a real outage, cache it long) from a fast ERROR exit (endpoint
    refused / transient flake — cache it short so a recovering tunnel is
    retried within minutes, not written off for the full 10-minute TTL
    as happened in r3 s3)."""
    import subprocess
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import jax; d = jax.devices()[0]; print(d.platform)"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        start_new_session=True)
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            out = proc.stdout.read() if proc.stdout else ""
            ok = proc.returncode == 0 and out.strip() in ("tpu", "axon")
            return ok, ("ok" if ok else "error")
        time.sleep(0.5)
    proc.kill()
    for _ in range(10):  # bounded reap; abandon a D-state zombie
        if proc.poll() is not None:
            break
        time.sleep(0.5)
    return False, "timeout"


_DONATE_PROBE_SRC = """
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.models.llama import llama_tiny
paddle.seed(0)
model = llama_tiny()
opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                             parameters=model.parameters())
x = paddle.to_tensor(np.ones((2, 64), np.int32))
y = paddle.to_tensor(np.ones((2, 64), np.int32))
def step(x, y):
    loss = model(x, labels=y)
    loss.backward(); opt.step(); opt.clear_grad()
    return loss
step = paddle.jit.to_static(step, donate_state=True)
for _ in range(3):
    loss = step(x, y)
float(np.asarray(loss._data))
print("DONATE_OK")
"""


def _probe_donation(timeout_s: float) -> bool:
    """Validate donated-state stepping in a SUBPROCESS before the parent
    initializes the TPU (donation hung the tunnel backend in r2 s1; a hang
    here dies with the child, not the bench). Verdict cached 1 h so driver
    re-runs don't repay the probe."""
    import subprocess
    ok_cache, bad_cache = "/tmp/paddle_tpu_donate_ok", \
        "/tmp/paddle_tpu_donate_bad"
    now = time.time()
    for path, verdict in ((ok_cache, True), (bad_cache, False)):
        if os.path.exists(path) and now - os.path.getmtime(path) < 3600:
            print(f"bench: donation verdict cached: {verdict}",
                  file=sys.stderr)
            return verdict
    proc = subprocess.Popen([sys.executable, "-c", _DONATE_PROBE_SRC],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True,
                            start_new_session=True,
                            cwd=os.path.dirname(os.path.abspath(__file__)))
    deadline = time.monotonic() + timeout_s
    ok = False
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            out = proc.stdout.read() if proc.stdout else ""
            ok = proc.returncode == 0 and "DONATE_OK" in out
            break
        time.sleep(1.0)
    else:
        proc.kill()
        for _ in range(10):
            if proc.poll() is not None:
                break
            time.sleep(0.5)
    try:
        with open(ok_cache if ok else bad_cache, "w") as f:
            f.write(str(now))
        os.remove(bad_cache if ok else ok_cache)
    except OSError:
        pass
    print(f"bench: donation probe -> {ok}", file=sys.stderr)
    return ok


def _probe_cache_ttl(kind):
    """Seconds the probe-down verdict stays trusted, by failure kind:
    a probe TIMEOUT means the tunnel is wedged (real outages run hours —
    long TTL); a fast error or an init flake after a good probe is the
    transient class that burned an entire recovering window in r3 s3 —
    short TTL so the next bench retries within minutes."""
    return 600 if kind == "timeout" else 150


def _init_devices():
    """Initialize the JAX backend, surviving tunnel flake AND tunnel
    hangs. Probe via subprocess first (hang-safe), retry with backoff over
    ~4 minutes (outages are long), then fall back to CPU via jax.config
    (which wins over the baked-in JAX_PLATFORMS=axon env) so the bench
    still emits its one JSON line."""
    import threading

    # Probe-down cache TTL is keyed on failure KIND (r3 weak #4: a blunt
    # 600 s cache after one transient wedge sent a whole recovering
    # window to CPU fallback). timeout = tunnel wedged, likely a real
    # outage -> 600 s; error/init-flake = transient class -> 150 s.
    cache = "/tmp/paddle_tpu_probe_down"
    cached_kind, cache_age = None, None
    try:   # one try around stat+read: a sibling bench can remove the
        # cache on tunnel recovery between our stat and read (TOCTOU)
        cache_age = time.time() - os.path.getmtime(cache)
        with open(cache) as f:
            cached_kind = f.read().split()[0] or "timeout"
    except OSError:
        cached_kind, cache_age = None, None
    except IndexError:
        cached_kind = "timeout"
    ttl = _probe_cache_ttl(cached_kind)
    # oneshot mode's premise is "the CALLER probed successfully moments
    # ago" (tpu_session5 run() probes before every phase) — a stale
    # probe-down cache from an earlier flap must not override that fresh
    # evidence, so oneshot ignores the cache read entirely
    oneshot = os.environ.get("BENCH_PROBE_ONESHOT") == "1"
    if os.environ.get("BENCH_TPU_UNAVAILABLE") == "1" or (
            not oneshot and cache_age is not None and cache_age < ttl):
        age_s = f"{round(cache_age)}s" if cache_age is not None else "env"
        print(f"bench: TPU marked unavailable (env/cache "
              f"kind={cached_kind} age={age_s} ttl={ttl}s); "
              "skipping probes", file=sys.stderr)
        import jax
        jax.config.update("jax_platforms", "cpu")
        return jax, jax.devices()[0], True

    # worst case: 3×75 s probes + 60 s sleeps + 120 s init watchdog ≈ 7 min
    # before the CPU fallback; driver timeouts must budget for that.
    # BENCH_PROBE_ONESHOT=1 (session tools whose caller ALREADY probed —
    # e.g. tpu_session5 run() probes right before each phase): one probe,
    # no retry sleeps — a mid-phase tunnel death fails in ~75 s.
    delays = [0] if oneshot else [0, 15, 45]
    fail_kinds = []
    for i, delay in enumerate(delays):
        if delay:
            time.sleep(delay)
        probe_ok, probe_kind = _probe_tpu(timeout_s=75)
        if not probe_ok:
            fail_kinds.append(probe_kind)
        if probe_ok:
            # donation probe must run while NO process holds the TPU (the
            # tunnel grant is exclusive) — i.e. before our own init below
            global _DONATE_OK
            if os.environ.get("PADDLE_TPU_DONATE") == "1":
                _DONATE_OK = True   # explicit override: skip the probe
            elif not oneshot \
                    and os.environ.get("BENCH_DONATE_PROBE", "1") != "0" \
                    and _budget_left(float(os.environ.get(
                        "BENCH_BUDGET_S", "1500"))) > 900:
                # oneshot callers (llama_1b & co) never donate — the
                # up-to-420 s donation probe would undercut the fast path
                _DONATE_OK = _probe_donation(timeout_s=420)
            import jax
            # a wedge inside native init never returns to the bytecode
            # loop, so SIGALRM can't raise — a watchdog thread hard-exits
            # instead (rc=3 tells the driver "init hang", vs hanging
            # forever while holding the exclusive TPU grant)
            done = threading.Event()

            def _watchdog():
                if not done.wait(120.0):
                    print("bench: in-process TPU init hung after a good "
                          "probe; exiting(3)", file=sys.stderr)
                    os._exit(3)
            threading.Thread(target=_watchdog, daemon=True).start()
            try:
                dev = jax.devices()[0]
                done.set()
                try:
                    os.remove(cache)  # tunnel is back: clear the skip
                except OSError:
                    pass
                return jax, dev, False
            except Exception as e:
                done.set()
                fail_kinds.append("init-flake")
                print(f"bench: init after good probe failed: {e}",
                      file=sys.stderr)
        print(f"bench: TPU probe {i + 1}/{len(delays)} failed",
              file=sys.stderr)
    print("bench: accelerator unreachable; falling back to CPU (number "
          "is NOT comparable to TPU baselines)", file=sys.stderr)
    # cache kind = timeout only if EVERY failure was a wedge; any
    # fast-error or init-flake in the mix gets the short TTL. A oneshot
    # run never WRITES the cache either: its single sample lacks the
    # 3-probe consensus this classification was designed around, and a
    # 600 s cache from one flaky probe would silently send the rest of
    # the window's phases to CPU fallback.
    if not oneshot:
        kind = "timeout" if fail_kinds and all(
            k == "timeout" for k in fail_kinds) else "error"
        try:  # let sibling benches skip the probe ladder for the TTL
            with open(cache, "w") as f:
                f.write(f"{kind} {time.time()}")
        except OSError:
            pass
    import jax
    jax.config.update("jax_platforms", "cpu")
    return jax, jax.devices()[0], True


def _timed_steps(step_fn, fetch_loss, steps):
    """Median per-step seconds over chained chunks with a device→host
    fetch per chunk. NOTE: block_until_ready is NOT a completion barrier
    on the axon tunnel backend (measured: returns ~100× early) — the host
    fetch is the only reliable drain."""
    chunk = max(1, steps // 5)
    times = []
    final_loss = None
    done = 0
    while done < steps:
        n = min(chunk, steps - done)
        t0 = time.perf_counter()
        for _ in range(n):
            out = step_fn()
        final_loss = fetch_loss(out)
        times.append((time.perf_counter() - t0) / n)
        done += n
    return float(np.median(times)), final_loss


def _budget_left(budget_s):
    return budget_s - (time.monotonic() - _T0)


def _release_memory():
    """Free the previous config's HBM before the next one starts.

    Observed r3 s4: ViT-L and MoE RESOURCE_EXHAUSTED only when run AFTER
    gpt2+bert+llama in one process (each ran fine alone) — dead models'
    buffers linger until a gc pass breaks the Layer/tape reference cycles,
    and compiled executables can pin donated buffers. Configs cannot run
    in subprocesses (the tunnel's TPU grant is exclusive and the parent
    holds it), so: collect cycles, then hard-delete every remaining live
    device array. Each bench function rebuilds all state from scratch and
    reseeds (paddle.seed overwrites the global RNG key's array), so no
    cross-config array survives legitimately."""
    import gc
    gc.collect()
    try:
        import jax
        n = 0
        for a in jax.live_arrays():
            a.delete()
            n += 1
        if n:
            print(f"bench: released {n} live device arrays",
                  file=sys.stderr)
    except Exception as e:   # release is best-effort; never kill the bench
        print(f"bench: memory release failed: {e}", file=sys.stderr)


_TPU_LOG = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_tpu.json")


def _load_standing_ratchet():
    """Latest committed HEADLINE window record from BENCH_tpu.json
    (append-only, newest last; decode windows also append, so filter to
    entries carrying the 5-config array — the driver must not regress-
    gate headline MFU against a decode tokens/s record). On a CPU
    fallback this rides in the output as `standing_tpu_ratchet` so the
    driver's JSON is never information-free about TPU perf."""
    try:
        with open(_TPU_LOG) as f:
            entries = json.load(f)
        if not isinstance(entries, list):
            return None
        for e in reversed(entries):
            # BENCH_HEADLINE=0 sweep windows carry configs but a null
            # headline value — never let one become the standing ratchet
            if (isinstance(e, dict) and "configs" in e
                    and e.get("value") is not None):
                return e
        return None     # decode-only log: NO headline ratchet to report
    except (OSError, ValueError):
        return None


def _append_tpu_window(record):
    """Stamp a completed on-TPU record with the window timestamp and
    append it to BENCH_tpu.json — the one shared convention for every
    bench that logs TPU windows (bench.py, bench_decode.py)."""
    import datetime
    window = dict(record)
    window["window_utc"] = datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")
    return _append_tpu_record(window)


def _append_tpu_record(record):
    """Append a completed on-TPU bench record to BENCH_tpu.json (create if
    missing, never overwrite earlier windows). Committed to git by the
    session, this is the machine-readable ratchet log the driver and judge
    can regress-gate against (r3 verdict ask #1a)."""
    try:
        entries = []
        if os.path.exists(_TPU_LOG):
            try:
                with open(_TPU_LOG) as f:
                    entries = json.load(f)
            except ValueError:
                # corrupt (bad merge): preserve the old bytes aside and
                # start a fresh list — NEVER drop a measured TPU window
                os.replace(_TPU_LOG, _TPU_LOG + ".corrupt")
                print(f"bench: {os.path.basename(_TPU_LOG)} unparseable; "
                      "moved aside to .corrupt", file=sys.stderr)
        if not isinstance(entries, list):  # hand edit / bad merge: keep
            entries = [entries]            # the old content, don't crash
        entries.append(record)
        tmp = _TPU_LOG + ".tmp"
        with open(tmp, "w") as f:
            json.dump(entries, f, indent=1)
            f.write("\n")
        os.replace(tmp, _TPU_LOG)
        print(f"bench: appended TPU window record #{len(entries)} to "
              f"{os.path.basename(_TPU_LOG)}", file=sys.stderr)
        return True
    except (OSError, ValueError) as e:
        print(f"bench: could not append TPU record: {e}", file=sys.stderr)
        return False


_DONATE_OK = False  # set by _init_devices after a successful probe


def _first_call_watchdog(enabled, timeout_s=900.0):
    """Guard the first (compiling) call of a donated step: the donation
    probe validates the mechanism on a tiny model, but a big-model-only
    hang would wedge the bench while it holds the exclusive TPU grant.
    On timeout: poison the donation cache so the driver's retry runs
    undonated, then exit(3) like the init watchdog. Returns a disarm
    callable; call it after the first step's host fetch."""
    if not enabled:
        return lambda: None
    import threading
    done = threading.Event()

    def _watch():
        if not done.wait(timeout_s):
            try:
                with open("/tmp/paddle_tpu_donate_bad", "w") as f:
                    f.write(str(time.time()))
                os.remove("/tmp/paddle_tpu_donate_ok")
            except OSError:
                pass
            print("bench: donated step hung on first call; poisoned "
                  "donation cache for the retry; exiting(3)",
                  file=sys.stderr)
            os._exit(3)
    threading.Thread(target=_watch, daemon=True).start()
    return done.set


def _warm(train_step, args, n, donate):
    """Warmup calls with the donation first-call watchdog armed; the
    watchdog is ALWAYS disarmed on exit — a warmup exception is a failure
    the per-config retry handles, not a hang, and an orphaned watchdog
    would poison the donation cache and exit(3) a healthy later config."""
    disarm = _first_call_watchdog(donate)
    try:
        for _ in range(n):
            loss = train_step(*args)
        float(np.asarray(loss._data))   # host fetch: drains the pipeline
    finally:
        disarm()


_BUDGET_S = [1500.0]   # set by main(); scan gating reads it


def _timed_train(train_step, args, make_stacked, steps, scan_k):
    """Median per-step seconds for a compiled train step, scan-amortized
    when scan_k > 0 (k steps per device program via run_steps).
    make_stacked() builds the [k, ...]-stacked per-step batches — called
    only on the scan path so BENCH_SCAN=0 A/B runs don't upload unused
    device buffers. Returns (med_s, loss).

    The scan wrapper costs a SECOND compile (~1-3 min healthy; an
    unhealthy tunnel can wedge it far longer — observed 25 min on a
    dying remote-compile endpoint, r3 s4). If the remaining budget can't
    absorb that, fall back to plain per-step timing: a slightly worse
    number for this config beats starving the configs after it.
    Returns (med_s, loss, effective_scan_k) — callers MUST record the
    returned scan_k, not the requested one, so per-dispatch fallback
    runs are distinguishable in the JSON."""
    if scan_k > 0 and _budget_left(_BUDGET_S[0]) < 300:
        print("bench: scan skipped (budget) — per-dispatch timing",
              file=sys.stderr)
        scan_k = 0
    if scan_k > 0:
        stacked_args = make_stacked()
        out = train_step.run_steps(scan_k, *stacked_args)  # compile + warm
        float(np.asarray(out._data[-1]))
        med_chunk, loss = _timed_steps(
            lambda: train_step.run_steps(scan_k, *stacked_args),
            lambda o: float(np.asarray(o._data[-1])),
            max(steps // scan_k, 3))
        return med_chunk / scan_k, loss, scan_k
    med, loss = _timed_steps(lambda: train_step(*args),
                             lambda out: float(np.asarray(out._data)), steps)
    return med, loss, 0


# --------------------------------------------------------------------------
# configs[0] — GPT-2 124M single-chip train (headline / ratchet)
# --------------------------------------------------------------------------

def bench_gpt2(on_tpu, peak_tflops):
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import gpt2_124m, gpt2_tiny

    batch = int(os.environ.get("BENCH_BATCH", "8" if on_tpu else "2"))
    seq = int(os.environ.get("BENCH_SEQ", "1024" if on_tpu else "128"))
    steps = int(os.environ.get("BENCH_STEPS", "20" if on_tpu else "3"))
    warmup = int(os.environ.get("BENCH_WARMUP", "3" if on_tpu else "1"))

    paddle.seed(0)
    # CPU fallback is a SMOKE config (r3 verdict weak #1): the full 124M
    # model at 2.9 s/step ate the whole CPU budget and starved the other
    # four configs; a tiny model exercises the identical code path and the
    # number is non-comparable either way (tpu_unavailable is flagged, and
    # standing_tpu_ratchet carries the real signal).
    model = gpt2_124m() if on_tpu else gpt2_tiny()
    vocab = min(model.config.vocab_size, 50000)  # real-token range (pad
    # rows above 50256 are never sampled; tiny model samples its own 1024)
    if on_tpu:
        model.bfloat16()  # bf16 params; fp32 master weights in AdamW
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=on_tpu)
    n_params = sum(p.size for p in model.parameters())

    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (batch, seq + 1)).astype(np.int32)
    x = paddle.to_tensor(ids[:, :-1])
    y = paddle.to_tensor(ids[:, 1:])

    def _step(x, y):
        loss = model(x, labels=y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    donate = _DONATE_OK and on_tpu
    train_step = paddle.jit.to_static(_step, donate_state=donate)

    # First call traces with slot creation (state superset), second call
    # recompiles into the steady signature — no eager per-op compile storm.
    _warm(train_step, (x, y), warmup, donate)

    # default on TPU: 8 steps per device program (lax.scan over the step) —
    # the tunnel backend pays a host RPC per dispatch, worth ~6.5 ms/step
    # at the headline shape (measured r3 s4: 98.2 → 91.7 ms/step).
    # Distinct batches per step, stacked on a [k, ...] leading axis.
    scan_k = int(os.environ.get("BENCH_SCAN", "8" if on_tpu else "0"))

    def make_stacked():
        sids = rng.randint(0, vocab,
                           (scan_k, batch, seq + 1)).astype(np.int32)
        return (paddle.to_tensor(sids[:, :, :-1]),
                paddle.to_tensor(sids[:, :, 1:]))
    med, final_loss, scan_k = _timed_train(train_step, (x, y),
                                           make_stacked, steps, scan_k)
    tokens_per_sec = batch * seq / med

    cfg = model.config
    flops_per_token = 6 * n_params + 12 * cfg.num_layers * cfg.hidden_size * seq
    mfu = (flops_per_token * tokens_per_sec) / (peak_tflops * 1e12)

    return {
        "metric": "gpt2_124m_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s",
        "mfu": round(mfu, 4),
        "median_step_s": round(med, 5),
        "batch": batch, "seq": seq, "params": n_params,
        "loss": final_loss,
        "donated": donate,
        "warmup": warmup,   # methodology field: r4 default drops 5 -> 3
        **({"scan_steps": scan_k} if scan_k > 0 else {}),
    }


# --------------------------------------------------------------------------
# configs[1] — BERT-base pretrain, DP + AMP-O2 + GroupSharded stage2
# --------------------------------------------------------------------------

def bench_bert(on_tpu, peak_tflops):
    import paddle_tpu as paddle
    from paddle_tpu.models.bert import (BertForPretraining, bert_base,
                                        bert_tiny)
    from paddle_tpu.distributed.sharding import group_sharded_parallel

    batch = int(os.environ.get("BENCH_BERT_BATCH", "16" if on_tpu else "2"))
    seq = int(os.environ.get("BENCH_BERT_SEQ", "512" if on_tpu else "64"))
    steps = 10 if on_tpu else 2

    paddle.seed(0)
    # vocab padded 30522 -> 30720 (240x128): MXU lane alignment for the
    # MLM decoder matmul, same trick as GPT-2's 50304 default; ids and
    # labels are sampled from the REAL 30522 vocab below so no token or
    # MLM target ever indexes the 198 pad slots (MFU still counts the pad
    # rows — they are multiplied whether or not they are ever the target)
    model = BertForPretraining(bert_base(vocab_size=30720) if on_tpu
                               else bert_tiny())
    real_vocab = 30522 if on_tpu else None  # None -> model's own (tiny)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    # AMP-O2: bf16 params + fp32 master weights (the reference's fp16-O2
    # on TPU hardware terms), stage-2 = optimizer+grad sharding specs
    model, opt = paddle.amp.decorate(model, opt, level="O2",
                                     dtype="bfloat16")
    if os.environ.get("BENCH_BERT_PLAIN") != "1":
        # BENCH_BERT_PLAIN=1: drop the stage-2 wrapper (keep AMP-O2) —
        # isolates what the sharding machinery costs at world=1
        model, opt, _ = group_sharded_parallel(model, opt, level="os_g")
    n_params = sum(p.size for p in model.parameters())

    rng = np.random.RandomState(0)
    vocab = real_vocab or (model._layers.config.vocab_size
                           if hasattr(model, "_layers")
                           else model.config.vocab_size)
    ids = rng.randint(0, vocab, (batch, seq)).astype(np.int32)
    labels = ids.copy()
    labels[rng.rand(*labels.shape) > 0.15] = -100  # MLM: 15% predicted
    x = paddle.to_tensor(ids)
    y = paddle.to_tensor(labels)
    nsp = paddle.to_tensor(rng.randint(0, 2, (batch,)).astype(np.int32))

    def _step(x, y, nsp):
        with paddle.amp.auto_cast(level="O2", dtype="bfloat16"):
            loss = model(x, masked_lm_labels=y, next_sentence_labels=nsp)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    donate = _DONATE_OK and on_tpu
    train_step = paddle.jit.to_static(_step, donate_state=donate)
    _warm(train_step, (x, y, nsp), 3 if on_tpu else 1, donate)

    scan_k = int(os.environ.get("BENCH_SCAN", "8" if on_tpu else "0"))

    def make_stacked():
        sids = rng.randint(0, vocab, (scan_k, batch, seq)).astype(np.int32)
        slabels = sids.copy()
        slabels[rng.rand(*slabels.shape) > 0.15] = -100
        return (paddle.to_tensor(sids), paddle.to_tensor(slabels),
                paddle.to_tensor(rng.randint(
                    0, 2, (scan_k, batch)).astype(np.int32)))
    med, final_loss, scan_k = _timed_train(train_step, (x, y, nsp),
                                           make_stacked, steps, scan_k)
    tokens_per_sec = batch * seq / med
    mfu = (6 * n_params * tokens_per_sec) / (peak_tflops * 1e12)
    return {
        "metric": "bert_base_amp_o2_stage2_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2), "unit": "tokens/s",
        "mfu": round(mfu, 4), "median_step_s": round(med, 5),
        "batch": batch, "seq": seq, "params": n_params,
        "loss": final_loss,
    }


# --------------------------------------------------------------------------
# configs[2] — LLaMA proxy under Fleet hybrid mp·pp·stage3 (single-chip
# degrees collapse to 1; the 8-device composition is proven by
# dryrun_multichip phase 5 + tests/test_hybrid_composition.py)
# --------------------------------------------------------------------------

def bench_llama(on_tpu, peak_tflops):
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.distributed.sharding import group_sharded_parallel

    if on_tpu:
        # ~350M proxy of the 7B architecture, scaled to one v5e chip
        c = LlamaConfig(vocab_size=32000, hidden_size=1024, num_layers=16,
                        num_heads=16, intermediate_size=2816,
                        max_position=1024)
        batch, seq, steps = 8, 1024, 10
    else:
        c = LlamaConfig(vocab_size=256, hidden_size=64, num_layers=2,
                        num_heads=4, intermediate_size=128, max_position=128)
        batch, seq, steps = 2, 64, 2

    paddle.seed(0)
    model = LlamaForCausalLM(c)
    if on_tpu:
        model.bfloat16()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=on_tpu)
    if os.environ.get("BENCH_LLAMA_PLAIN") != "1":
        # BENCH_LLAMA_PLAIN=1: drop the stage-3 wrapper — isolates what
        # param/grad resharding costs at world=1 (llama's MFU laggard
        # hunt; the 8-dev composition is proven by dryrun_multichip)
        model, opt, _ = group_sharded_parallel(model, opt, level="p_g_os")
    n_params = sum(p.size for p in model.parameters())

    rng = np.random.RandomState(0)
    ids = rng.randint(0, c.vocab_size, (batch, seq + 1)).astype(np.int32)
    x = paddle.to_tensor(ids[:, :-1])
    y = paddle.to_tensor(ids[:, 1:])

    def _step(x, y):
        loss = model(x, labels=y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    donate = _DONATE_OK and on_tpu
    train_step = paddle.jit.to_static(_step, donate_state=donate)
    _warm(train_step, (x, y), 3 if on_tpu else 1, donate)

    scan_k = int(os.environ.get("BENCH_SCAN", "8" if on_tpu else "0"))

    def make_stacked():
        sids = rng.randint(0, c.vocab_size,
                           (scan_k, batch, seq + 1)).astype(np.int32)
        return (paddle.to_tensor(sids[:, :, :-1]),
                paddle.to_tensor(sids[:, :, 1:]))
    med, final_loss, scan_k = _timed_train(train_step, (x, y),
                                           make_stacked, steps, scan_k)
    tokens_per_sec = batch * seq / med
    flops_per_token = 6 * n_params + 12 * c.num_layers * c.hidden_size * seq
    mfu = (flops_per_token * tokens_per_sec) / (peak_tflops * 1e12)
    return {
        "metric": "llama_proxy_stage3_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2), "unit": "tokens/s",
        "mfu": round(mfu, 4), "median_step_s": round(med, 5),
        "batch": batch, "seq": seq, "params": n_params,
        "loss": final_loss,
    }


# --------------------------------------------------------------------------
# configs[3] — ViT-L/16 ImageNet-shaped classification train
# --------------------------------------------------------------------------

def bench_vit(on_tpu, peak_tflops):
    import paddle_tpu as paddle
    from paddle_tpu.models.vit import vit_l_16, vit_tiny

    paddle.seed(0)   # BEFORE model build: initializers draw from the key
    if on_tpu:
        # recompute: ViT-L b32 saved-residuals OOMed the tunnel chip twice
        # (r3 s3) — remat the 24 blocks, trading ~1/3 extra FLOPs for O(1)
        # per-block activation memory. BENCH_VIT_REMAT: "1" every block
        # (default), N>=2 every Nth block, "0" none — the granular-remat
        # A/B (the OOM predates the r3s4 cross-config HBM hygiene).
        # int semantics match ViT.forward exactly: 0 = none, 1 = every
        # block, N>=2 = every Nth block
        model = vit_l_16(
            recompute=int(os.environ.get("BENCH_VIT_REMAT", "1")))
        batch, size, steps = int(os.environ.get("BENCH_VIT_BATCH", "32")), \
            224, 10
    else:
        model = vit_tiny()
        batch, size, steps = 2, 32, 2

    if on_tpu:
        model.bfloat16()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=on_tpu)
    n_params = sum(p.size for p in model.parameters())

    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(batch, 3, size, size).astype(np.float32))
    if on_tpu:
        x = x.astype("bfloat16")   # match the bf16 params: conv on the MXU
    y = paddle.to_tensor(rng.randint(
        0, 10, (batch,)).astype(np.int32))

    def _step(x, y):
        logits = model(x)
        loss = paddle.nn.functional.cross_entropy(logits, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    donate = _DONATE_OK and on_tpu
    train_step = paddle.jit.to_static(_step, donate_state=donate)
    _warm(train_step, (x, y), 3 if on_tpu else 1, donate)

    # scan capped at 4: the stacked image batches are the one large input
    # ([k, B, 3, 224, 224]); k=8 would hold ~150 MB of inputs resident
    scan_k = min(int(os.environ.get("BENCH_SCAN", "4" if on_tpu else "0")), 4)

    def make_stacked():
        sx = rng.randn(scan_k, batch, 3, size, size).astype(np.float32)
        xs = paddle.to_tensor(sx)
        if on_tpu:
            xs = xs.astype("bfloat16")
        return (xs, paddle.to_tensor(
            rng.randint(0, 10, (scan_k, batch)).astype(np.int32)))
    med, final_loss, scan_k = _timed_train(train_step, (x, y),
                                           make_stacked, steps, scan_k)
    images_per_sec = batch / med
    # ViT-L/16 fwd ≈ 61 GFLOPs/image at 224², train ≈ 3×
    flops_per_image = (61e9 * 3) if on_tpu else (6 * n_params)
    mfu = (flops_per_image * images_per_sec) / (peak_tflops * 1e12)
    return {
        "metric": "vit_l16_train_images_per_sec_per_chip",
        "value": round(images_per_sec, 2), "unit": "images/s",
        "mfu": round(mfu, 4), "median_step_s": round(med, 5),
        "batch": batch, "image_size": size, "params": n_params,
        "loss": final_loss,
    }


# --------------------------------------------------------------------------
# configs[4] — ERNIE-MoE expert-parallel train step
# --------------------------------------------------------------------------

def bench_moe(on_tpu, peak_tflops):
    import paddle_tpu as paddle
    from paddle_tpu.models.moe import ErnieMoEConfig, ErnieMoEForCausalLM

    if on_tpu:
        c = ErnieMoEConfig(vocab_size=30000, hidden_size=768, num_layers=6,
                           num_heads=12, intermediate_size=3072,
                           num_experts=8, max_position=1024, dropout=0.0)
        batch, seq, steps = 8, 512, 10
    else:
        c = ErnieMoEConfig(vocab_size=512, hidden_size=64, num_layers=2,
                           num_heads=2, intermediate_size=128,
                           num_experts=4, max_position=128, dropout=0.0)
        batch, seq, steps = 2, 32, 2

    paddle.seed(0)
    model = ErnieMoEForCausalLM(c)
    if on_tpu:
        model.bfloat16()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=on_tpu)
    n_params = sum(p.size for p in model.parameters())

    rng = np.random.RandomState(0)
    ids = rng.randint(0, c.vocab_size, (batch, seq + 1)).astype(np.int32)
    x = paddle.to_tensor(ids[:, :-1])
    y = paddle.to_tensor(ids[:, 1:])

    def _step(x, y):
        loss = model(x, labels=y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    donate = _DONATE_OK and on_tpu
    train_step = paddle.jit.to_static(_step, donate_state=donate)
    _warm(train_step, (x, y), 3 if on_tpu else 1, donate)

    scan_k = int(os.environ.get("BENCH_SCAN", "8" if on_tpu else "0"))

    def make_stacked():
        sids = rng.randint(0, c.vocab_size,
                           (scan_k, batch, seq + 1)).astype(np.int32)
        return (paddle.to_tensor(sids[:, :, :-1]),
                paddle.to_tensor(sids[:, :, 1:]))
    med, final_loss, scan_k = _timed_train(train_step, (x, y),
                                           make_stacked, steps, scan_k)
    tokens_per_sec = batch * seq / med

    # MFU from the COMPUTED flops (capacity-padded expert compute, the
    # flops the chip actually runs): per token fwd = attn block matmuls
    # + dense-FFN layers + (E·C/S)-weighted expert FFN + tied LM head.
    e_dim, i_dim = c.hidden_size, c.intermediate_size
    # the gate's own capacity rule — not a re-derivation that could drift
    cap = next(blk.ffn.gate for blk in model.blocks
               if blk.use_moe).capacity(seq)
    n_moe = sum(1 for i in range(c.num_layers)
                if i % c.moe_every == c.moe_every - 1)
    n_dense = c.num_layers - n_moe
    per_tok_fwd = (
        c.num_layers * (8 * e_dim * e_dim + 4 * seq * e_dim)   # attn+proj
        + n_dense * 4 * e_dim * i_dim                          # dense FFN
        + n_moe * (c.num_experts * cap / seq) * 4 * e_dim * i_dim
        + 2 * e_dim * c.vocab_size)                            # LM head
    mfu = (3 * per_tok_fwd * tokens_per_sec) / (peak_tflops * 1e12)

    # decomposition (BASELINE configs[4]'s real metric): identity-dispatch
    # twin keeps the expert compute identical but removes gate + dispatch/
    # combine einsums (the alltoall path under EP) — the delta IS the
    # dispatch cost. BOTH sides of the subtraction are timed PER-DISPATCH
    # (the main `med` above is scan-amortized on TPU; subtracting a
    # per-dispatch twin from it would fold the ~6.5 ms tunnel RPC into
    # the delta and could even go negative). Two extra timings; gated on
    # remaining budget.
    dispatch_ms = None
    dispatch_raw_ms = None
    noise_floor_ms = None
    if _budget_left(_BUDGET_S[0]) > (300 if on_tpu else 60):
        try:
            med_plain, _ = _timed_steps(          # real step, per-dispatch
                lambda: train_step(x, y),
                lambda out: float(np.asarray(out._data)),
                max(steps // 2, 2))
            os.environ["PADDLE_TPU_MOE_IDENTITY_DISPATCH"] = "1"
            twin_step = paddle.jit.to_static(_step, donate_state=False)
            _warm(twin_step, (x, y), 2 if on_tpu else 1, False)
            med_twin, _ = _timed_steps(
                lambda: twin_step(x, y),
                lambda out: float(np.asarray(out._data)),
                max(steps // 2, 2))
            # repeat the plain side (already compiled, cheap): the spread
            # between its two medians is the run-to-run noise floor. At
            # tiny CPU shapes the twin can time SLOWER than the real step
            # (r4 emitted -0.193 ms into the driver artifact); a delta
            # below the floor is indistinguishable from noise and must
            # not be published as a (let alone negative) cost.
            med_plain2, _ = _timed_steps(
                lambda: train_step(x, y),
                lambda out: float(np.asarray(out._data)),
                max(steps // 2, 2))
            noise_floor_ms = round(abs(med_plain - med_plain2) * 1000, 3)
            raw = (med_plain + med_plain2) / 2 - med_twin
            dispatch_raw_ms = round(raw * 1000, 3)
            dispatch_ms = (dispatch_raw_ms
                           if dispatch_raw_ms > noise_floor_ms else 0.0)
        except Exception as e:
            print(f"bench: moe decomposition probe failed: {e}",
                  file=sys.stderr)
        finally:
            os.environ.pop("PADDLE_TPU_MOE_IDENTITY_DISPATCH", None)

    rec = {
        "metric": "ernie_moe_ep_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2), "unit": "tokens/s",
        "mfu": round(mfu, 4),
        "median_step_s": round(med, 5),
        "batch": batch, "seq": seq, "params": n_params,
        "num_experts": c.num_experts, "loss": final_loss,
    }
    if dispatch_ms is not None:
        rec["gate_dispatch_combine_ms"] = dispatch_ms
        rec["gate_dispatch_combine_raw_ms"] = dispatch_raw_ms
        rec["dispatch_noise_floor_ms"] = noise_floor_ms
        rec["expert_compute_step_ms"] = round(med_twin * 1000, 3)
    return rec


# --------------------------------------------------------------------------

def main():
    jax, dev, tpu_unavailable = _init_devices()
    on_tpu = dev.platform in ("tpu", "axon")
    # Persistent compile cache: cuts time-to-first-TPU-number on driver
    # re-runs (r3 verdict ask #1c). Best-effort — the axon tunnel's
    # remote-compile path may bypass it, but XLA:CPU hits it for sure.
    if os.environ.get("BENCH_COMPILE_CACHE", "1") != "0":
        try:
            jax.config.update("jax_compilation_cache_dir",
                              "/tmp/paddle_tpu_jax_cache")
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 2.0)
        except Exception as e:
            print(f"bench: compile cache unavailable: {e}", file=sys.stderr)
    peak_tflops = float(os.environ.get("BENCH_PEAK_TFLOPS",
                                       "197" if on_tpu else "1"))
    budget_s = float(os.environ.get("BENCH_BUDGET_S",
                                    "1500" if on_tpu else "420"))
    _BUDGET_S[0] = budget_s

    # Resume (BENCH_RESUME=1, session5 bench_all phase): a tunnel flap
    # mid-run leaves completed configs in BENCH_partial.json; re-measuring
    # them on the retry burns scarce window minutes (the gpt2 headline
    # alone is ~7 min). Reuse fresh (<6 h) TPU-run partials; rehearsals
    # can't resume (on_tpu is False) and errored/skipped rows re-run.
    # BENCH_ONLY sweeps must not CONSUME a bench_all partial either (they
    # also don't delete it, mirroring _checkpoint's guard below): the
    # sweep would republish the banked headline inside its own window
    # record, and tools/publish_partial.py would then promote the same
    # partial a second time — the exact double-publish the deletion
    # guard exists to prevent.
    partial_path = os.path.join(os.path.dirname(__file__),
                                "BENCH_partial.json")
    prior = None
    if os.environ.get("BENCH_RESUME", "0") == "1" and on_tpu \
            and not os.environ.get("BENCH_ONLY"):
        try:
            if time.time() - os.path.getmtime(partial_path) < 6 * 3600:
                with open(partial_path) as f:
                    prior = json.load(f)
            # provenance: only TPU-run partials may be reused — a CPU
            # rehearsal's smoke numbers must never be republished as a
            # TPU window row (the partial records its own on_tpu)
            if prior is not None and prior.get("on_tpu") is not True:
                prior = None
        except Exception:
            prior = None

    headline = None
    if prior:
        h = prior.get("headline") or {}
        if h.get("value") is not None and "error" not in h:
            headline = h
            print("bench: resume — gpt2 headline reused from "
                  "BENCH_partial.json", file=sys.stderr)
    if (headline is None and os.environ.get("BENCH_ONLY")
            and os.environ.get("BENCH_HEADLINE", "1") == "0"):
        # sweep phases measuring ONE extra config (e.g. BENCH_ONLY=vit)
        # shouldn't pay the ~7 min gpt2 headline as overhead; only
        # honored in BENCH_ONLY mode so the canonical bench_all always
        # measures its headline
        headline = {"metric": "gpt2_124m_train_tokens_per_sec_per_chip",
                    "value": None, "skipped": "BENCH_HEADLINE=0"}
        print("bench: gpt2 headline skipped (BENCH_HEADLINE=0)",
              file=sys.stderr)
    if headline is None:
        headline = bench_gpt2(on_tpu, peak_tflops)
        print(f"bench: gpt2 done {headline['value']} tok/s "
              f"(mfu {headline['mfu']})", file=sys.stderr)

    # (name, fn, stable metric key, rough compile+run cost estimate in s —
    # a config only STARTS if the estimate fits the remaining budget; a
    # started config runs to completion, so the driver's own timeout must
    # budget BENCH_BUDGET_S + one config overrun)
    # bert runs LAST: it is the one config observed to wedge the tunnel on
    # its first donated call (2026-08-01 window) — a wedge must not cost
    # the configs behind it, and with BENCH_RESUME the retry banks
    # everything else before reaching it again
    extra_benches = [
        ("llama", bench_llama,
         "llama_proxy_stage3_tokens_per_sec_per_chip", 300),
        ("vit", bench_vit, "vit_l16_train_images_per_sec_per_chip", 300),
        ("moe", bench_moe, "ernie_moe_ep_tokens_per_sec_per_chip", 240),
        ("bert", bench_bert,
         "bert_base_amp_o2_stage2_tokens_per_sec_per_chip", 300),
    ]
    only = os.environ.get("BENCH_ONLY")
    if only:
        # tuning-sweep mode (tools/tpu_session.sh): headline config only,
        # skip the four extras so each sweep point costs one compile+run
        extra_benches = [e for e in extra_benches if e[0] == only]
    skip = {s for s in os.environ.get("BENCH_SKIP", "").split(",") if s}
    if skip:
        # e.g. BENCH_SKIP=moe — run a wedge-prone config in its own
        # process/phase so a hang can't eat the whole session
        extra_benches = [e for e in extra_benches if e[0] not in skip]
    configs = []
    done_metrics = {}
    if prior:
        for rec in prior.get("configs") or []:
            if (isinstance(rec, dict) and rec.get("value") is not None
                    and "error" not in rec and "skipped" not in rec):
                done_metrics[rec.get("metric")] = rec

    def _checkpoint():
        # kill-safety: if the driver times the process out mid-config, the
        # completed results survive in a side file. Reused-but-not-yet-
        # reached rows are merged in so a SECOND flap can't destroy what
        # the first flap's run already measured (the loop only appends
        # rows as it passes them).
        if not on_tpu or only:
            # CPU fallback/rehearsal runs must not clobber a real TPU
            # window's partial waiting for its resume (observed live:
            # a smoke run overwrote the flap-saved TPU headline); and
            # BENCH_ONLY sweep phases are not bench_all — their partial
            # would destroy a flap-banked one (and with BENCH_HEADLINE=0
            # replace the real headline with a null stub)
            return
        merged = list(configs)
        have = {r.get("metric") for r in merged if isinstance(r, dict)}
        for mk, rec in done_metrics.items():
            if mk not in have:
                merged.append(rec)
        try:
            with open(partial_path, "w") as f:
                json.dump({"headline": headline, "configs": merged,
                           "on_tpu": True}, f)
        except OSError:
            pass

    _checkpoint()
    for name, fn, metric_key, est_s in extra_benches:
        if metric_key in done_metrics:
            configs.append(done_metrics[metric_key])
            print(f"bench: {name} reused from partial (resume)",
                  file=sys.stderr)
            _checkpoint()
            continue
        left = _budget_left(budget_s)
        if left < (est_s if on_tpu else 90):
            configs.append({"metric": metric_key, "skipped": "time budget",
                            "budget_left_s": round(left, 1)})
            print(f"bench: {name} skipped (budget)", file=sys.stderr)
            continue
        # one retry: tunnel compiles fail transiently (observed live:
        # "remote_compile: read body: response body closed") and the
        # failed-trace rollback (jit/__init__.py::_execute) guarantees a
        # clean retry is possible
        rec = None
        for attempt in (1, 2):
            try:
                _release_memory()
                rec = fn(on_tpu, peak_tflops)
                break
            except Exception as e:
                import traceback
                traceback.print_exc()
                rec = {"metric": metric_key,
                       "error": f"{type(e).__name__}: {e}",
                       "attempts": attempt}
                if attempt == 2 or _budget_left(budget_s) < (
                        est_s if on_tpu else 90):
                    break
                print(f"bench: {name} attempt {attempt} failed; "
                      f"retrying", file=sys.stderr)
        configs.append(rec)
        if "error" not in rec:
            print(f"bench: {name} done {rec.get('value')} "
                  f"{rec.get('unit')}", file=sys.stderr)
        _checkpoint()

    baseline_path = os.path.join(os.path.dirname(__file__),
                                 "bench_baseline.json")
    vs_baseline = None
    try:
        with open(baseline_path) as f:
            prev = json.load(f).get("value")
        if prev and headline.get("value") is not None:
            vs_baseline = round(headline["value"] / prev, 4)
    except Exception:
        pass

    record = dict(headline)
    record["vs_baseline"] = vs_baseline
    record["device"] = str(dev)
    record["configs"] = configs
    if tpu_unavailable:
        # honest flag: this run measured the CPU fallback because the TPU
        # tunnel was unreachable — not comparable to the TPU ratchet.
        # The standing ratchet (latest committed TPU window) rides along
        # so the driver's JSON still carries the real TPU numbers.
        record["tpu_unavailable"] = True
        record["smoke"] = True   # tiny-shape models on the fallback path
        standing = _load_standing_ratchet()
        if standing is not None:
            record["standing_tpu_ratchet"] = standing
    elif on_tpu:
        _append_tpu_window(record)
        # this run's rows are now published as a window record — a later
        # BENCH_RESUME must re-measure, not republish them as a second
        # "new" window (stale-partial trap). BENCH_ONLY sweeps mirror
        # _checkpoint's guard: they are not bench_all, so they must not
        # consume a flap-banked bench_all partial that
        # tools/publish_partial.py still has to promote.
        if not only:
            try:
                os.remove(partial_path)
            except OSError:
                pass
    _emit_record(record)


def _emit_record(record):
    """Driver contract: stdout gets ONE compact, bounded JSON line; the
    full record goes to BENCH_RESULT.json. The r4 driver artifact showed
    the driver keeps only a bounded TAIL of output — the full record
    (configs + embedded standing ratchet) overflowed it and parsed as
    null. The compact line stays well under any plausible tail buffer;
    anything that doesn't fit lives in the canonical file."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_RESULT.json")
    try:
        with open(path, "w") as f:
            json.dump(record, f, indent=1)
    except Exception as e:
        print(f"bench: could not write BENCH_RESULT.json: {e}",
              file=sys.stderr)
    compact = {k: record[k] for k in
               ("metric", "value", "unit", "vs_baseline", "mfu",
                "device", "tpu_unavailable", "smoke", "error")
               if k in record}
    standing = record.get("standing_tpu_ratchet")
    if standing:   # fallback runs still surface the real TPU headline
        compact["standing_tpu"] = {
            k: standing[k] for k in ("value", "unit", "mfu")
            if k in standing}
    compact["full_record"] = "BENCH_RESULT.json"
    print(json.dumps(compact))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:
        # Last-resort: keep the one-JSON-line contract even on an
        # unexpected failure so the driver records what went wrong
        # instead of a bare traceback with parsed=null.
        import traceback
        traceback.print_exc()
        _emit_record({
            "metric": "gpt2_124m_train_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/s", "vs_baseline": None,
            "error": f"{type(e).__name__}: {e}",
        })
        # nonzero: the record is emitted for the driver's parser, but a
        # crashed bench must not read as success (tpu_session5.sh marks
        # phases done on rc==0 — exit 0 here would permanently skip a
        # bench phase that actually failed)
        sys.exit(4)
