"""Benchmark: GPT-2 124M causal-LM training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
Self-baseline protocol per BASELINE.md (reference published numbers are
unknown; vs_baseline tracks the last recorded run in bench_baseline.json).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _probe_tpu(timeout_s: float) -> bool:
    """Touch the TPU backend in a SUBPROCESS with a hard timeout.

    Two observed failure modes (2026-07-30) make an in-process probe
    unsafe: (a) jax.devices() can BLOCK forever when the tunnel is
    wedged, and — worse — (b) a process stuck mid-init holds the
    exclusive TPU grant, deadlocking every later attempt in any process.
    Uses Popen + poll (not subprocess.run): a child wedged in
    uninterruptible device I/O survives SIGKILL, and run()'s timeout path
    would then block in wait() forever — poll with a deadline and ABANDON
    an unreapable child instead."""
    import subprocess
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import jax; d = jax.devices()[0]; print(d.platform)"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        start_new_session=True)
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            out = proc.stdout.read() if proc.stdout else ""
            return proc.returncode == 0 and out.strip() in ("tpu", "axon")
        time.sleep(0.5)
    proc.kill()
    for _ in range(10):  # bounded reap; abandon a D-state zombie
        if proc.poll() is not None:
            break
        time.sleep(0.5)
    return False


def _init_devices():
    """Initialize the JAX backend, surviving tunnel flake AND tunnel
    hangs. Probe via subprocess first (hang-safe), retry with backoff over
    ~4 minutes (outages are long), then fall back to CPU via jax.config
    (which wins over the baked-in JAX_PLATFORMS=axon env) so the bench
    still emits its one JSON line."""
    import threading

    cache = "/tmp/paddle_tpu_probe_down"
    if os.environ.get("BENCH_TPU_UNAVAILABLE") == "1" or (
            os.path.exists(cache)
            and time.time() - os.path.getmtime(cache) < 600):
        print("bench: TPU marked unavailable (env/cache); skipping probes",
              file=sys.stderr)
        import jax
        jax.config.update("jax_platforms", "cpu")
        return jax, jax.devices()[0], True

    # worst case: 3×75 s probes + 60 s sleeps + 120 s init watchdog ≈ 7 min
    # before the CPU fallback; driver timeouts must budget for that
    delays = [0, 15, 45]
    for i, delay in enumerate(delays):
        if delay:
            time.sleep(delay)
        if _probe_tpu(timeout_s=75):
            import jax
            # a wedge inside native init never returns to the bytecode
            # loop, so SIGALRM can't raise — a watchdog thread hard-exits
            # instead (rc=3 tells the driver "init hang", vs hanging
            # forever while holding the exclusive TPU grant)
            done = threading.Event()

            def _watchdog():
                if not done.wait(120.0):
                    print("bench: in-process TPU init hung after a good "
                          "probe; exiting(3)", file=sys.stderr)
                    os._exit(3)
            threading.Thread(target=_watchdog, daemon=True).start()
            try:
                dev = jax.devices()[0]
                done.set()
                try:
                    os.remove(cache)  # tunnel is back: clear the skip
                except OSError:
                    pass
                return jax, dev, False
            except Exception as e:
                done.set()
                print(f"bench: init after good probe failed: {e}",
                      file=sys.stderr)
        print(f"bench: TPU probe {i + 1}/{len(delays)} failed",
              file=sys.stderr)
    print("bench: accelerator unreachable; falling back to CPU (number "
          "is NOT comparable to TPU baselines)", file=sys.stderr)
    try:  # let sibling benches skip the probe ladder for the next 10 min
        with open(cache, "w") as f:
            f.write(str(time.time()))
    except OSError:
        pass
    import jax
    jax.config.update("jax_platforms", "cpu")
    return jax, jax.devices()[0], True


def main():
    jax, dev, tpu_unavailable = _init_devices()
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import gpt2_124m

    on_tpu = dev.platform in ("tpu", "axon")
    batch = int(os.environ.get("BENCH_BATCH", "8" if on_tpu else "2"))
    seq = int(os.environ.get("BENCH_SEQ", "1024" if on_tpu else "128"))
    steps = int(os.environ.get("BENCH_STEPS", "20" if on_tpu else "3"))
    warmup = int(os.environ.get("BENCH_WARMUP", "5" if on_tpu else "1"))

    paddle.seed(0)
    model = gpt2_124m()
    if on_tpu:
        model.bfloat16()  # bf16 params; fp32 master weights in AdamW
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=on_tpu)
    n_params = sum(p.size for p in model.parameters())

    rng = np.random.RandomState(0)
    ids = rng.randint(0, 50000, (batch, seq + 1)).astype(np.int32)
    x = paddle.to_tensor(ids[:, :-1])
    y = paddle.to_tensor(ids[:, 1:])

    @paddle.jit.to_static
    def train_step(x, y):
        loss = model(x, labels=y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    # First call traces with slot creation (state superset), second call
    # recompiles into the steady signature — no eager per-op compile storm.
    for _ in range(warmup):
        loss = train_step(x, y)
    float(np.asarray(loss._data))   # host fetch: drains the pipeline

    # NOTE: block_until_ready is NOT a completion barrier on the axon
    # tunnel backend (measured: it returns ~100x early). Time chained
    # chunks (each step depends on the previous via the optimizer state),
    # forcing a device->host fetch per chunk, and take the median chunk
    # rate so a mid-run recompile can't skew the number.
    chunk = max(1, steps // 5)
    chunk_times = []
    final_loss = None
    done = 0
    while done < steps:
        n = min(chunk, steps - done)
        t0 = time.perf_counter()
        for _ in range(n):
            loss = train_step(x, y)
        final_loss = float(np.asarray(loss._data))
        chunk_times.append((time.perf_counter() - t0) / n)
        done += n
    med = float(np.median(chunk_times))
    tokens_per_sec = batch * seq / med

    # MFU: dense-transformer 6·N·tokens estimate + attention term
    cfg = model.config
    flops_per_token = 6 * n_params + 12 * cfg.num_layers * cfg.hidden_size * seq
    peak_tflops = float(os.environ.get("BENCH_PEAK_TFLOPS",
                                       "197" if on_tpu else "1"))
    mfu = (flops_per_token * tokens_per_sec) / (peak_tflops * 1e12)

    baseline_path = os.path.join(os.path.dirname(__file__),
                                 "bench_baseline.json")
    vs_baseline = None
    try:
        with open(baseline_path) as f:
            prev = json.load(f).get("value")
        if prev:
            vs_baseline = round(tokens_per_sec / prev, 4)
    except Exception:
        pass

    record = {
        "metric": "gpt2_124m_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s",
        "vs_baseline": vs_baseline,
        "mfu": round(mfu, 4),
        "median_step_s": round(med, 5),
        "batch": batch, "seq": seq, "params": n_params,
        "device": str(dev), "loss": final_loss,
    }
    if tpu_unavailable:
        # honest flag: this run measured the CPU fallback because the TPU
        # tunnel was unreachable — not comparable to the TPU ratchet
        record["tpu_unavailable"] = True
    print(json.dumps(record))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:
        # Last-resort: keep the one-JSON-line contract even on an
        # unexpected failure so the driver records what went wrong
        # instead of a bare traceback with parsed=null.
        import traceback
        traceback.print_exc()
        print(json.dumps({
            "metric": "gpt2_124m_train_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/s", "vs_baseline": None,
            "error": f"{type(e).__name__}: {e}",
        }))
        sys.exit(0)
