"""paddle.amp namespace."""
from . import debugging
from .auto_cast import auto_cast, amp_guard, decorate, white_list, black_list
from .grad_scaler import GradScaler, AmpScaler


def is_bfloat16_supported(device=None):
    """bf16 is native on every TPU generation (and XLA:CPU emulates)."""
    return True


def is_float16_supported(device=None):
    """fp16 compute is supported via XLA (TPU prefers bf16; the MXU runs
    fp16 at the same rate)."""
    return True
