"""paddle.geometric — graph learning primitives.

Parity: python/paddle/geometric/ (math.py :: segment_sum/mean/max/min;
message_passing/send_recv.py :: send_u_recv, send_ue_recv, send_uv;
reindex.py :: reindex_graph; sampling/neighbors.py :: sample_neighbors).

TPU-first: every primitive is a gather + jax.ops.segment_* reduction —
static segment counts, no atomics (the reference's CUDA kernels use
atomicAdd; segment_sum is XLA's deterministic sorted-scatter equivalent).
Graph-structure ops (reindex, sampling) are host-side numpy: structure
manipulation, not device math, exactly as the reference runs them on CPU
for CPU graphs."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor.tensor import Tensor, apply_op

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min",
           "send_u_recv", "send_ue_recv", "send_uv", "reindex_graph",
           "sample_neighbors"]


def _ids(x):
    return jnp.asarray(x._data if isinstance(x, Tensor) else x, jnp.int32)


def _nseg(segment_ids, num_segments=None):
    if num_segments is not None:
        return int(num_segments)
    ids = np.asarray(segment_ids)
    return int(ids.max()) + 1 if ids.size else 0


def segment_sum(data: Tensor, segment_ids, name=None):
    ids = _ids(segment_ids)
    n = _nseg(ids)
    return apply_op(
        lambda d: jax.ops.segment_sum(d, ids, num_segments=n), data)


def segment_mean(data: Tensor, segment_ids, name=None):
    ids = _ids(segment_ids)
    n = _nseg(ids)

    def fn(d):
        s = jax.ops.segment_sum(d, ids, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones_like(ids, d.dtype), ids,
                                  num_segments=n)
        cnt = cnt.reshape((n,) + (1,) * (d.ndim - 1))
        return s / jnp.maximum(cnt, 1)
    return apply_op(fn, data)


def _empty_mask(ids, n, ndim):
    """[n] bool → broadcastable: which segments received no element (the
    reference zeros them; segment_max/min leave dtype extremes / ±inf)."""
    cnt = jax.ops.segment_sum(jnp.ones(ids.shape, jnp.int32), ids,
                              num_segments=n)
    return (cnt == 0).reshape((n,) + (1,) * (ndim - 1))


def segment_max(data: Tensor, segment_ids, name=None):
    ids = _ids(segment_ids)
    n = _nseg(ids)

    def fn(d):
        out = jax.ops.segment_max(d, ids, num_segments=n)
        return jnp.where(_empty_mask(ids, n, d.ndim),
                         jnp.zeros((), d.dtype), out)
    return apply_op(fn, data)


def segment_min(data: Tensor, segment_ids, name=None):
    ids = _ids(segment_ids)
    n = _nseg(ids)

    def fn(d):
        out = jax.ops.segment_min(d, ids, num_segments=n)
        return jnp.where(_empty_mask(ids, n, d.ndim),
                         jnp.zeros((), d.dtype), out)
    return apply_op(fn, data)


_REDUCERS = {
    "sum": jax.ops.segment_sum,
    "mean": None,  # composed below
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
}


def _reduce(contrib, dst, n, pool_type, dtype):
    if pool_type == "mean":
        s = jax.ops.segment_sum(contrib, dst, num_segments=n)
        cnt = jax.ops.segment_sum(
            jnp.ones(dst.shape, contrib.dtype), dst, num_segments=n)
        cnt = cnt.reshape((n,) + (1,) * (contrib.ndim - 1))
        return s / jnp.maximum(cnt, 1)
    out = _REDUCERS[pool_type](contrib, dst, num_segments=n)
    if pool_type in ("max", "min"):
        out = jnp.where(_empty_mask(dst, n, contrib.ndim),
                        jnp.zeros((), contrib.dtype), out)
    return out


def send_u_recv(x: Tensor, src_index, dst_index, reduce_op: str = "sum",
                out_size=None, name=None):
    """Gather x[src] along edges, reduce at dst (message passing without
    edge features)."""
    src, dst = _ids(src_index), _ids(dst_index)
    n = out_size if out_size is not None else x.shape[0]
    n = int(n)

    def fn(a):
        contrib = jnp.take(a, src, axis=0)
        return _reduce(contrib, dst, n, reduce_op, a.dtype)
    return apply_op(fn, x)


_EDGE_OPS = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
             "div": jnp.divide}


def send_ue_recv(x: Tensor, y: Tensor, src_index, dst_index,
                 message_op: str = "add", reduce_op: str = "sum",
                 out_size=None, name=None):
    """Combine x[src] with edge features y via message_op, reduce at dst."""
    src, dst = _ids(src_index), _ids(dst_index)
    n = int(out_size if out_size is not None else x.shape[0])
    op = _EDGE_OPS[message_op]

    def fn(a, e):
        contrib = op(jnp.take(a, src, axis=0), e)
        return _reduce(contrib, dst, n, reduce_op, a.dtype)
    return apply_op(fn, x, y)


def send_uv(x: Tensor, y: Tensor, src_index, dst_index,
            message_op: str = "add", name=None):
    """Per-edge message x[src] op y[dst] (no reduction)."""
    src, dst = _ids(src_index), _ids(dst_index)
    op = _EDGE_OPS[message_op]
    return apply_op(
        lambda a, b: op(jnp.take(a, src, axis=0), jnp.take(b, dst, axis=0)),
        x, y)


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact (x ∪ neighbors) into contiguous ids: returns (reindexed_src,
    reindexed_dst, out_nodes). Host-side structure op."""
    xs = np.asarray(x._data if isinstance(x, Tensor) else x).ravel()
    nbr = np.asarray(neighbors._data if isinstance(neighbors, Tensor)
                     else neighbors).ravel()
    cnt = np.asarray(count._data if isinstance(count, Tensor)
                     else count).ravel()
    # order: seed nodes first, then unseen neighbors in first-appearance order
    mapping: dict[int, int] = {}
    for v in xs.tolist():
        mapping.setdefault(int(v), len(mapping))
    for v in nbr.tolist():
        mapping.setdefault(int(v), len(mapping))
    out_nodes = np.fromiter(mapping.keys(), np.int64, len(mapping))
    reindex_src = np.array([mapping[int(v)] for v in nbr], np.int64)
    reindex_dst = np.repeat(np.arange(len(xs), dtype=np.int64), cnt)
    return (Tensor(reindex_src), Tensor(reindex_dst), Tensor(out_nodes))


def sample_neighbors(row, colptr, input_nodes, sample_size: int = -1,
                     eids=None, return_eids: bool = False, perm_buffer=None,
                     name=None):
    """Uniform neighbor sampling from CSC graph (row=indices,
    colptr=offsets): returns (out_neighbors, out_count[, out_eids]).
    Host-side; sampling is data-dependent-shape by nature, so it stays off
    the accelerator (matching the reference's CPU sampler role)."""
    r = np.asarray(row._data if isinstance(row, Tensor) else row).ravel()
    cp = np.asarray(colptr._data if isinstance(colptr, Tensor)
                    else colptr).ravel()
    nodes = np.asarray(input_nodes._data if isinstance(input_nodes, Tensor)
                       else input_nodes).ravel()
    e = None if eids is None else np.asarray(
        eids._data if isinstance(eids, Tensor) else eids).ravel()
    out_n, out_c, out_e = [], [], []
    rng = np.random
    for v in nodes.tolist():
        beg, end = int(cp[v]), int(cp[v + 1])
        deg = end - beg
        if sample_size < 0 or deg <= sample_size:
            sel = np.arange(beg, end)
        else:
            sel = beg + rng.choice(deg, size=sample_size, replace=False)
        out_n.append(r[sel])
        out_c.append(len(sel))
        if return_eids and e is not None:
            out_e.append(e[sel])
    neighbors = Tensor(np.concatenate(out_n) if out_n else
                       np.zeros(0, np.int64))
    counts = Tensor(np.asarray(out_c, np.int64))
    if return_eids:
        return neighbors, counts, Tensor(
            np.concatenate(out_e) if out_e else np.zeros(0, np.int64))
    return neighbors, counts
