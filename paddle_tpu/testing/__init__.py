"""paddle_tpu.testing — test-only instrumentation shipped with the package.

The single registry of fault-injection env vars lives HERE so the harness
(`fault.py`), the conftest leak guard, and the docs all read one list —
adding a knob in fault.py without registering it is a test failure, not a
silent drift.
"""
from __future__ import annotations

import os

# Every env var the fault-injection harness reads. Keep sorted; the
# conftest guard fails any non-FT test that runs with one of these set.
FI_ENV_VARS = (
    "PADDLE_FI_AT_POINT",       # named hook point targeting KILL/HANG/RAISE
    "PADDLE_FI_AT_STEP",        # step index gating KILL/HANG ("step" point)
    "PADDLE_FI_DROP_HEARTBEAT",  # rank whose heartbeat publisher goes dark
    "PADDLE_FI_HANG",           # rank that hangs (bounded sleep) at the point
    "PADDLE_FI_KILL_RANK",      # rank that hard-exits (os._exit) at the point
    "PADDLE_FI_RAISE",          # rank that raises FaultInjected at the point
    "PADDLE_FI_RPC_DELAY_MS",   # flaky transport: per-rpc-call delay
    "PADDLE_FI_RPC_ERR_RATE",   # flaky transport: deterministic error frac
    "PADDLE_FI_SLOW_MS",        # gray failure: persistent delay at a point
    "PADDLE_FI_SLOW_POINT",     # which hook point the slowness rides
)

# Flight-recorder configuration (distributed/resilience/flight_recorder.py)
# — same registry discipline as the FI knobs: a test leaking recorder
# config silently changes what every later collective records (and where
# dumps land), so the conftest guard fails non-flight tests loudly.
FR_ENV_VARS = (
    "PADDLE_FLIGHT_DUMP_DIR",   # where flightdump.<rank>.<gen>.json land
    "PADDLE_FLIGHT_RECORDER",   # ring size; 0 = disabled; unset = auto
)

# Cluster-gateway configuration (serving_cluster/) — same registry
# discipline: a leaked router policy / heartbeat threshold silently
# changes placement and failover behavior in every later cluster test,
# so only tests/test_serving_cluster.py may run with these set (and it
# uses monkeypatch / constructor args, not the process env).
GW_ENV_VARS = (
    # elastic autoscaler (serving_cluster/autoscale.py): leaked
    # watermarks silently change when every later cluster spawns or
    # drains replicas — same guard discipline as the router knobs
    "PADDLE_AUTOSCALE_COOLDOWN_S",  # seconds between scale events
    # disaggregated per-pool watermarks (autoscale.py role_aware mode):
    # the prefill pool scales on queue depth, the decode pool on kv
    # headroom + resident-session depth — leaked values silently split
    # every later cluster's scaling behavior by role
    "PADDLE_AUTOSCALE_DC_KV_FREE_FRAC",   # decode pool-free frac -> up
    "PADDLE_AUTOSCALE_DC_SESSIONS_HIGH",  # decode session frac -> up
    "PADDLE_AUTOSCALE_DC_SESSIONS_LOW",   # decode session frac -> down
    "PADDLE_AUTOSCALE_HYSTERESIS",  # consecutive agreeing ticks needed
    "PADDLE_AUTOSCALE_KV_FREE_FRAC",  # pool-free fraction -> scale up
    "PADDLE_AUTOSCALE_MAX",        # replica-count ceiling
    "PADDLE_AUTOSCALE_MIN",        # replica-count floor
    "PADDLE_AUTOSCALE_PF_QUEUE_HIGH",  # prefill queue depth -> up
    "PADDLE_AUTOSCALE_PF_QUEUE_LOW",   # prefill queue depth -> down
    "PADDLE_AUTOSCALE_QUEUE_HIGH",  # mean queue depth -> scale up
    "PADDLE_AUTOSCALE_QUEUE_LOW",  # mean queue depth -> scale down
    "PADDLE_AUTOSCALE_ROLE_AWARE",  # per-pool scaling on/off
    "PADDLE_GATEWAY_HB_DEAD_S",    # heartbeat age -> replica dead
    "PADDLE_GATEWAY_HB_S",         # gateway health-sweep interval
    "PADDLE_GATEWAY_HB_TIMEOUT_S",  # rpc replica liveness-probe timeout
    "PADDLE_GATEWAY_POLL_S",       # SSE harvest poll interval
    "PADDLE_GATEWAY_PORT",         # gateway listen port (0 = ephemeral)
    "PADDLE_GATEWAY_REPLICAS",     # demo-cluster replica count
    "PADDLE_GATEWAY_ROLES",        # demo-cluster pool spec "prefill:1,..."
    "PADDLE_GATEWAY_TRACE_RING",   # HTTP span ring size (0 = off)
    # QoS / multi-tenant knobs (inference/serving.py weighted-fair
    # shares; serving_cluster/gateway.py shed + tenant buckets): a
    # leaked share split or rate limit silently reshapes every later
    # engine's packing and the gateway's 429 behavior
    "PADDLE_QOS_SHARES",           # per-class budget shares "high=4,..."
    "PADDLE_QOS_SHED_DEPTH",       # mean queue depth -> shed low class
    # disaggregated serving roles (inference/serving.py role= and
    # serving_cluster/router.py streamed handoff): a leaked role turns
    # every later engine into a prefill-only worker
    "PADDLE_ROLE",                 # engine role prefill|decode|mixed
    "PADDLE_ROLE_HANDOFF_BLOCKS",  # streamed-handoff chunk (0 = off)
    "PADDLE_ROUTER_AUDIT_RING",    # decision ring (0 = ring off;
                                   # reason counters stay)
    # gray-failure defense (serving_cluster/router.py): a leaked breaker
    # threshold or hedge quantile silently changes which replicas every
    # later cluster sheds and when it speculates — guard them all
    "PADDLE_ROUTER_BREAKER_COOLDOWN_S",  # open -> half-open delay (s)
    "PADDLE_ROUTER_BREAKER_ERRS",  # consecutive errors -> breaker open
    "PADDLE_ROUTER_BREAKER_PROBES",  # concurrent half-open placements
    "PADDLE_ROUTER_BREAKER_RATIO",  # x cluster median -> degraded/open
    "PADDLE_ROUTER_HEDGE_MARGIN",  # hedge delay = pXX * margin
    "PADDLE_ROUTER_HEDGE_MIN_S",   # hedge delay floor (s)
    "PADDLE_ROUTER_HEDGE_QUANTILE",  # TTFT percentile (0 = hedging off)
    "PADDLE_ROUTER_POLICY",        # prefix_affinity|least_loaded|round_robin
    "PADDLE_ROUTER_RETRY_BURST",   # retry/hedge token-bucket capacity
    "PADDLE_ROUTER_RETRY_RATE",    # retry/hedge bucket refill (tokens/s)
    "PADDLE_ROUTER_SNAP_AGE_S",    # snapshot staleness bound
    "PADDLE_ROUTER_SPILL_DEPTH",   # owner queue depth -> affinity spill
    "PADDLE_ROUTER_SUSPECT_RATIO",  # x cluster median -> suspect verdict
    # rpc client timeouts (distributed/rpc.py + serving_cluster/
    # replica.py RpcReplica): a leaked timeout silently changes how fast
    # every later cluster declares a frozen replica dead
    "PADDLE_RPC_PING_TIMEOUT_S",   # liveness-probe rpc timeout
    "PADDLE_RPC_TIMEOUT_S",        # per-call rpc client timeout
    # tensor-parallel serving mesh (parallel/__init__.py
    # init_serving_mesh; inference/generation.py weight placement): a
    # leaked mp degree makes every later engine try to stand up a
    # mesh, a leaked weight opt-out silently re-replicates every later
    # sharded engine's stacks
    "PADDLE_SERVING_MESH_MP",      # mesh mp degree (0/1 = no mesh)
    "PADDLE_SERVING_MESH_WEIGHTS",  # 0 = replicate weights under mesh
    # SLO objectives (inference/telemetry.py SloPolicy): a leaked
    # objective silently flips every later engine's goodput counters —
    # same guard discipline as the router knobs
    "PADDLE_SLO_E2E_S",            # end-to-end latency objective (s)
    "PADDLE_SLO_ITL_S",            # mean inter-token latency objective
    "PADDLE_SLO_TTFT_S",           # time-to-first-token objective (s)
    # per-tenant admission (serving_cluster/gateway.py token buckets):
    # X-Tenant header keys the bucket; 429s carry reason=rate_limited /
    # quota_exceeded with a bucket-derived Retry-After
    "PADDLE_TENANT_BURST",         # token-bucket capacity per tenant
    "PADDLE_TENANT_QUOTA",         # live-request quota per tenant
    "PADDLE_TENANT_RATE",          # bucket refill rate (req/s)
)


# Serving quantization knobs (inference/generation.py _weight_quant_mode
# / _int8_cache; ctor args weight_quant=/kv_quant= override them) — same
# registry discipline: a leaked weight flavor silently re-stacks every
# later engine's weights (different bytes, different numerics, different
# jit cache), and a leaked cache flavor flips every later pool to int8.
# Only the quant suites may run with these set; everyone else uses
# monkeypatch or the ctor args.
QUANT_ENV_VARS = (
    "PADDLE_TPU_DECODE_INT4_WEIGHTS",  # int4-packed stacked weights
    "PADDLE_TPU_DECODE_INT8_CACHE",    # int8 KV pool + scale mirrors
    "PADDLE_TPU_DECODE_INT8_HEAD",     # int8 LM head
    "PADDLE_TPU_DECODE_INT8_WEIGHTS",  # int8 stacked weights
)


def fi_env_active() -> list:
    """The PADDLE_FI_* vars currently set (empty list = harness disarmed)."""
    return [v for v in FI_ENV_VARS if os.environ.get(v) not in (None, "")]


def fr_env_active() -> list:
    """The flight-recorder env vars currently set (empty = default)."""
    return [v for v in FR_ENV_VARS if os.environ.get(v) not in (None, "")]


def gw_env_active() -> list:
    """The gateway/router env vars currently set (empty = default)."""
    return [v for v in GW_ENV_VARS if os.environ.get(v) not in (None, "")]


def quant_env_active() -> list:
    """The serving-quant env vars currently set (empty = fp default)."""
    return [v for v in QUANT_ENV_VARS
            if os.environ.get(v) not in (None, "")]


from . import fault  # noqa: E402  (re-export the harness)

__all__ = ["FI_ENV_VARS", "FR_ENV_VARS", "GW_ENV_VARS", "QUANT_ENV_VARS",
           "fi_env_active", "fr_env_active", "gw_env_active",
           "quant_env_active", "fault"]
