"""paddle_tpu.testing — test-only instrumentation shipped with the package.

The single registry of fault-injection env vars lives HERE so the harness
(`fault.py`), the conftest leak guard, and the docs all read one list —
adding a knob in fault.py without registering it is a test failure, not a
silent drift.
"""
from __future__ import annotations

import os

# Every env var the fault-injection harness reads. Keep sorted; the
# conftest guard fails any non-FT test that runs with one of these set.
FI_ENV_VARS = (
    "PADDLE_FI_AT_STEP",        # step index gating KILL/HANG ("step" point)
    "PADDLE_FI_DROP_HEARTBEAT",  # rank whose heartbeat publisher goes dark
    "PADDLE_FI_HANG",           # rank that hangs (bounded sleep) at the point
    "PADDLE_FI_KILL_RANK",      # rank that hard-exits (os._exit) at the point
)


def fi_env_active() -> list:
    """The PADDLE_FI_* vars currently set (empty list = harness disarmed)."""
    return [v for v in FI_ENV_VARS if os.environ.get(v) not in (None, "")]


from . import fault  # noqa: E402  (re-export the harness)

__all__ = ["FI_ENV_VARS", "fi_env_active", "fault"]
