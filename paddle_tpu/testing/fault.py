"""Env-driven fault-injection harness (SURVEY §5.3 failure paths).

Hook points are compiled into the real code paths (parallel init, the
optimizer train step, the watchdog's heartbeat publisher) and stay ~free
when disarmed: `inject()` is a no-op unless a PADDLE_FI_* var is set.

Knobs (registered in paddle_tpu.testing.FI_ENV_VARS):

  PADDLE_FI_KILL_RANK=<r>       rank r hard-exits (os._exit(FI_EXIT_CODE))
  PADDLE_FI_HANG=<r>            rank r hangs (bounded sleep, supervisor's
                                problem) instead of exiting
  PADDLE_FI_AT_STEP=<n>         gate KILL/HANG to train-step n ("step"
                                hook); unset -> they fire at "init"
  PADDLE_FI_AT_POINT=<name>     target a NAMED hook point instead
                                ("init" | "step" | "collective" — the
                                flight-recorder choke point — or
                                "migration" — the router's live-slot
                                transfer, fired BETWEEN export and
                                import so the state is off the source
                                but on no target, the worst moment —
                                or "preempt" — the engine's QoS
                                preemption-to-host, fired AFTER the
                                slot is freed but BEFORE the parking-
                                lot insert, so the parked copy is lost
                                and the router's classic failover must
                                pick the stream up exactly-once);
                                KILL/HANG/RAISE fire at the AT_STEP-th
                                occurrence of that point (unset
                                AT_STEP = the first).
                                "collective" requires the flight
                                recorder to be enabled (the hook rides
                                its choke point) — the desync e2e's
                                lever: wedge one rank at its Nth
                                collective entry, BEFORE the entry is
                                recorded, so peers' dumps show the
                                collective in flight and the wedged
                                rank's shows it never entered
  PADDLE_FI_DROP_HEARTBEAT=<r>  rank r's heartbeat publisher goes dark
                                (the process stays alive: the watchdog on
                                the PEERS must convert this into a
                                PeerFailureError)
  PADDLE_FI_RAISE=<r>           rank r RAISES FaultInjected at the
                                point instead of exiting — the
                                in-process fault flavor (a single-
                                process cluster cannot os._exit to
                                simulate a peer dying mid-transfer;
                                the caller's abort path must handle
                                the exception exactly like a transport
                                error)
  PADDLE_FI_SLOW_MS=<ms>        GRAY-FAILURE flavor: the named point
                                (PADDLE_FI_SLOW_POINT, default "step")
                                sleeps <ms> on EVERY occurrence from
                                the PADDLE_FI_AT_STEP-th onward (unset
                                AT_STEP = from the first). Unlike
                                KILL/HANG/RAISE this is PERSISTENT —
                                a slow replica stays slow until the
                                env is cleared — because gray failure
                                is a condition, not an event. The
                                process stays alive and keeps beating
                                its heartbeat: the router's health
                                scoring / circuit breaker, not death
                                detection, must shed it.
  PADDLE_FI_SLOW_POINT=<name>   which hook point the slowness rides
                                (any inject() point name)
  PADDLE_FI_RPC_DELAY_MS=<ms>   flaky-transport: every rpc client
                                call sleeps <ms> before the wire
  PADDLE_FI_RPC_ERR_RATE=<f>    flaky-transport: fraction of rpc
                                client calls (deterministic
                                accumulator, not random) that raise
                                FaultInjected instead of sending —
                                the caller must treat it exactly like
                                a transport error (ReplicaError path)

Injections fire at most once per process (a restarted generation whose
env cleared the vars is unaffected; one that kept them re-injects —
companions gate on PADDLE_RESTART_COUNT to fault only generation 0).
The SLOW and RPC flavors are the exception: they model a *condition*
(degraded host, lossy link) and fire on every qualifying call.
"""
from __future__ import annotations

import os
import time

from . import FI_ENV_VARS

__all__ = ["inject", "heartbeat_dropped", "step_count", "reset",
           "slow_s", "rpc_flaky", "FaultInjected", "FI_EXIT_CODE",
           "HANG_BOUND_S"]

FI_EXIT_CODE = 43          # distinctive: never collides with signal codes
HANG_BOUND_S = 3600.0      # a "hang" is a bounded sleep, not a true wedge


class FaultInjected(RuntimeError):
    """Raised by the PADDLE_FI_RAISE flavor: an injected in-process
    failure the exercised code path must degrade from (e.g. a migration
    transfer dying mid-flight -> classic failover fallback)."""

_steps = 0                 # "step"-point calls observed in this process
_point_counts: dict = {}   # point -> calls observed (AT_POINT mode)
_slow_counts: dict = {}    # point -> calls observed (SLOW gating)
_fired = False
_rpc_calls = 0             # rpc client calls observed (flaky accounting)
_rpc_errs = 0              # flaky errors already raised


def reset():
    """Re-arm the harness (in-process tests; subprocesses never need it)."""
    global _steps, _fired, _rpc_calls, _rpc_errs
    _steps, _fired = 0, False
    _rpc_calls, _rpc_errs = 0, 0
    _point_counts.clear()
    _slow_counts.clear()


def step_count() -> int:
    return _steps


def _rank() -> str:
    return os.environ.get("PADDLE_TRAINER_ID", "0")


def _armed() -> bool:
    return any(os.environ.get(v) not in (None, "") for v in FI_ENV_VARS)


def heartbeat_dropped(rank=None) -> bool:
    """Consulted by the watchdog's publisher before every beat."""
    r = str(rank) if rank is not None else _rank()
    return os.environ.get("PADDLE_FI_DROP_HEARTBEAT") == r


def slow_s(point: str) -> float:
    """Seconds of injected slowness for THIS occurrence of `point`.

    Advances the point's private occurrence counter; returns 0.0 when
    disarmed or before the PADDLE_FI_AT_STEP-th occurrence. Persistent:
    every occurrence from the threshold onward is slowed (gray failure
    is a condition, not a one-shot event), so `_fired` is not consulted.
    """
    ms = os.environ.get("PADDLE_FI_SLOW_MS")
    if ms in (None, ""):
        return 0.0
    target = os.environ.get("PADDLE_FI_SLOW_POINT", "step") or "step"
    if point != target:
        return 0.0
    idx = _slow_counts.get(point, 0)
    _slow_counts[point] = idx + 1
    at = os.environ.get("PADDLE_FI_AT_STEP")
    if at not in (None, "") and idx < int(at):
        return 0.0
    return float(ms) / 1000.0


def rpc_flaky():
    """Flaky-transport hook: called by the rpc client before every call.

    Applies PADDLE_FI_RPC_DELAY_MS as a pre-wire sleep, then raises
    FaultInjected for a PADDLE_FI_RPC_ERR_RATE fraction of calls. The
    error schedule is a DETERMINISTIC accumulator (fire whenever the
    running error count falls behind rate * calls), not a coin flip —
    chaos drills must reproduce bit-for-bit across runs.
    """
    global _rpc_calls, _rpc_errs
    delay = os.environ.get("PADDLE_FI_RPC_DELAY_MS")
    rate = os.environ.get("PADDLE_FI_RPC_ERR_RATE")
    if delay in (None, "") and rate in (None, ""):
        return
    _rpc_calls += 1
    if delay not in (None, ""):
        time.sleep(float(delay) / 1000.0)
    if rate not in (None, ""):
        if _rpc_errs < int(float(rate) * _rpc_calls):
            _rpc_errs += 1
            raise FaultInjected(
                f"injected rpc transport error (call {_rpc_calls})")


def _should_fire(point: str) -> bool:
    """Gating + counter bookkeeping for one inject() call.

    PADDLE_FI_AT_POINT set: KILL/HANG target that named point, at its
    AT_STEP-th occurrence (unset AT_STEP = the first occurrence).
    Unset: legacy semantics — "step" fires at step AT_STEP, any other
    point fires iff AT_STEP is unset.
    """
    global _steps
    at_point = os.environ.get("PADDLE_FI_AT_POINT")
    at = os.environ.get("PADDLE_FI_AT_STEP")
    if at_point not in (None, ""):
        idx = _point_counts.get(point, 0)
        _point_counts[point] = idx + 1
        if point == "step":
            _steps += 1        # step_count() keeps counting in this mode
        return point == at_point and (at is None or idx == int(at))
    if point == "step":
        hit = at is not None and _steps == int(at)
        _steps += 1
        return hit
    return at is None


def inject(point: str, rank=None):
    """Run the injections registered for `point` ("init" | "step").

    The "step" point also advances the harness step counter, so
    PADDLE_FI_AT_STEP indexes optimizer steps 0, 1, 2, ... regardless of
    where the caller is in its own loop.
    """
    global _steps, _fired
    if not _armed():
        return
    d = slow_s(point)          # gray-failure flavor: slow, don't die
    if d > 0.0:
        time.sleep(d)
    hit = _should_fire(point)
    if not hit or _fired:
        return
    r = str(rank) if rank is not None else _rank()
    if os.environ.get("PADDLE_FI_RAISE") == r:
        _fired = True
        print(f"paddle_tpu.testing.fault: rank {r} RAISING at {point}",
              flush=True)
        raise FaultInjected(f"injected fault at point {point!r}")
    if os.environ.get("PADDLE_FI_HANG") == r:
        _fired = True
        print(f"paddle_tpu.testing.fault: rank {r} HANGING at {point} "
              f"(step {_steps - 1 if point == 'step' else '-'})", flush=True)
        time.sleep(HANG_BOUND_S)
        os._exit(FI_EXIT_CODE)   # the bound expired without a supervisor
    if os.environ.get("PADDLE_FI_KILL_RANK") == r:
        _fired = True
        print(f"paddle_tpu.testing.fault: rank {r} KILLED at {point} "
              f"(step {_steps - 1 if point == 'step' else '-'})", flush=True)
        os._exit(FI_EXIT_CODE)
