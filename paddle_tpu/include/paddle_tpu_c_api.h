/* paddle_tpu plain-C ABI — declarations for the native runtime exported by
 * csrc/runtime.cc (built as libpaddle_tpu_runtime.so; loaded via ctypes from
 * paddle_tpu/core/native.py). External C++ extensions compile against this
 * header; paths come from paddle.sysconfig.get_include()/get_lib().
 *
 * Parity role: the reference ships its C++ surface via pybind11 headers;
 * this build's binding strategy is a stable C ABI instead (pybind11 absent
 * in the image). */
#ifndef PADDLE_TPU_C_API_H_
#define PADDLE_TPU_C_API_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ---- TCPStore rendezvous (reference: paddle/fluid/distributed/store) ---- */
void* pd_store_master_start(int port);       /* 0 picks a free port */
int   pd_store_master_port(void* master);
void  pd_store_master_stop(void* master);

void* pd_store_client_connect(const char* host, int port, int timeout_ms);
void  pd_store_client_close(void* client);
int   pd_store_set(void* client, const char* key, const uint8_t* data,
                   int len);
/* returns value length (may exceed cap: retry with a bigger buffer) */
int   pd_store_get(void* client, const char* key, uint8_t* out, int cap);
int   pd_store_add(void* client, const char* key, long long delta,
                   long long* out);
int   pd_store_wait(void* client, const char* key, int timeout_ms);

/* ---- host tracer (reference: paddle/fluid/platform/profiler) ----------- */
void  pd_trace_enable(int on);
void  pd_trace_begin(const char* name);
void  pd_trace_end(void);
int   pd_trace_count(void);
/* write events as chrome-trace JSON to path; returns 0 on success */
int   pd_trace_dump(const char* path);

/* ---- MPMC prefetch queue (reference: paddle/fluid/operators/reader) ---- */
void* pd_queue_new(int capacity);
/* item ownership transfers to the queue; 0 on success, -1 on timeout/closed */
int   pd_queue_put(void* q, void* item, int timeout_ms);
void* pd_queue_get(void* q, int timeout_ms);  /* NULL on timeout/closed */
int   pd_queue_size(void* q);
void  pd_queue_close(void* q);
void  pd_queue_free(void* q);

#ifdef __cplusplus
}  /* extern "C" */
#endif

#endif  /* PADDLE_TPU_C_API_H_ */
