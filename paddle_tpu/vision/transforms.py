"""Vision transforms. Parity: python/paddle/vision/transforms/ (core subset)."""
from __future__ import annotations

import numpy as np

from ..tensor.tensor import Tensor

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "RandomCrop",
           "CenterCrop", "RandomHorizontalFlip", "Transpose"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, dtype=np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return Tensor(arr)


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img,
                                                                     np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        arr = (arr - self.mean.reshape(shape)) / self.std.reshape(shape)
        return Tensor(arr)


class Resize:
    # reference interpolation names (and the cv2-backend int codes ported
    # code passes) -> jax.image.resize methods; 'area' has no jax.image
    # equivalent and raises loudly rather than silently bilinear-sampling
    # (which corrupts e.g. integer label masks)
    _METHODS = {"nearest": "nearest", "bilinear": "linear",
                "bicubic": "cubic", "lanczos": "lanczos3",
                0: "nearest", 1: "linear", 2: "cubic", 4: "lanczos3"}

    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        if interpolation not in self._METHODS:
            raise ValueError(
                f"Resize: unsupported interpolation {interpolation!r}; "
                f"supported: {sorted(map(str, self._METHODS))}")
        self.interpolation = interpolation

    def __call__(self, img):
        import jax
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
        dtype = arr.dtype
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        if chw:
            new_shape = (arr.shape[0],) + self.size
        else:
            new_shape = self.size + (arr.shape[-1],) if arr.ndim == 3 else self.size
        method = self._METHODS[self.interpolation]
        if method == "nearest":   # exact-copy sampling: any dtype directly
            out = jax.image.resize(arr, new_shape, method="nearest")
        else:
            out = jax.image.resize(arr.astype(np.float32), new_shape,
                                   method=method)
            if np.issubdtype(dtype, np.integer):
                info = np.iinfo(dtype)
                out = np.clip(np.rint(np.asarray(out)), info.min, info.max)
        return Tensor(np.asarray(out).astype(dtype, copy=False))


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        if self.padding:
            pads = [(0, 0)] * arr.ndim
            pads[h_ax] = (self.padding, self.padding)
            pads[w_ax] = (self.padding, self.padding)
            arr = np.pad(arr, pads)
        h, w = arr.shape[h_ax], arr.shape[w_ax]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        sl = [slice(None)] * arr.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        return Tensor(arr[tuple(sl)])


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        h, w = arr.shape[h_ax], arr.shape[w_ax]
        th, tw = self.size
        i = (h - th) // 2
        j = (w - tw) // 2
        sl = [slice(None)] * arr.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        return Tensor(arr[tuple(sl)])


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
        if np.random.rand() < self.prob:
            arr = arr[..., ::-1].copy()
        return Tensor(arr)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
        return Tensor(arr.transpose(self.order))


# ---------------------------------------------------------------------------
# Functional API (host-side numpy: these run in the input pipeline before
# device transfer, like the reference's transforms.functional on ndarray)
# ---------------------------------------------------------------------------

def _to_arr(img):
    """ndarray view of the input + whether it was a Tensor + CHW flag."""
    was_tensor = isinstance(img, Tensor)
    arr = img.numpy() if was_tensor else np.asarray(img)
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[-1] not in (1, 3)
    return arr, was_tensor, chw


def _wrap(arr, was_tensor):
    return Tensor(np.ascontiguousarray(arr)) if was_tensor else arr


def _hwc(arr, chw):
    return arr.transpose(1, 2, 0) if chw else arr


def _unhwc(arr, chw):
    return arr.transpose(2, 0, 1) if chw else arr


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def hflip(img):
    arr, wt, chw = _to_arr(img)
    return _wrap(arr[..., ::-1] if (chw or arr.ndim == 2) else
                 arr[:, ::-1], wt)


def vflip(img):
    arr, wt, chw = _to_arr(img)
    if chw:
        return _wrap(arr[:, ::-1], wt)
    return _wrap(arr[::-1], wt)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img) if isinstance(img, Tensor) \
        else np.asarray(Resize(size, interpolation)(img).numpy())


def crop(img, top, left, height, width):
    arr, wt, chw = _to_arr(img)
    h_ax, w_ax = (1, 2) if chw else (0, 1)
    sl = [slice(None)] * arr.ndim
    sl[h_ax] = slice(top, top + height)
    sl[w_ax] = slice(left, left + width)
    return _wrap(arr[tuple(sl)], wt)


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


def pad(img, padding, fill=0, padding_mode="constant"):
    arr, wt, chw = _to_arr(img)
    if isinstance(padding, int):
        l = r = t = b = padding
    elif len(padding) == 2:
        l = r = padding[0]
        t = b = padding[1]
    else:
        l, t, r, b = padding
    h_ax, w_ax = (1, 2) if chw else (0, 1)
    pads = [(0, 0)] * arr.ndim
    pads[h_ax] = (t, b)
    pads[w_ax] = (l, r)
    mode = {"constant": "constant", "reflect": "reflect",
            "edge": "edge", "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return _wrap(np.pad(arr, pads, mode=mode, **kw), wt)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def adjust_brightness(img, brightness_factor):
    arr, wt, chw = _to_arr(img)
    out = np.clip(arr.astype(np.float32) * brightness_factor, 0,
                  255 if arr.dtype == np.uint8 else None)
    return _wrap(out.astype(arr.dtype), wt)


def adjust_contrast(img, contrast_factor):
    arr, wt, chw = _to_arr(img)
    f = arr.astype(np.float32)
    hw = _hwc(f, chw) if f.ndim == 3 else f
    gray = hw @ np.array([0.299, 0.587, 0.114], np.float32) \
        if f.ndim == 3 and hw.shape[-1] == 3 else hw
    mean = gray.mean()
    out = mean + contrast_factor * (f - mean)
    out = np.clip(out, 0, 255 if arr.dtype == np.uint8 else None)
    return _wrap(out.astype(arr.dtype), wt)


def adjust_saturation(img, saturation_factor):
    arr, wt, chw = _to_arr(img)
    f = arr.astype(np.float32)
    hw = _hwc(f, chw)
    gray = (hw @ np.array([0.299, 0.587, 0.114], np.float32))[..., None]
    out = gray + saturation_factor * (hw - gray)
    out = np.clip(out, 0, 255 if arr.dtype == np.uint8 else None)
    return _wrap(_unhwc(out, chw).astype(arr.dtype), wt)


def _rgb_to_hsv(rgb):
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    maxc = np.max(rgb, -1)
    minc = np.min(rgb, -1)
    v = maxc
    diff = maxc - minc
    s = np.where(maxc > 0, diff / np.maximum(maxc, 1e-12), 0.0)
    diff_safe = np.maximum(diff, 1e-12)
    rc = (maxc - r) / diff_safe
    gc = (maxc - g) / diff_safe
    bc = (maxc - b) / diff_safe
    h = np.where(maxc == r, bc - gc,
                 np.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc))
    h = np.where(diff > 0, (h / 6.0) % 1.0, 0.0)
    return np.stack([h, s, v], -1)


def _hsv_to_rgb(hsv):
    h, s, v = hsv[..., 0], hsv[..., 1], hsv[..., 2]
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(np.int32) % 6
    out = np.choose(i[..., None] * 0 + i[..., None],
                    [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
                     np.stack([p, v, t], -1), np.stack([p, q, v], -1),
                     np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    return out


def adjust_hue(img, hue_factor):
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError(f"hue_factor {hue_factor} not in [-0.5, 0.5]")
    arr, wt, chw = _to_arr(img)
    scale = 255.0 if arr.dtype == np.uint8 else 1.0
    hw = _hwc(arr.astype(np.float32), chw) / scale
    hsv = _rgb_to_hsv(hw)
    hsv[..., 0] = (hsv[..., 0] + hue_factor) % 1.0
    out = _hsv_to_rgb(hsv) * scale
    return _wrap(_unhwc(out, chw).astype(arr.dtype), wt)


def to_grayscale(img, num_output_channels=1):
    arr, wt, chw = _to_arr(img)
    hw = _hwc(arr.astype(np.float32), chw)
    gray = hw @ np.array([0.299, 0.587, 0.114], np.float32)
    out = np.repeat(gray[..., None], num_output_channels, -1)
    return _wrap(_unhwc(out, chw).astype(arr.dtype), wt)


def _inv_affine_matrix(angle, translate, scale, shear, center):
    """Inverse of the affine transform (output->input coords), matching
    the reference's rotation-about-center + shear + scale + translate."""
    rot = np.deg2rad(angle)
    sx, sy = (np.deg2rad(s) for s in (shear if isinstance(shear, (list,
              tuple)) else (shear, 0.0)))
    cx, cy = center
    tx, ty = translate
    # forward: T(center) R S Sh T(-center) + translate
    a = np.cos(rot - sy) / np.cos(sy)
    b = -np.cos(rot - sy) * np.tan(sx) / np.cos(sy) - np.sin(rot)
    c = np.sin(rot - sy) / np.cos(sy)
    d = -np.sin(rot - sy) * np.tan(sx) / np.cos(sy) + np.cos(rot)
    m = np.array([[a * scale, b * scale, 0.0],
                  [c * scale, d * scale, 0.0],
                  [0.0, 0.0, 1.0]], np.float64)
    m[0, 2] = cx + tx - m[0, 0] * cx - m[0, 1] * cy
    m[1, 2] = cy + ty - m[1, 0] * cx - m[1, 1] * cy
    return np.linalg.inv(m)


def _warp(img, inv3, fill=0.0, interpolation="bilinear"):
    """Inverse warp with a 3x3 output->input homography; bilinear or
    nearest sampling (nearest preserves label values on integer masks)."""
    arr, wt, chw = _to_arr(img)
    f = _hwc(arr.astype(np.float32), chw)
    if f.ndim == 2:
        f = f[..., None]
        squeeze = True
    else:
        squeeze = False
    H, W, C = f.shape
    ys, xs = np.meshgrid(np.arange(H), np.arange(W), indexing="ij")
    ones = np.ones_like(xs)
    coords = np.stack([xs, ys, ones], 0).reshape(3, -1).astype(np.float64)
    src = inv3 @ coords
    sx = src[0] / src[2]
    sy = src[1] / src[2]
    def sample(yy, xx):
        valid = (yy >= 0) & (yy < H) & (xx >= 0) & (xx < W)
        yc = np.clip(yy, 0, H - 1)
        xc = np.clip(xx, 0, W - 1)
        vals = f[yc, xc]
        return np.where(valid[:, None], vals, np.float32(fill))

    if interpolation == "nearest":
        out = sample(np.round(sy).astype(np.int64),
                     np.round(sx).astype(np.int64))
    else:
        x0 = np.floor(sx).astype(np.int64)
        y0 = np.floor(sy).astype(np.int64)
        fx = (sx - x0).astype(np.float32)[:, None]
        fy = (sy - y0).astype(np.float32)[:, None]
        out = (sample(y0, x0) * (1 - fx) * (1 - fy)
               + sample(y0, x0 + 1) * fx * (1 - fy)
               + sample(y0 + 1, x0) * (1 - fx) * fy
               + sample(y0 + 1, x0 + 1) * fx * fy)
    out = out.reshape(H, W, C)
    if squeeze:
        out = out[..., 0]
    return _wrap(_unhwc(out, chw).astype(arr.dtype), wt)


def affine(img, angle, translate, scale, shear, interpolation="bilinear",
           fill=0, center=None):
    arr, _, chw = _to_arr(img)
    h_ax, w_ax = (1, 2) if chw else (0, 1)
    H, W = arr.shape[h_ax], arr.shape[w_ax]
    c = center if center is not None else ((W - 1) * 0.5, (H - 1) * 0.5)
    return _warp(img, _inv_affine_matrix(angle, translate, scale, shear,
                                         c), fill, interpolation)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Counter-clockwise rotation (reference convention: rotate(angle) ==
    affine(-angle)). expand=True (grow the canvas to fit) is not
    implemented — the output keeps the input size."""
    return affine(img, -angle, (0, 0), 1.0, (0.0, 0.0), interpolation,
                  fill, center)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Projective warp mapping startpoints -> endpoints (4 corners)."""
    _interp = interpolation
    a = []
    bvec = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        a.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        a.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        bvec += [sx, sy]
    coeff = np.linalg.solve(np.asarray(a, np.float64),
                            np.asarray(bvec, np.float64))
    inv3 = np.array([[coeff[0], coeff[1], coeff[2]],
                     [coeff[3], coeff[4], coeff[5]],
                     [coeff[6], coeff[7], 1.0]])
    return _warp(img, inv3, fill, _interp)


def erase(img, i, j, h, w, v, inplace=False):
    arr, wt, chw = _to_arr(img)
    out = arr if inplace else arr.copy()
    h_ax = 1 if chw else 0
    sl = [slice(None)] * out.ndim
    sl[h_ax] = slice(i, i + h)
    sl[h_ax + 1] = slice(j, j + w)
    vv = v.numpy() if isinstance(v, Tensor) else np.asarray(v)
    out[tuple(sl)] = vv.astype(out.dtype)
    return _wrap(out, wt)


# ---------------------------------------------------------------------------
# Transform classes over the functional API
# ---------------------------------------------------------------------------

class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        return vflip(img) if np.random.rand() < self.prob else img


class BrightnessTransform:
    def __init__(self, value):
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_brightness(img, f)


class ContrastTransform:
    def __init__(self, value):
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, f)


class SaturationTransform:
    def __init__(self, value):
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_saturation(img, f)


class HueTransform:
    def __init__(self, value):
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return img
        return adjust_hue(img, np.random.uniform(-self.value, self.value))


class ColorJitter:
    def __init__(self, brightness=0.0, contrast=0.0, saturation=0.0,
                 hue=0.0):
        self.ts = [BrightnessTransform(brightness),
                   ContrastTransform(contrast),
                   SaturationTransform(saturation), HueTransform(hue)]

    def __call__(self, img):
        for t in np.random.permutation(self.ts):
            img = t(img)
        return img


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.args = (padding, fill, padding_mode)

    def __call__(self, img):
        return pad(img, *self.args)


class Grayscale:
    def __init__(self, num_output_channels=1):
        self.n = num_output_channels

    def __call__(self, img):
        return to_grayscale(img, self.n)


class RandomRotation:
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0):
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) \
            else tuple(degrees)
        self.center, self.fill = center, fill
        self.interpolation = interpolation

    def __call__(self, img):
        angle = np.random.uniform(*self.degrees)
        return rotate(img, angle, self.interpolation, False, self.center,
                      self.fill)


class RandomAffine:
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None):
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) \
            else tuple(degrees)
        self.translate, self.scale_rng = translate, scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill, self.center = fill, center

    def __call__(self, img):
        arr, _, chw = _to_arr(img)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        H, W = arr.shape[h_ax], arr.shape[w_ax]
        angle = np.random.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * W
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * H
        sc = np.random.uniform(*self.scale_rng) if self.scale_rng else 1.0
        sh = (0.0, 0.0)
        if self.shear is not None:
            s = self.shear
            sh = (np.random.uniform(-s, s), 0.0) if np.isscalar(s) else \
                (np.random.uniform(s[0], s[1]), 0.0)
        return affine(img, angle, (tx, ty), sc, sh,
                      interpolation=self.interpolation, fill=self.fill,
                      center=self.center)


class RandomPerspective:
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0):
        self.prob, self.d = prob, distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def __call__(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr, _, chw = _to_arr(img)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        H, W = arr.shape[h_ax], arr.shape[w_ax]
        dx, dy = self.d * W / 2, self.d * H / 2
        start = [(0, 0), (W - 1, 0), (W - 1, H - 1), (0, H - 1)]
        end = [(np.random.uniform(0, dx), np.random.uniform(0, dy)),
               (W - 1 - np.random.uniform(0, dx), np.random.uniform(0, dy)),
               (W - 1 - np.random.uniform(0, dx),
                H - 1 - np.random.uniform(0, dy)),
               (np.random.uniform(0, dx), H - 1 - np.random.uniform(0, dy))]
        return perspective(img, start, end,
                           interpolation=self.interpolation,
                           fill=self.fill)


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4, 4. / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale, self.ratio = scale, ratio
        self.interpolation = interpolation

    def __call__(self, img):
        arr, _, chw = _to_arr(img)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        H, W = arr.shape[h_ax], arr.shape[w_ax]
        area = H * W
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            w = int(round(np.sqrt(target * ar)))
            h = int(round(np.sqrt(target / ar)))
            if 0 < w <= W and 0 < h <= H:
                top = np.random.randint(0, H - h + 1)
                left = np.random.randint(0, W - w + 1)
                return resize(crop(img, top, left, h, w), self.size,
                              self.interpolation)
        # fallback: center crop to the valid aspect
        return resize(center_crop(img, min(H, W)), self.size,
                      self.interpolation)


class RandomErasing:
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False):
        self.prob, self.scale, self.ratio = prob, scale, ratio
        self.value, self.inplace = value, inplace

    def __call__(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr, _, chw = _to_arr(img)
        h_ax = 1 if chw else 0
        H, W = arr.shape[h_ax], arr.shape[h_ax + 1]
        for _ in range(10):
            target = H * W * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            h = int(round(np.sqrt(target * ar)))
            w = int(round(np.sqrt(target / ar)))
            if h < H and w < W:
                i = np.random.randint(0, H - h + 1)
                j = np.random.randint(0, W - w + 1)
                val = np.asarray(self.value, np.float32)
                if arr.ndim == 2:
                    shape, val_r = (h, w), val
                elif chw:
                    shape = (arr.shape[0], h, w)
                    val_r = val.reshape(-1, 1, 1) if val.ndim else val
                else:
                    shape = (h, w, arr.shape[-1])
                    val_r = val
                v = np.broadcast_to(val_r, shape)
                return erase(img, i, j, h, w, v, self.inplace)
        return img


__all__ += ["RandomVerticalFlip", "ColorJitter", "RandomRotation",
            "RandomResizedCrop", "Pad", "Grayscale", "BrightnessTransform",
            "ContrastTransform", "SaturationTransform", "HueTransform",
            "RandomAffine", "RandomPerspective", "RandomErasing",
            "adjust_brightness", "adjust_contrast", "adjust_saturation",
            "adjust_hue", "affine", "center_crop", "crop", "erase",
            "hflip", "normalize", "pad", "perspective", "resize", "rotate",
            "to_grayscale", "to_tensor", "vflip"]
