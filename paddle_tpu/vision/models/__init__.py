"""Vision model zoo. Parity: python/paddle/vision/models/ (resnet, vgg,
mobilenet, lenet) + ViT for the benchmark config (BASELINE configs[3])."""
from .resnet import ResNet, resnet18, resnet34, resnet50, resnet101, resnet152
from .lenet import LeNet
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19
from .mobilenetv2 import MobileNetV2, mobilenet_v2
from .alexnet import AlexNet, alexnet
from .squeezenet import SqueezeNet, squeezenet1_0, squeezenet1_1
from .densenet import (DenseNet, densenet121, densenet161, densenet169,
                       densenet201, densenet264)
from .shufflenetv2 import (ShuffleNetV2, shufflenet_v2_x0_25,
                           shufflenet_v2_x0_5, shufflenet_v2_x1_0,
                           shufflenet_v2_x1_5, shufflenet_v2_x2_0)
from .googlenet import GoogLeNet, googlenet
