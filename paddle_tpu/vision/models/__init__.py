"""Vision model zoo. Parity: python/paddle/vision/models/ (resnet, vgg,
mobilenet, lenet) + ViT for the benchmark config (BASELINE configs[3])."""
from .resnet import ResNet, resnet18, resnet34, resnet50, resnet101, resnet152
from .lenet import LeNet
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19
from .mobilenetv2 import MobileNetV2, mobilenet_v2
