"""GoogLeNet (Inception v1). Parity: python/paddle/vision/models/googlenet.py."""
from __future__ import annotations

from ...nn.layer.activation import ReLU
from ...nn.layer.common import Dropout, Linear
from ...nn.layer.conv import Conv2D
from ...nn.layer.layers import Layer, Sequential
from ...nn.layer.pooling import AdaptiveAvgPool2D, MaxPool2D
from ...tensor.manipulation import concat, flatten

__all__ = ["GoogLeNet", "googlenet"]


def _conv_relu(in_ch, out_ch, k, stride=1, padding=0):
    return Sequential(Conv2D(in_ch, out_ch, k, stride=stride,
                             padding=padding), ReLU())


class Inception(Layer):
    def __init__(self, in_ch, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _conv_relu(in_ch, c1, 1)
        self.b2 = Sequential(_conv_relu(in_ch, c3r, 1),
                             _conv_relu(c3r, c3, 3, padding=1))
        self.b3 = Sequential(_conv_relu(in_ch, c5r, 1),
                             _conv_relu(c5r, c5, 5, padding=2))
        self.b4 = Sequential(MaxPool2D(3, stride=1, padding=1),
                             _conv_relu(in_ch, proj, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                      axis=1)


class GoogLeNet(Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = Sequential(
            _conv_relu(3, 64, 7, stride=2, padding=3),
            MaxPool2D(3, stride=2, padding=1),
            _conv_relu(64, 64, 1),
            _conv_relu(64, 192, 3, padding=1),
            MaxPool2D(3, stride=2, padding=1))
        self.inc3 = Sequential(
            Inception(192, 64, 96, 128, 16, 32, 32),
            Inception(256, 128, 128, 192, 32, 96, 64),
            MaxPool2D(3, stride=2, padding=1))
        self.inc4 = Sequential(
            Inception(480, 192, 96, 208, 16, 48, 64),
            Inception(512, 160, 112, 224, 24, 64, 64),
            Inception(512, 128, 128, 256, 24, 64, 64),
            Inception(512, 112, 144, 288, 32, 64, 64),
            Inception(528, 256, 160, 320, 32, 128, 128),
            MaxPool2D(3, stride=2, padding=1))
        self.inc5 = Sequential(
            Inception(832, 256, 160, 320, 32, 128, 128),
            Inception(832, 384, 192, 384, 48, 128, 128))
        self.pool = AdaptiveAvgPool2D((1, 1))
        self.dropout = Dropout(0.2)
        self.fc = Linear(1024, num_classes)

    def forward(self, x):
        x = self.inc5(self.inc4(self.inc3(self.stem(x))))
        x = self.dropout(flatten(self.pool(x), start_axis=1))
        return self.fc(x)


def googlenet(pretrained=False, **kw):
    return GoogLeNet(**kw)
