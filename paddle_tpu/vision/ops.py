"""paddle.vision.ops — detection/vision operators.

Parity: python/paddle/vision/ops.py :: nms, roi_align, roi_pool, RoIAlign,
RoIPool, box_coder, yolo_box, distribute_fpn_proposals, deform_conv2d,
DeformConv2D, PSRoIPool (subset; CUDA kernels under
paddle/fluid/operators/detection/).

TPU-first realizations:
- nms: O(N²) pairwise-IoU mask + lax.while-free greedy scan — static
  shapes, no dynamic compaction on device; final index extraction is a
  host-side nonzero (detection post-processing is host-bound in practice).
- roi_align / roi_pool: bilinear-gather + pooled reductions per sampling
  grid, vectorized over (roi, bin, sample) — gathers feed the VPU.
- deform_conv2d: offset-shifted bilinear gathers + one MXU matmul per
  kernel tap (the rulebook-free dense analogue of the reference kernel).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.layer.layers import Layer
from ..tensor.tensor import Tensor, apply_op

__all__ = ["nms", "box_iou", "roi_align", "roi_pool", "RoIAlign", "RoIPool",
           "box_coder", "yolo_box", "distribute_fpn_proposals",
           "deform_conv2d", "DeformConv2D"]


def _arr(x):
    # deliberately dtype-preserving (boxes stay float, index/count inputs
    # stay integer) — unlike distribution._arr's float32 coercion
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def box_iou(boxes1, boxes2):
    """Pairwise IoU for [N,4] and [M,4] xyxy boxes → [N,M]."""
    def iou(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter + 1e-10)
    if isinstance(boxes1, Tensor) or isinstance(boxes2, Tensor):
        return apply_op(iou, boxes1 if isinstance(boxes1, Tensor)
                        else Tensor(_arr(boxes1)),
                        boxes2 if isinstance(boxes2, Tensor)
                        else Tensor(_arr(boxes2)))
    return Tensor(iou(_arr(boxes1), _arr(boxes2)))


def nms(boxes, iou_threshold: float = 0.3, scores=None,
        category_idxs=None, categories=None, top_k=None):
    """Greedy NMS → kept indices sorted by score. Category-aware when
    category_idxs given (reference semantics: suppression only within a
    category)."""
    b = np.asarray(_arr(boxes), np.float32)
    n = b.shape[0]
    s = (np.arange(n, 0, -1, dtype=np.float32) if scores is None
         else np.asarray(_arr(scores), np.float32))
    cats = None if category_idxs is None else np.asarray(
        _arr(category_idxs))
    order = np.argsort(-s)
    area = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    keep = []
    suppressed = np.zeros(n, bool)
    for oi in order:
        if suppressed[oi]:
            continue
        keep.append(oi)
        lt = np.maximum(b[oi, :2], b[:, :2])
        rb = np.minimum(b[oi, 2:], b[:, 2:])
        wh = np.clip(rb - lt, 0, None)
        inter = wh[:, 0] * wh[:, 1]
        iou = inter / (area[oi] + area - inter + 1e-10)
        kill = iou > iou_threshold
        if cats is not None:
            kill &= cats == cats[oi]
        suppressed |= kill
    kept = np.asarray(keep, np.int64)
    if top_k is not None:
        kept = kept[:top_k]
    return Tensor(kept)


def _roi_align_fn(feat, rois, roi_batch_ids, out_h, out_w, spatial_scale,
                  sampling_ratio, aligned, _adaptive_sr=2):
    """feat [N,C,H,W], rois [R,4] xyxy → [R,C,out_h,out_w].

    sampling_ratio=-1 uses a STATIC grid of _adaptive_sr samples per bin
    side — computed by the caller from the concrete RoIs when available
    (the reference adapts per-RoI, which is a dynamic shape XLA can't
    tile; one static grid sized for the largest bin is the TPU form)."""
    N, C, H, W = feat.shape
    offset = 0.5 if aligned else 0.0
    x1 = rois[:, 0] * spatial_scale - offset
    y1 = rois[:, 1] * spatial_scale - offset
    x2 = rois[:, 2] * spatial_scale - offset
    y2 = rois[:, 3] * spatial_scale - offset
    rw = x2 - x1
    rh = y2 - y1
    if not aligned:
        rw = jnp.maximum(rw, 1.0)
        rh = jnp.maximum(rh, 1.0)
    bin_h = rh / out_h
    bin_w = rw / out_w
    sr = int(sampling_ratio if sampling_ratio > 0 else _adaptive_sr)
    # sample grid: [R, out_h, sr] y coords and [R, out_w, sr] x coords
    iy = (jnp.arange(out_h)[None, :, None]
          + (jnp.arange(sr)[None, None, :] + 0.5) / sr)
    ys = y1[:, None, None] + iy * bin_h[:, None, None]       # [R,oh,sr]
    ix = (jnp.arange(out_w)[None, :, None]
          + (jnp.arange(sr)[None, None, :] + 0.5) / sr)
    xs = x1[:, None, None] + ix * bin_w[:, None, None]       # [R,ow,sr]

    def bilinear(r_feat, yy, xx):
        # r_feat [C,H,W]; yy [oh,sr]; xx [ow,sr] → [C,oh,sr,ow,sr]
        y0 = jnp.clip(jnp.floor(yy), 0, H - 1)
        x0 = jnp.clip(jnp.floor(xx), 0, W - 1)
        y1_ = jnp.clip(y0 + 1, 0, H - 1)
        x1_ = jnp.clip(x0 + 1, 0, W - 1)
        wy1 = jnp.clip(yy - y0, 0, 1)
        wx1 = jnp.clip(xx - x0, 0, 1)
        wy0, wx0 = 1 - wy1, 1 - wx1
        y0i, y1i = y0.astype(jnp.int32), y1_.astype(jnp.int32)
        x0i, x1i = x0.astype(jnp.int32), x1_.astype(jnp.int32)

        def gather(yi, xi):
            # [C, oh, sr, ow, sr]
            return r_feat[:, yi[:, :, None, None], xi[None, None, :, :]]
        val = (gather(y0i, x0i) * (wy0[:, :, None, None]
                                   * wx0[None, None, :, :])
               + gather(y0i, x1i) * (wy0[:, :, None, None]
                                     * wx1[None, None, :, :])
               + gather(y1i, x0i) * (wy1[:, :, None, None]
                                     * wx0[None, None, :, :])
               + gather(y1i, x1i) * (wy1[:, :, None, None]
                                     * wx1[None, None, :, :]))
        # outside-image samples contribute 0 (reference semantics)
        valid = ((yy >= -1) & (yy <= H))[:, :, None, None] & \
                ((xx >= -1) & (xx <= W))[None, None, :, :]
        return jnp.where(valid, val, 0.0)

    def per_roi(r):
        r_feat = feat[roi_batch_ids[r]]
        val = bilinear(r_feat, ys[r], xs[r])       # [C,oh,sr,ow,sr]
        return val.mean(axis=(2, 4))               # average over samples
    return jax.vmap(per_roi)(jnp.arange(rois.shape[0]))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """boxes: [R,4] concatenated across batch; boxes_num: per-image counts."""
    out_h, out_w = (output_size, output_size) if isinstance(
        output_size, int) else tuple(output_size)
    bn = np.asarray(_arr(boxes_num)).astype(np.int64)
    batch_ids = jnp.asarray(np.repeat(np.arange(len(bn)), bn), jnp.int32)
    adaptive = 2
    if sampling_ratio <= 0:
        try:  # concrete boxes: size the static grid for the largest bin
            b_np = np.asarray(_arr(boxes))
            bh = (b_np[:, 3] - b_np[:, 1]) * spatial_scale / out_h
            bw = (b_np[:, 2] - b_np[:, 0]) * spatial_scale / out_w
            adaptive = int(np.clip(np.ceil(max(bh.max(initial=1.0),
                                               bw.max(initial=1.0))),
                                   1, 8))
        except Exception:  # traced boxes: keep the default grid
            pass
    fn = lambda f, b: _roi_align_fn(f, b, batch_ids, out_h, out_w,
                                    spatial_scale, sampling_ratio, aligned,
                                    adaptive)
    return apply_op(fn, x if isinstance(x, Tensor) else Tensor(_arr(x)),
                    boxes if isinstance(boxes, Tensor)
                    else Tensor(_arr(boxes)))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    """Max pooling over each RoI bin (quantized, reference roi_pool)."""
    out_h, out_w = (output_size, output_size) if isinstance(
        output_size, int) else tuple(output_size)
    bn = np.asarray(_arr(boxes_num)).astype(np.int64)
    batch_ids = jnp.asarray(np.repeat(np.arange(len(bn)), bn), jnp.int32)

    def fn(feat, rois):
        N, C, H, W = feat.shape

        def per_roi(r):
            rf = feat[batch_ids[r]]
            x1 = jnp.round(rois[r, 0] * spatial_scale).astype(jnp.int32)
            y1 = jnp.round(rois[r, 1] * spatial_scale).astype(jnp.int32)
            x2 = jnp.round(rois[r, 2] * spatial_scale).astype(jnp.int32)
            y2 = jnp.round(rois[r, 3] * spatial_scale).astype(jnp.int32)
            rh = jnp.maximum(y2 - y1 + 1, 1)
            rw = jnp.maximum(x2 - x1 + 1, 1)

            ph = jnp.arange(out_h)
            pw = jnp.arange(out_w)
            hstart = y1 + (ph * rh) // out_h
            hend = y1 + ((ph + 1) * rh + out_h - 1) // out_h
            wstart = x1 + (pw * rw) // out_w
            wend = x1 + ((pw + 1) * rw + out_w - 1) // out_w
            yy = jnp.arange(H)[None, :]
            xx = jnp.arange(W)[None, :]
            ymask = (yy >= hstart[:, None]) & (yy < hend[:, None]) \
                & (yy >= 0) & (yy < H)                    # [oh,H]
            xmask = (xx >= wstart[:, None]) & (xx < wend[:, None]) \
                & (xx >= 0) & (xx < W)                    # [ow,W]
            m = ymask[:, None, :, None] & xmask[None, :, None, :]
            big = jnp.where(m[None], rf[:, None, None, :, :], -jnp.inf)
            out = big.max(axis=(3, 4))                    # [C,oh,ow]
            return jnp.where(jnp.isfinite(out), out, 0.0)
        return jax.vmap(per_roi)(jnp.arange(rois.shape[0]))
    return apply_op(fn, x if isinstance(x, Tensor) else Tensor(_arr(x)),
                    boxes if isinstance(boxes, Tensor)
                    else Tensor(_arr(boxes)))


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode boxes against priors (reference box_coder op)."""
    pb = _arr(prior_box)
    pbv = None if prior_box_var is None else jnp.asarray(
        np.asarray(prior_box_var, np.float32))
    tb = _arr(target_box)
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    px = pb[:, 0] + pw * 0.5
    py = pb[:, 1] + ph * 0.5
    if pbv is None:
        pbv = jnp.ones((4,), jnp.float32)
    if pbv.ndim == 1:
        pbv = jnp.broadcast_to(pbv, pb.shape)
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tx = tb[:, 0] + tw * 0.5
        ty = tb[:, 1] + th * 0.5
        # every target against every prior: [T, P, 4]
        ox = ((tx[:, None] - px[None, :]) / pw[None, :]) / pbv[None, :, 0]
        oy = ((ty[:, None] - py[None, :]) / ph[None, :]) / pbv[None, :, 1]
        ow = jnp.log(tw[:, None] / pw[None, :]) / pbv[None, :, 2]
        oh = jnp.log(th[:, None] / ph[None, :]) / pbv[None, :, 3]
        return Tensor(jnp.stack([ox, oy, ow, oh], axis=-1))
    # decode_center_size: tb [T, P, 4] deltas (or [P,4] broadcast)
    if tb.ndim == 2:
        tb = tb[:, None, :] if axis == 0 else tb[None, :, :]
    dx, dy, dw, dh = tb[..., 0], tb[..., 1], tb[..., 2], tb[..., 3]
    cx = dx * pbv[None, :, 0] * pw[None, :] + px[None, :]
    cy = dy * pbv[None, :, 1] * ph[None, :] + py[None, :]
    w = jnp.exp(dw * pbv[None, :, 2]) * pw[None, :]
    h = jnp.exp(dh * pbv[None, :, 3]) * ph[None, :]
    return Tensor(jnp.stack([cx - w * 0.5, cy - h * 0.5,
                             cx + w * 0.5 - norm, cy + h * 0.5 - norm],
                            axis=-1))


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """Decode YOLOv3 head output [N, A*(5+K), H, W] into boxes+scores
    (reference yolo_box op)."""
    xa = _arr(x)
    img = _arr(img_size).astype(jnp.float32)
    N, _, H, W = xa.shape
    A = len(anchors) // 2
    K = class_num
    ioup = None
    if iou_aware:
        # reference layout: A iou channels first, then A*(5+K) head channels
        ioup = jax.nn.sigmoid(xa[:, :A])                 # [N,A,H,W]
        xa = xa[:, A:]
    a = xa.reshape(N, A, 5 + K, H, W)
    gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    anc = jnp.asarray(np.asarray(anchors, np.float32).reshape(A, 2))
    sig = jax.nn.sigmoid
    bx = (sig(a[:, :, 0]) * scale_x_y
          - 0.5 * (scale_x_y - 1) + gx) / W            # [N,A,H,W]
    by = (sig(a[:, :, 1]) * scale_x_y
          - 0.5 * (scale_x_y - 1) + gy) / H
    input_w = W * downsample_ratio
    input_h = H * downsample_ratio
    bw = jnp.exp(a[:, :, 2]) * anc[None, :, 0, None, None] / input_w
    bh = jnp.exp(a[:, :, 3]) * anc[None, :, 1, None, None] / input_h
    conf = sig(a[:, :, 4])
    if ioup is not None:
        conf = conf ** (1.0 - iou_aware_factor) * ioup ** iou_aware_factor
    cls = sig(a[:, :, 5:])                              # [N,A,K,H,W]
    scores = conf[:, :, None] * cls
    imh = img[:, 0][:, None, None, None]
    imw = img[:, 1][:, None, None, None]
    x1 = (bx - bw / 2) * imw
    y1 = (by - bh / 2) * imh
    x2 = (bx + bw / 2) * imw
    y2 = (by + bh / 2) * imh
    if clip_bbox:
        x1 = jnp.clip(x1, 0, imw - 1)
        y1 = jnp.clip(y1, 0, imh - 1)
        x2 = jnp.clip(x2, 0, imw - 1)
        y2 = jnp.clip(y2, 0, imh - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(N, -1, 4)
    mask = (conf > conf_thresh).reshape(N, 1, -1)
    scores = scores.transpose(0, 2, 1, 3, 4).reshape(N, K, -1)
    scores = jnp.where(mask, scores, 0.0).transpose(0, 2, 1)  # [N,AHW,K]
    return Tensor(boxes), Tensor(scores)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale (reference op; host-side
    structure work)."""
    rois = np.asarray(_arr(fpn_rois), np.float32)
    w = rois[:, 2] - rois[:, 0]
    h = rois[:, 3] - rois[:, 1]
    scale = np.sqrt(np.clip(w * h, 0, None))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    # image id per roi (rois_num gives per-image counts; one image if absent)
    if rois_num is not None:
        per_img = np.asarray(_arr(rois_num)).astype(np.int64)
    else:
        per_img = np.asarray([len(rois)], np.int64)
    img_id = np.repeat(np.arange(len(per_img)), per_img)
    outs, order, rois_num_per = [], [], []
    for L in range(min_level, max_level + 1):
        sel = np.nonzero(lvl == L)[0]
        # keep per-level rois grouped by image (reference ordering)
        sel = sel[np.argsort(img_id[sel], kind="stable")]
        outs.append(Tensor(rois[sel]))
        order.append(sel)
        rois_num_per.append(Tensor(np.bincount(
            img_id[sel], minlength=len(per_img)).astype(np.int32)))
    restore = np.argsort(np.concatenate(order)) if order else np.zeros(0)
    return outs, Tensor(restore.astype(np.int64)), rois_num_per


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (mask → v2). x [N,Cin,H,W], offset
    [N, 2*dg*kh*kw, Ho, Wo], weight [Cout, Cin/g, kh, kw]."""
    def _2(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    sh, sw = _2(stride)
    ph, pw = _2(padding)
    dh, dw = _2(dilation)
    wshape = tuple(weight.shape)
    cout, cin_g, kh, kw = wshape
    assert groups == 1 and deformable_groups == 1, \
        "deform_conv2d subset: groups == deformable_groups == 1"

    def fn(xa, off, w, *maybe):
        N, Cin, H, W = xa.shape
        Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        mask_a = maybe[0] if maybe else None
        base_y = (jnp.arange(Ho) * sh - ph)[:, None]      # [Ho,1]
        base_x = (jnp.arange(Wo) * sw - pw)[None, :]      # [1,Wo]
        off = off.reshape(N, kh * kw, 2, Ho, Wo)
        cols = []
        for ki in range(kh):
            for kj in range(kw):
                t = ki * kw + kj
                yy = base_y + ki * dh + off[:, t, 0]      # [N,Ho,Wo]
                xx = base_x + kj * dw + off[:, t, 1]
                y0 = jnp.floor(yy)
                x0 = jnp.floor(xx)
                wy1 = yy - y0
                wx1 = xx - x0
                val = 0.0
                for oy, wyw in ((0, 1 - wy1), (1, wy1)):
                    for ox, wxw in ((0, 1 - wx1), (1, wx1)):
                        yi = jnp.clip(y0 + oy, 0, H - 1).astype(jnp.int32)
                        xi = jnp.clip(x0 + ox, 0, W - 1).astype(jnp.int32)
                        inb = ((y0 + oy >= 0) & (y0 + oy <= H - 1)
                               & (x0 + ox >= 0) & (x0 + ox <= W - 1))
                        g = jax.vmap(
                            lambda f, a, b: f[:, a, b])(xa, yi, xi)
                        val = val + g * (wyw * wxw)[:, None] * inb[:, None]
                if mask_a is not None:
                    val = val * mask_a[:, t][:, None]
                cols.append(val)                          # [N,Cin,Ho,Wo]
        col = jnp.stack(cols, axis=2).reshape(
            N, Cin, kh * kw, Ho * Wo)                     # [N,Cin,KK,L]
        out = jnp.einsum("ock,nckl->nol",
                         w.reshape(cout, cin_g, kh * kw), col)
        return out.reshape(N, cout, Ho, Wo)

    args = [x if isinstance(x, Tensor) else Tensor(_arr(x)),
            offset if isinstance(offset, Tensor) else Tensor(_arr(offset)),
            weight if isinstance(weight, Tensor) else Tensor(_arr(weight))]
    if mask is not None:
        args.append(mask if isinstance(mask, Tensor)
                    else Tensor(_arr(mask)))
    out = apply_op(fn, *args)
    if bias is not None:
        out = apply_op(lambda a, b: a + b[None, :, None, None], out,
                       bias if isinstance(bias, Tensor)
                       else Tensor(_arr(bias)))
    return out


class DeformConv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        from ..nn.initializer import Constant, Uniform
        def _2(v):
            return (v, v) if isinstance(v, int) else tuple(v)
        kh, kw = _2(kernel_size)
        self.stride, self.padding, self.dilation = stride, padding, dilation
        k = 1.0 / np.sqrt(in_channels * kh * kw)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, kh, kw],
            default_initializer=Uniform(-k, k))
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], is_bias=True,
            default_initializer=Constant(0.0))

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             self.stride, self.padding, self.dilation,
                             mask=mask)
