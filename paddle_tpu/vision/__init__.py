from . import models
from . import transforms
from . import datasets
from . import ops
