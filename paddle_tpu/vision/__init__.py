from . import models
from . import transforms
