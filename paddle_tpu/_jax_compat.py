"""Version shims for the jax API surface this codebase targets.

The code is written against jax >= 0.6, where `shard_map` is a top-level
export (`jax.shard_map` / `from jax import shard_map`) and its
replication-check kwarg is spelled `check_vma`. Older 0.4.x installs ship
the same functionality as `jax.experimental.shard_map.shard_map` with the
kwarg spelled `check_rep`. Rather than fork every call site (and the
tests, which also do `from jax import shard_map`), this module installs a
uniform `jax.shard_map` into the jax namespace when it is missing.

Imported for its side effect from paddle_tpu/__init__.py, before any
submodule that does `from jax import shard_map` at module scope.
"""
from __future__ import annotations

import jax

if not hasattr(jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, check_rep=None, axis_names=None, **kw):
        """jax>=0.6-style shard_map on a 0.4.x install. `check_vma` maps
        onto the old `check_rep` switch (both gate the same replication/
        varying-manual-axes validation; passing False skips it), and
        `axis_names` (the MANUAL axes) onto the old `auto` kwarg (its
        complement: the mesh axes left to GSPMD)."""
        if check_rep is None and check_vma is not None:
            check_rep = check_vma
        if check_rep is not None:
            kw["check_rep"] = check_rep
        if axis_names is not None:
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
            kw["auto"] = auto
            if auto:
                # jax>=0.6 resolves bare PartitionSpecs inside a
                # partially-auto shard_map against the call-site mesh;
                # 0.4.x needs the mesh context manager active while the
                # body traces, or with_sharding_constraint(P(...)) raises
                # "requires a non-empty mesh"
                inner, phys = f, mesh

                def f(*a, **k):
                    with phys:
                        return inner(*a, **k)
        return _shard_map_legacy(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, **kw)

    jax.shard_map = shard_map

if not hasattr(jax.lax, "pvary"):
    def _pvary(x, axis_name):  # noqa: ARG001 - name(s) unused on 0.4.x
        """jax>=0.6's lax.pvary marks a replicated value as varying over
        manual axes for the vma (varying-manual-axes) type system. 0.4.x
        has no vma tracking — its check_rep model treats replicated and
        varying uniformly — so the marker is the identity."""
        return x

    jax.lax.pvary = _pvary

if not hasattr(jax.lax, "axis_size"):
    def _axis_size(axis_name):
        """jax>=0.6's lax.axis_size on 0.4.x: psum of 1 over the axis —
        constant-folded at trace time inside shard_map/pmap, so no
        runtime collective is actually issued."""
        return jax.lax.psum(1, axis_name)

    jax.lax.axis_size = _axis_size

if not hasattr(jax, "typeof"):
    # jax>=0.6's jax.typeof is the abstract value; 0.4.x spells it
    # core.get_aval. 0.4.x avals have no .vma attribute, which callers
    # already probe with getattr(..., None) — the right degradation.
    jax.typeof = jax.core.get_aval
