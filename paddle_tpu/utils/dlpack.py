"""paddle.utils.dlpack. Parity: python/paddle/utils/dlpack.py ::
to_dlpack, from_dlpack — tensor exchange via the DLPack protocol.

jax.Array speaks __dlpack__ natively on CPU/GPU (zero-copy). TPU buffers are
not DLPack-addressable (the protocol has no TPU device type), so on TPU the
bridge transfers through host memory — the same data path the reference's
GPU→CPU interop takes, minus the zero-copy."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..tensor.tensor import Tensor

__all__ = ["to_dlpack", "from_dlpack"]


def _host_if_tpu(arr):
    try:
        platform = arr.devices().pop().platform if hasattr(
            arr, "devices") else "cpu"
    except Exception:
        platform = "cpu"
    if platform not in ("cpu", "gpu", "cuda", "rocm"):
        # writable host copy: TPU is outside DLPack's device model, and a
        # read-only np view cannot be exported through the protocol
        return np.array(arr)
    return arr


def to_dlpack(x: Tensor):
    """Export a tensor as a DLPack capsule (consumable by torch/numpy)."""
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return _host_if_tpu(arr).__dlpack__()


def from_dlpack(capsule) -> Tensor:
    """Import a DLPack capsule or any __dlpack__-bearing object."""
    if hasattr(capsule, "__dlpack__") and not _is_capsule(capsule):
        arr = jnp.from_dlpack(_host_if_tpu(capsule))
    else:
        # raw capsule: route through jax's dlpack importer
        from jax import dlpack as jdlpack
        arr = jdlpack.from_dlpack(capsule)
    return Tensor(arr)


def _is_capsule(obj) -> bool:
    return type(obj).__name__ == "PyCapsule"
