"""paddle.utils.download. Parity: python/paddle/utils/download.py ::
get_weights_path_from_url, get_path_from_url — resolved against the local
cache ONLY (this environment has zero egress; a cache miss is an error that
names the expected path rather than a silent hang)."""
from __future__ import annotations

import hashlib
import os
import os.path as osp

__all__ = ["get_weights_path_from_url", "get_path_from_url"]

WEIGHTS_HOME = osp.expanduser("~/.cache/paddle/hapi/weights")
DOWNLOAD_HOME = osp.expanduser("~/.cache/paddle/dataset")


def _md5check(fullname: str, md5sum: str | None) -> bool:
    if md5sum is None:
        return True
    md5 = hashlib.md5()
    with open(fullname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            md5.update(chunk)
    return md5.hexdigest() == md5sum


def get_path_from_url(url: str, root_dir: str, md5sum: str | None = None,
                      check_exist: bool = True) -> str:
    """Map url → {root_dir}/{basename}; require it to already exist locally
    (offline environment). Decompression of archives is handled by the
    caller in the reference; here a pre-extracted directory also counts."""
    fname = osp.split(url)[-1]
    fullname = osp.join(root_dir, fname)
    # pre-extracted directory (reference decompresses then returns the dir)
    stem = fullname
    for ext in (".tar.gz", ".tgz", ".tar", ".zip"):
        if stem.endswith(ext):
            stem = stem[:-len(ext)]
            break
    if osp.isdir(stem):
        return stem
    if osp.exists(fullname):
        if check_exist and not _md5check(fullname, md5sum):
            raise RuntimeError(
                f"md5 mismatch for cached file {fullname}; remove it and "
                f"re-provision")
        return fullname
    raise RuntimeError(
        f"cannot download {url}: this environment has no network access. "
        f"Place the file at {fullname} (or the extracted dir at {stem}) "
        f"and retry.")


def get_weights_path_from_url(url: str, md5sum: str | None = None) -> str:
    """Resolve a pretrained-weights url against the local weights cache."""
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
