"""paddle.utils.cpp_extension. Parity: python/paddle/utils/cpp_extension/ ::
load, CppExtension, setup — JIT-compile a C++ sources list into a shared
library and expose its functions. pybind11 is not in this image, so the ABI
is plain-C (extern "C") loaded via ctypes — the same binding strategy as the
framework's own native runtime (paddle_tpu/core/native.py, csrc/runtime.cc)."""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sysconfig as _pysysconfig
import tempfile

__all__ = ["load", "CppExtension", "CUDAExtension", "setup",
           "get_build_directory"]


def get_build_directory() -> str:
    d = os.environ.get("PADDLE_EXTENSION_DIR",
                       os.path.join(tempfile.gettempdir(),
                                    "paddle_tpu_extensions"))
    os.makedirs(d, exist_ok=True)
    return d


def load(name: str, sources: list[str], extra_cxx_cflags=None,
         extra_ldflags=None, verbose: bool = False,
         build_directory: str | None = None):
    """Compile sources into lib{name}.so and return a ctypes.CDLL handle.

    Functions must be declared extern "C"; callers attach argtypes/restype
    themselves (ctypes binding model, not pybind11 auto-binding)."""
    build_dir = build_directory or get_build_directory()
    srcs = [os.path.abspath(s) for s in sources]
    key = hashlib.sha1(("|".join(srcs) + repr(extra_cxx_cflags)
                        + repr(extra_ldflags)).encode())
    for s in srcs:
        with open(s, "rb") as f:
            key.update(f.read())
    out = os.path.join(build_dir, f"lib{name}_{key.hexdigest()[:12]}.so")
    if not os.path.exists(out):
        cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
               "-o", out, *srcs,
               "-I", _pysysconfig.get_paths()["include"],
               *(extra_cxx_cflags or []), *(extra_ldflags or []),
               "-lpthread"]
        if verbose:
            print(" ".join(cmd))
        res = subprocess.run(cmd, capture_output=True, text=True)
        if res.returncode != 0:
            raise RuntimeError(
                f"cpp_extension build failed:\n{res.stderr}")
    return ctypes.CDLL(out)


class CppExtension:
    """Declarative extension spec for setup() (setuptools-compatible)."""

    def __init__(self, sources, *args, **kwargs):
        self.sources = sources
        self.kwargs = kwargs
        self.name = kwargs.get("name")


def CUDAExtension(sources, *args, **kwargs):  # pragma: no cover - no CUDA
    raise RuntimeError(
        "CUDAExtension is not supported on the TPU build; write a Pallas "
        "kernel (paddle_tpu/ops/pallas/) or a C++ host extension instead.")


def setup(name: str, ext_modules=None, **kwargs):
    """Build each CppExtension eagerly into the extension dir (the
    reference delegates to setuptools; here load() is the builder)."""
    exts = ext_modules or []
    if not isinstance(exts, (list, tuple)):
        exts = [exts]
    return [load(ext.name or name, ext.sources,
                 extra_cxx_cflags=ext.kwargs.get("extra_cxx_cflags"),
                 extra_ldflags=ext.kwargs.get("extra_ldflags"))
            for ext in exts]
