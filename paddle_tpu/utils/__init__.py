"""paddle.utils. Parity: python/paddle/utils/ — deprecated decorator,
try_import/require_version, dlpack bridge, nested-structure helpers
(flatten/pack_sequence_as/map_structure), run_check install check, and the
download helpers (offline: local cache only, zero-egress environment)."""
from __future__ import annotations

import functools
import importlib
import os
import warnings

from . import unique_name
from . import download
from . import dlpack
from . import cpp_extension

__all__ = ["deprecated", "try_import", "require_version", "run_check",
           "flatten", "pack_sequence_as", "map_structure", "unique_name",
           "download", "dlpack", "cpp_extension"]


def deprecated(update_to: str = "", since: str = "", reason: str = "",
               level: int = 1):
    """Mark an API deprecated; warns once per call site like the reference
    (python/paddle/utils/deprecated.py)."""

    def decorator(fn):
        msg = f"API '{fn.__module__}.{fn.__name__}' is deprecated"
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f", use '{update_to}' instead"
        if reason:
            msg += f". Reason: {reason}"
        if level == 2:
            def dead(*a, **k):
                raise RuntimeError(msg)
            return functools.wraps(fn)(dead)

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        wrapper.__doc__ = (fn.__doc__ or "") + f"\n\n.. deprecated:: {msg}"
        return wrapper
    return decorator


def try_import(module_name: str, err_msg: str | None = None):
    """Import a soft dependency, raising a friendly error if absent."""
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(
            err_msg or f"Optional dependency '{module_name}' is required "
            f"for this API; it is not installed in this environment.")


def require_version(min_version: str, max_version: str | None = None):
    """Check the installed framework version against [min, max]."""
    from ..version import full_version

    def _tup(v):
        return tuple(int(p) for p in str(v).split(".")[:3] if p.isdigit())

    cur = _tup(full_version)
    if _tup(min_version) > cur:
        raise Exception(
            f"version {full_version} < required minimum {min_version}")
    if max_version is not None and _tup(max_version) < cur:
        raise Exception(
            f"version {full_version} > allowed maximum {max_version}")
    return True


def run_check():
    """Parity: paddle.utils.run_check — verify the install can compile and
    run a matmul on the current backend, and report device count."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    n = len(jax.devices())
    plat = jax.devices()[0].platform
    x = jnp.ones((4, 4))
    y = jax.jit(lambda a: a @ a)(x)
    assert np.allclose(np.asarray(y), 4.0)
    print(f"PaddleTPU works well on 1 {plat} device.")
    if n > 1:
        print(f"PaddleTPU works well on {n} {plat} devices.")
    print("PaddleTPU is installed successfully!")


# ---- nested structure helpers (python/paddle/utils/layers_utils.py) ----

def flatten(nest):
    """Flatten a nested structure (dict/list/tuple) into a flat list,
    matching paddle.utils.flatten ordering (dicts by insertion order)."""
    import jax
    return jax.tree.leaves(nest, is_leaf=lambda x: x is None)


def pack_sequence_as(structure, flat_sequence):
    """Inverse of flatten: pack a flat list back into the given structure."""
    import jax
    treedef = jax.tree.structure(structure, is_leaf=lambda x: x is None)
    return jax.tree.unflatten(treedef, flat_sequence)


def map_structure(func, *structures):
    """Apply func leaf-wise across parallel nested structures."""
    import jax
    return jax.tree.map(func, *structures)
