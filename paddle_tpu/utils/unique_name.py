"""paddle.utils.unique_name. Parity: python/paddle/utils/unique_name.py ::
generate, guard, switch — process-wide unique name generator used by Layer
parameter naming and static-graph variable naming."""
from __future__ import annotations

import contextlib

__all__ = ["generate", "guard", "switch"]


class UniqueNameGenerator:
    def __init__(self):
        self.ids: dict[str, int] = {}

    def __call__(self, key: str) -> str:
        tmp = self.ids.setdefault(key, 0)
        self.ids[key] = tmp + 1
        return f"{key}_{tmp}"


generator = UniqueNameGenerator()


def generate(key: str) -> str:
    """Return a unique name of the form ``{key}_{N}``."""
    return generator(key)


def switch(new_generator: UniqueNameGenerator | None = None):
    """Swap the process-wide generator; returns the old one."""
    global generator
    old = generator
    generator = new_generator if new_generator is not None \
        else UniqueNameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    """Scope a fresh generator (names restart inside the with-block)."""
    if isinstance(new_generator, str) or new_generator is None:
        new_generator = UniqueNameGenerator()
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
