"""paddle.fluid — 1.x/2.x-transition compat namespace.

Parity: python/paddle/fluid/ (the reference at the 2.5 vintage still ships
this namespace; its migration guide maps each legacy `fluid.layers.*` name
onto the modern `paddle.*` op). Only the subset whose SEMANTICS map 1:1 is
aliased here — names whose 1.x behavior silently differs from the modern op
(e.g. `layers.expand` = tile-semantics, `layers.cross_entropy` over
probabilities) raise with the migration pointer instead of mis-computing.
"""
from __future__ import annotations

from ..core.place import CPUPlace, CUDAPlace  # noqa: F401
from ..framework.io import load, save  # noqa: F401
from ..static import (Executor, Program, default_main_program,  # noqa: F401
                      default_startup_program, program_guard)
from . import layers  # noqa: F401
from .layers import data  # noqa: F401

__all__ = ["layers", "CPUPlace", "CUDAPlace", "Executor", "Program",
           "default_main_program", "default_startup_program",
           "program_guard", "data", "load", "save"]
