"""fluid.layers compat subset. Parity: python/paddle/fluid/layers/ (2.5-era
legacy API surface the reference's own test corpus still exercises).

Each alias is the migration-guide mapping; semantics-trap names raise."""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor import (creation as _creation, manipulation as _manip,
                      math as _math, search as _search)
from ..nn import functional as _F
from ..static import data  # noqa: F401  (fluid.layers.data lived here)
from ..tensor.tensor import Tensor, apply_op

__all__ = [
    "data", "fill_constant", "assign", "cast", "concat", "split", "reshape",
    "transpose", "squeeze", "unsqueeze", "shape", "zeros", "ones",
    "zeros_like", "ones_like", "gather", "gather_nd", "scatter",
    "one_hot", "clip", "clip_by_norm", "mean", "mul", "matmul",
    "reduce_sum", "reduce_mean", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_all", "reduce_any", "elementwise_add", "elementwise_sub",
    "elementwise_mul", "elementwise_div", "elementwise_max",
    "elementwise_min", "elementwise_pow", "elementwise_mod",
    "elementwise_floordiv", "relu", "leaky_relu", "sigmoid", "tanh",
    "softmax", "log_softmax", "softplus", "softsign", "swish", "hard_swish",
    "hard_sigmoid", "elu", "gelu", "square", "sqrt", "abs", "exp", "log",
    "floor", "ceil", "round", "reciprocal", "reverse", "sign", "pad",
    "expand", "cross_entropy", "accuracy", "increment", "cumsum", "topk",
    "argmax", "argmin", "argsort", "where", "cond", "unstack", "stack",
]


def _reduce(modern):
    def op(input, dim=None, keep_dim=False, name=None):
        return modern(input, axis=dim, keepdim=keep_dim)
    op.__name__ = f"reduce_{modern.__name__}"
    return op


reduce_sum = _reduce(_math.sum)
reduce_mean = _reduce(_math.mean)
reduce_max = _reduce(_math.max)
reduce_min = _reduce(_math.min)
reduce_prod = _reduce(_math.prod)
reduce_all = _reduce(_math.all)
reduce_any = _reduce(_math.any)


def _elementwise(jfn, name):
    def op(x, y, axis=-1, act=None, name=None):
        def f(a, b):
            if axis != -1 and b.ndim < a.ndim:
                # 1.x broadcast contract: align y's dims starting at `axis`
                shape = [1] * a.ndim
                shape[axis:axis + b.ndim] = b.shape
                b = b.reshape(shape)
            return jfn(a, b)
        out = apply_op(f, x, y) if isinstance(y, Tensor) else \
            apply_op(lambda a: jfn(a, y), x)
        if act:
            out = getattr(_F, act)(out)
        return out
    op.__name__ = name
    return op


elementwise_add = _elementwise(jnp.add, "elementwise_add")
elementwise_sub = _elementwise(jnp.subtract, "elementwise_sub")
elementwise_mul = _elementwise(jnp.multiply, "elementwise_mul")
elementwise_div = _elementwise(jnp.divide, "elementwise_div")
elementwise_max = _elementwise(jnp.maximum, "elementwise_max")
elementwise_min = _elementwise(jnp.minimum, "elementwise_min")
elementwise_pow = _elementwise(jnp.power, "elementwise_pow")
elementwise_mod = _elementwise(jnp.mod, "elementwise_mod")
elementwise_floordiv = _elementwise(jnp.floor_divide,
                                    "elementwise_floordiv")


def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    return _creation.full(shape, value, dtype=dtype)





def one_hot(input, depth, allow_out_of_range=False, name=None):
    return _F.one_hot(input, depth)


def clip_by_norm(x, max_norm, name=None):
    def f(a):
        norm = jnp.sqrt(jnp.sum(a * a))
        return jnp.where(norm > max_norm, a * (max_norm / norm), a)
    return apply_op(f, x)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    def f(a, b):
        a2 = a.reshape((int(jnp.prod(jnp.asarray(a.shape[:x_num_col_dims]))),
                        -1)) if a.ndim > 2 else a
        b2 = b.reshape((int(jnp.prod(jnp.asarray(b.shape[:y_num_col_dims]))),
                        -1)) if b.ndim > 2 else b
        return a2 @ b2
    return apply_op(f, x, y)


def expand(x, expand_times=None, name=None):
    raise RuntimeError(
        "fluid.layers.expand has TILE semantics (repeat per-dim), not the "
        "modern broadcast expand — use paddle.tile(x, expand_times) "
        "(migration guide mapping) to avoid a silent behavior change")


def cross_entropy(input, label, soft_label=False, ignore_index=-100,
                  name=None):
    raise RuntimeError(
        "fluid.layers.cross_entropy consumes PROBABILITIES (post-softmax); "
        "the modern paddle.nn.functional.cross_entropy consumes logits. "
        "Use F.cross_entropy on logits, or paddle.log + nll composition "
        "for the legacy probability contract")


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    from ..metric import accuracy as acc
    return acc(input, label, k=k)


def where(condition, name=None):
    """1.x fluid.layers.where = indices of true (modern paddle.nonzero);
    the modern ternary where lives at paddle.where."""
    return _manip.nonzero(condition)


def cond(pred, true_fn=None, false_fn=None, name=None):
    """Conditional: python-if on a concrete predicate, lax.cond under
    trace (fluid.layers.cond's dynamic-graph contract)."""
    import jax
    p = pred._data if isinstance(pred, Tensor) else pred
    if isinstance(p, jax.core.Tracer):
        return jax.lax.cond(jnp.all(p), lambda _: true_fn(),
                            lambda _: false_fn(), operand=None)
    import numpy as np
    return true_fn() if bool(np.asarray(p).all()) else false_fn()


# direct-mapping aliases (identical semantics)
shape = _manip.shape
assign = _creation.assign
cast = _manip.cast
concat = _manip.concat
split = _manip.split
reshape = _manip.reshape
transpose = _manip.transpose
squeeze = _manip.squeeze
unsqueeze = _manip.unsqueeze
zeros = _creation.zeros
ones = _creation.ones
zeros_like = _creation.zeros_like
ones_like = _creation.ones_like
gather = _manip.gather
gather_nd = _manip.gather_nd
scatter = _manip.scatter
clip = _math.clip
mean = _math.mean
matmul = _math.matmul
increment = _math.increment
cumsum = _math.cumsum
topk = _search.topk
argmax = _search.argmax
argmin = _search.argmin
argsort = _search.argsort
unstack = _manip.unstack
stack = _manip.stack
reverse = _manip.reverse
pad = _manip.pad
sign = _math.sign
square = _math.square
sqrt = _math.sqrt
abs = _math.abs  # noqa: A001
exp = _math.exp
log = _math.log
floor = _math.floor
ceil = _math.ceil
round = _math.round  # noqa: A001
reciprocal = _math.reciprocal
relu = _F.relu
leaky_relu = _F.leaky_relu
sigmoid = _F.sigmoid
tanh = _F.tanh
softmax = _F.softmax
log_softmax = _F.log_softmax
softplus = _F.softplus
softsign = _F.softsign
swish = _F.swish
hard_swish = _F.hardswish
hard_sigmoid = _F.hardsigmoid
elu = _F.elu
gelu = _F.gelu
