"""paddle.hub. Parity: python/paddle/hub.py :: list, help, load — load
models from a repo's hubconf.py. source='local' is fully supported;
'github'/'gitee' require network and are gated with a clear error (zero-
egress environment)."""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf"
_hubconf_cache: dict[str, object] = {}


def _load_hubconf(repo_dir: str, force_reload: bool = False):
    if not force_reload and repo_dir in _hubconf_cache:
        return _hubconf_cache[repo_dir]
    path = os.path.join(repo_dir, _HUBCONF + ".py")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {_HUBCONF}.py found in {repo_dir}")
    spec = importlib.util.spec_from_file_location(
        f"{_HUBCONF}_{abs(hash(repo_dir))}", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(repo_dir)
    _hubconf_cache[repo_dir] = mod
    return mod


def _resolve(repo_dir: str, source: str) -> str:
    source = source.lower()
    if source == "local":
        return repo_dir
    if source in ("github", "gitee"):
        raise RuntimeError(
            f"paddle.hub source='{source}' needs network access, which this "
            f"environment does not have. Clone the repo locally and call "
            f"with source='local'.")
    raise ValueError(
        f"unknown source {source!r}; expected 'github', 'gitee' or 'local'")


def list(repo_dir: str, source: str = "github", force_reload: bool = False):
    """Entrypoint names exported by the repo's hubconf.py."""
    mod = _load_hubconf(_resolve(repo_dir, source), force_reload)
    return [k for k, v in vars(mod).items()
            if callable(v) and not k.startswith("_")]


def help(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False):
    """Docstring of one hubconf entrypoint."""
    mod = _load_hubconf(_resolve(repo_dir, source), force_reload)
    if not hasattr(mod, model):
        raise RuntimeError(f"hubconf has no entrypoint {model!r}")
    return getattr(mod, model).__doc__


def load(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False, **kwargs):
    """Instantiate one hubconf entrypoint with kwargs."""
    mod = _load_hubconf(_resolve(repo_dir, source), force_reload)
    if not hasattr(mod, model):
        raise RuntimeError(f"hubconf has no entrypoint {model!r}")
    return getattr(mod, model)(**kwargs)
