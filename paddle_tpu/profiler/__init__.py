"""paddle.profiler over jax.profiler.

Parity: python/paddle/profiler/profiler.py (Profiler, RecordEvent, scheduler
cycles, export_chrome_tracing) backed by paddle/fluid/platform/profiler/ host
+ CUPTI tracers. TPU-native: jax.profiler writes XPlane/Perfetto traces that
TensorBoard renders (the TPU-side analog of the Chrome trace), and
RecordEvent maps to jax.profiler.TraceAnnotation scopes compiled into the
XLA timeline.
"""
from __future__ import annotations

import contextlib
import os
import time
from enum import Enum
from typing import Callable, Iterable, Optional

import jax

from ..core.native import NativeTracer

__all__ = ["Profiler", "RecordEvent", "ProfilerTarget", "ProfilerState",
           "make_scheduler", "export_chrome_tracing", "load_profiler_result",
           "SummaryView", "ChromeTrace"]


class ChromeTrace:
    """Chrome-trace (chrome://tracing / Perfetto) event builder — the
    ONE event model shared by the profiler's host-span export and the
    serving telemetry export (inference/telemetry.py), so both render
    side by side with jax.profiler's XLA timeline in Perfetto.

    Phases used: "M" metadata (process/thread names), "X" complete
    events (ts + dur), "i" instants, "C" counters. Timestamps and
    durations are MICROSECONDS (the trace-event spec's unit)."""

    def __init__(self):
        self.events = []

    def process(self, pid, name):
        self.events.append({"ph": "M", "name": "process_name",
                            "pid": pid, "tid": 0,
                            "args": {"name": name}})

    def thread(self, pid, tid, name):
        self.events.append({"ph": "M", "name": "thread_name",
                            "pid": pid, "tid": tid,
                            "args": {"name": name}})

    def complete(self, name, pid, tid, ts_us, dur_us, args=None):
        ev = {"ph": "X", "name": name, "pid": pid, "tid": tid,
              "ts": round(float(ts_us), 3),
              "dur": round(max(float(dur_us), 0.0), 3)}
        if args:
            ev["args"] = args
        self.events.append(ev)

    def instant(self, name, pid, tid, ts_us):
        self.events.append({"ph": "i", "name": name, "pid": pid,
                            "tid": tid, "ts": round(float(ts_us), 3),
                            "s": "t"})

    def counter(self, name, pid, ts_us, values):
        self.events.append({"ph": "C", "name": name, "pid": pid,
                            "tid": 0, "ts": round(float(ts_us), 3),
                            "args": dict(values)})

    def to_dict(self):
        return {"traceEvents": self.events, "displayTimeUnit": "ms"}

    def write(self, path):
        import json
        with open(path, "w") as f:
            json.dump(self.to_dict(), f)
        return path

# Host span collector (C++, csrc/runtime.cc — parity with the reference's
# native host tracer); None-safe when the toolchain is absent.
_host_tracer = NativeTracer()


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class SummaryView(Enum):
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6


def make_scheduler(closed=0, ready=0, record=1, repeat=0, skip_first=0):
    total = closed + ready + record

    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        if repeat and s >= repeat * total:
            return ProfilerState.CLOSED
        pos = s % total
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == total - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    def handler(prof):
        pass
    handler._dir = dir_name
    return handler


class RecordEvent:
    """User scope annotation; shows up in the XLA trace timeline."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ctx = None
        self.begin_ns = None
        self.end_ns = None

    def begin(self):
        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()
        _host_tracer.begin(self.name)
        self.begin_ns = time.perf_counter_ns()

    def end(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None
        _host_tracer.end()
        self.end_ns = time.perf_counter_ns()

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *a):
        self.end()
        return False


class Profiler:
    def __init__(self, targets: Optional[Iterable] = None, scheduler=None,
                 on_trace_ready: Optional[Callable] = None,
                 timer_only: bool = False, record_shapes: bool = False,
                 profile_memory: bool = False, with_flops: bool = False):
        self.targets = list(targets or [ProfilerTarget.CPU, ProfilerTarget.TPU])
        if isinstance(scheduler, tuple):
            start, end = scheduler
            scheduler = make_scheduler(closed=start, ready=0,
                                       record=end - start, skip_first=0)
        self.scheduler = scheduler
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.step_num = 0
        self.state = ProfilerState.CLOSED
        self._dir = None
        self._active = False
        self._step_times: list[float] = []
        self._t0 = None

    def _log_dir(self):
        if self.on_trace_ready is not None and hasattr(self.on_trace_ready, "_dir"):
            return self.on_trace_ready._dir
        return os.environ.get("PADDLE_PROFILER_DIR", "/tmp/paddle_tpu_prof")

    def start(self):
        if not self.timer_only:
            try:
                jax.profiler.start_trace(self._log_dir())
                self._active = True
            except Exception:
                self._active = False
            _host_tracer.enable(True)
        self._t0 = time.perf_counter()

    def stop(self):
        if self._active:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._active = False
        if _host_tracer.available and not self.timer_only:
            # chrome trace of host spans alongside the XPlane dump
            os.makedirs(self._log_dir(), exist_ok=True)
            _host_tracer.dump(os.path.join(self._log_dir(),
                                           "host_trace.json"))
            _host_tracer.enable(False)
        if self.on_trace_ready:
            self.on_trace_ready(self)

    def step(self, num_samples: Optional[int] = None):
        now = time.perf_counter()
        if self._t0 is not None:
            self._step_times.append(now - self._t0)
        self._t0 = now
        self.step_num += 1

    def step_info(self, unit=None):
        if not self._step_times:
            return ""
        import numpy as np
        arr = np.asarray(self._step_times[-10:])
        return (f"avg step time {arr.mean()*1000:.2f} ms "
                f"(last {arr[-1]*1000:.2f} ms)")

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms", views=None):
        print(self.step_info())

    def export(self, path, format="json"):
        """Chrome-trace export of the timer-level step timeline (the
        XPlane/host dumps land in the log dir at stop(); this is the
        lightweight per-step view, same event model as the serving
        telemetry export)."""
        tr = ChromeTrace()
        tr.process(0, "paddle_tpu Profiler")
        tr.thread(0, 0, "train steps")
        t = 0.0
        for i, dt in enumerate(self._step_times):
            tr.complete(f"step {i}", 0, 0, t * 1e6, dt * 1e6)
            t += dt
        tr.write(path)
        return path

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.stop()
        return False


def load_profiler_result(filename):
    return None
