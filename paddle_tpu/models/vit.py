"""Vision Transformer (BASELINE configs[3]: ViT-L/16 ImageNet).

Parity target: ViT over this framework's layers — conv patch embed, learned
positions, class token, pre-LN encoder. Patch embedding is a single strided
conv → MXU; attention via F.scaled_dot_product_attention (Pallas on TPU).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..nn import functional as F
from ..nn.initializer import Normal, TruncatedNormal, Constant
from ..nn.layer.common import Dropout, Linear
from ..nn.layer.conv import Conv2D
from ..nn.layer.layers import Layer, LayerList
from ..nn.layer.norm import LayerNorm
from ..tensor.manipulation import concat, reshape, transpose
from ..tensor.tensor import Parameter, Tensor

__all__ = ["ViT", "vit_b_16", "vit_l_16", "vit_tiny"]


class MLP(Layer):
    def __init__(self, dim, hidden, dropout=0.0):
        super().__init__()
        self.fc1 = Linear(dim, hidden)
        self.fc2 = Linear(hidden, dim)
        self.drop = Dropout(dropout)

    def forward(self, x):
        return self.drop(self.fc2(self.drop(F.gelu(self.fc1(x)))))


class Attention(Layer):
    def __init__(self, dim, heads, dropout=0.0):
        super().__init__()
        self.heads = heads
        self.head_dim = dim // heads
        self.qkv = Linear(dim, 3 * dim)
        self.proj = Linear(dim, dim)
        self.dropout = dropout

    def forward(self, x):
        b, s = x.shape[0], x.shape[1]
        qkv = reshape(self.qkv(x), [b, s, 3, self.heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = F.scaled_dot_product_attention(
            q, k, v, dropout_p=self.dropout if self.training else 0.0)
        return self.proj(reshape(out, [b, s, self.heads * self.head_dim]))


class Block(Layer):
    def __init__(self, dim, heads, mlp_ratio=4.0, dropout=0.0):
        super().__init__()
        self.norm1 = LayerNorm(dim, 1e-6)
        self.attn = Attention(dim, heads, dropout)
        self.norm2 = LayerNorm(dim, 1e-6)
        self.mlp = MLP(dim, int(dim * mlp_ratio), dropout)

    def forward(self, x):
        x = x + self.attn(self.norm1(x))
        x = x + self.mlp(self.norm2(x))
        return x


class ViT(Layer):
    def __init__(self, image_size=224, patch_size=16, dim=768, depth=12,
                 heads=12, mlp_ratio=4.0, num_classes=1000, dropout=0.0,
                 in_channels=3, recompute=False, patch_matmul=True):
        super().__init__()
        self.recompute = recompute
        # patch_matmul: realize the stride-P patch conv as space-to-depth
        # + ONE matmul (mathematically identical — non-overlapping patches
        # make the conv a blocked matmul). The Conv2D layer still owns the
        # weights (state-dict parity with the conv formulation); only the
        # compute path changes: [B,C,H,W] -> [B,N,C·P²] @ [C·P²,D] hits
        # the MXU as a plain GEMM instead of relying on XLA's NCHW
        # strided-conv lowering (r3: ViT at 11.2% MFU, patch-conv layout a
        # named suspect). PADDLE_TPU_PATCH_CONV=1 forces the conv for A/B.
        self.patch_matmul = patch_matmul
        self.patch_size = patch_size
        self.patch_embed = Conv2D(in_channels, dim, patch_size,
                                  stride=patch_size)
        n_patches = (image_size // patch_size) ** 2
        self.cls_token = Parameter(jnp.zeros((1, 1, dim), jnp.float32))
        self.pos_embed = Parameter(
            TruncatedNormal(std=0.02)((1, n_patches + 1, dim), jnp.float32))
        self.pos_drop = Dropout(dropout)
        self.blocks = LayerList([Block(dim, heads, mlp_ratio, dropout)
                                 for _ in range(depth)])
        self.norm = LayerNorm(dim, 1e-6)
        self.head = Linear(dim, num_classes) if num_classes > 0 else None

    def forward(self, x, labels=None):
        import os
        b = x.shape[0]
        if self.patch_matmul and \
                os.environ.get("PADDLE_TPU_PATCH_CONV") != "1" and \
                x.shape[2] % self.patch_size == 0 and \
                x.shape[3] % self.patch_size == 0:
            # (non-multiple H/W fall through to the conv, which floors)
            # space-to-depth: [B,C,H,W] -> [B, N, C·P²] in the conv's
            # (c, ph, pw) flatten order, then one GEMM with the conv
            # weight viewed as [C·P², D]
            p = self.patch_size
            c, hh, ww = x.shape[1], x.shape[2], x.shape[3]
            gh, gw = hh // p, ww // p
            xp = reshape(x, [b, c, gh, p, gw, p])
            xp = transpose(xp, [0, 2, 4, 1, 3, 5])     # [B,gh,gw,C,p,p]
            xp = reshape(xp, [b, gh * gw, c * p * p])
            w = self.patch_embed.weight                # [D, C, P, P]
            d = w.shape[0]
            wm = transpose(reshape(w, [d, c * p * p]), [1, 0])
            x = xp @ wm
            if self.patch_embed.bias is not None:
                x = x + self.patch_embed.bias          # [B, N, D]
        else:
            x = self.patch_embed(x)                 # [B, D, H', W']
            d = x.shape[1]
            x = reshape(x, [b, d, -1])
            x = transpose(x, [0, 2, 1])             # [B, N, D]
        from ..tensor.manipulation import expand
        cls = expand(self.cls_token, [b, 1, d])
        x = concat([cls, x], axis=1)
        x = self.pos_drop(x + self.pos_embed)
        if self.recompute and self.training:
            from ..distributed.fleet.utils.recompute_mod import recompute
            # recompute=True: every block (max memory saving, +~33%
            # forward recompute). recompute=N (int>=2): every Nth block —
            # the blanket remat was added for a b32 OOM (r3s4); granular
            # remat trades some of that headroom back for the recompute
            # overhead, A/B'd on-chip via BENCH_VIT_REMAT.
            stride = 1 if self.recompute is True else max(
                1, int(self.recompute))
            for i, blk in enumerate(self.blocks):
                x = recompute(blk, x) if i % stride == 0 else blk(x)
        else:
            for blk in self.blocks:
                x = blk(x)
        x = self.norm(x)
        cls_out = x[:, 0]
        if self.head is not None:
            logits = self.head(cls_out)
            if labels is not None:
                return F.cross_entropy(logits, labels)
            return logits
        return cls_out


def vit_b_16(num_classes=1000, **kw):
    return ViT(dim=768, depth=12, heads=12, num_classes=num_classes, **kw)


def vit_l_16(num_classes=1000, **kw):
    return ViT(dim=1024, depth=24, heads=16, num_classes=num_classes, **kw)


def vit_tiny(num_classes=10, image_size=32, patch_size=8, **kw):
    return ViT(image_size=image_size, patch_size=patch_size, dim=64,
               depth=2, heads=2, num_classes=num_classes, **kw)
