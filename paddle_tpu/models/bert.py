"""BERT family (BASELINE configs[1]: BERT-base pretrain DP+AMP+stage2).

Parity target: PaddleNLP-style BERT on this framework's layers: learned
position + token-type embeddings, post-LN encoder, MLM + NSP pretraining
heads.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..nn import functional as F
from ..nn.initializer import Normal
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.layers import Layer
from ..nn.layer.norm import LayerNorm
from ..nn.layer.transformer import TransformerEncoder, TransformerEncoderLayer
from ..tensor.manipulation import reshape
from ..tensor.tensor import Tensor, apply_op

__all__ = ["BertConfig", "BertModel", "BertForPretraining",
           "BertForSequenceClassification", "bert_base", "bert_tiny"]


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=3072, max_position=512,
                 type_vocab_size=2, dropout=0.1, layer_norm_eps=1e-12,
                 initializer_range=0.02):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size
        self.max_position = max_position
        self.type_vocab_size = type_vocab_size
        self.dropout = dropout
        self.layer_norm_eps = layer_norm_eps
        self.initializer_range = initializer_range


def _attr(std):
    from ..nn.utils_ import ParamAttr
    return ParamAttr(initializer=Normal(0.0, std))


class BertEmbeddings(Layer):
    def __init__(self, c: BertConfig):
        super().__init__()
        self.word_embeddings = Embedding(c.vocab_size, c.hidden_size,
                                         weight_attr=_attr(c.initializer_range))
        self.position_embeddings = Embedding(c.max_position, c.hidden_size,
                                             weight_attr=_attr(c.initializer_range))
        self.token_type_embeddings = Embedding(c.type_vocab_size,
                                               c.hidden_size,
                                               weight_attr=_attr(c.initializer_range))
        self.layer_norm = LayerNorm(c.hidden_size, c.layer_norm_eps)
        self.dropout = Dropout(c.dropout)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        s = input_ids.shape[1]
        if position_ids is None:
            from ..tensor.creation import arange
            position_ids = arange(s, dtype="int32")
        emb = self.word_embeddings(input_ids) + \
            self.position_embeddings(position_ids)
        if token_type_ids is not None:
            emb = emb + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(emb))


class BertPooler(Layer):
    def __init__(self, c: BertConfig):
        super().__init__()
        self.dense = Linear(c.hidden_size, c.hidden_size,
                            weight_attr=_attr(c.initializer_range))

    def forward(self, hidden):
        first = hidden[:, 0]
        return F.tanh(self.dense(first))


class BertModel(Layer):
    def __init__(self, c: BertConfig):
        super().__init__()
        self.config = c
        self.embeddings = BertEmbeddings(c)
        enc_layer = TransformerEncoderLayer(
            c.hidden_size, c.num_heads, c.intermediate_size, c.dropout,
            activation="gelu", layer_norm_eps=c.layer_norm_eps)
        self.encoder = TransformerEncoder(enc_layer, c.num_layers)
        self.pooler = BertPooler(c)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                position_ids=None):
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        mask = None
        if attention_mask is not None:
            m = attention_mask._data if isinstance(attention_mask, Tensor) \
                else attention_mask
            mask = Tensor((m[:, None, None, :] > 0))
        seq = self.encoder(x, mask)
        pooled = self.pooler(seq)
        return seq, pooled


class BertForPretraining(Layer):
    """MLM (tied decoder) + NSP heads; returns combined loss when labels set."""

    def __init__(self, c: BertConfig):
        super().__init__()
        self.config = c
        self.bert = BertModel(c)
        self.transform = Linear(c.hidden_size, c.hidden_size,
                                weight_attr=_attr(c.initializer_range))
        self.transform_ln = LayerNorm(c.hidden_size, c.layer_norm_eps)
        from ..tensor.tensor import Parameter
        self.mlm_bias = Parameter(jnp.zeros((c.vocab_size,), jnp.float32))
        self.nsp = Linear(c.hidden_size, 2,
                          weight_attr=_attr(c.initializer_range))

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_lm_labels=None, next_sentence_labels=None):
        import os
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        nsp_logits = self.nsp(pooled)
        if masked_lm_labels is not None:
            # masked-positions gather (reference: BertPretrainingHeads
            # consumes masked_positions, max_predictions_per_seq): only
            # ~15% of tokens carry an MLM label, so running transform +
            # the [*, vocab] decoder matmul over the FULL sequence wastes
            # ~6x the head FLOPs. Gather the labeled positions (static
            # K = 22% of S, comfortably above the 15% mean; the CE's
            # ignore_index absorbs the padding slots) and decode only
            # those. Loss is exact whenever masked count <= K — the same
            # truncation contract as the reference's max_predictions.
            s_len = seq.shape[1]
            kmax = max(1, -(-22 * s_len // 100))
            overflow = None
            if os.environ.get("PADDLE_TPU_MLM_GATHER", "1") != "0" \
                    and kmax < s_len:
                lab_arr = (masked_lm_labels._data
                           if isinstance(masked_lm_labels, Tensor)
                           else jnp.asarray(masked_lm_labels))
                import jax as _jax
                if isinstance(lab_arr, _jax.core.Tracer):
                    # traced path (to_static/Engine): the concrete
                    # density check below cannot run on a Tracer, and a
                    # row with more labels than the gather budget would
                    # silently lose loss terms. Enforce the budget
                    # INSIDE the trace instead: overflow NaN-poisons the
                    # loss (below), so truncation is never silent — the
                    # reference's max_predictions_per_seq contract makes
                    # overflow inexpressible by construction; dense-label
                    # training here requires PADDLE_TPU_MLM_GATHER=0.
                    overflow = jnp.max(
                        jnp.sum(lab_arr != -100, axis=1)) > kmax
                if not isinstance(lab_arr, _jax.core.Tracer):
                    # concrete labels (eager path): detect rows denser
                    # than the gather budget — truncating them would
                    # silently drop loss terms, so fall back to the full
                    # head with a one-time warning (traced/bench paths
                    # use the standard 15% masking, well under 22%)
                    import numpy as _np
                    dens = int(_np.max(_np.sum(
                        _np.asarray(lab_arr) != -100, axis=1)))
                    if dens > kmax:
                        if not getattr(BertForPretraining,
                                       "_warned_dense_mlm", False):
                            BertForPretraining._warned_dense_mlm = True
                            import warnings
                            warnings.warn(
                                f"BertForPretraining: {dens} MLM labels "
                                f"in a row exceed the {kmax} gather "
                                "budget (22% of seq); scoring the full "
                                "sequence instead. Set "
                                "PADDLE_TPU_MLM_GATHER=0 to silence.",
                                UserWarning, stacklevel=2)
                        kmax = s_len
                # stable ascending sort of (label == -100) puts labeled
                # slots first, in order; indices carry no gradient
                order = jnp.argsort(lab_arr == -100, axis=1,
                                    stable=True)[:, :kmax]
                h_sel = apply_op(
                    lambda sq: jnp.take_along_axis(
                        sq, order[..., None], axis=1), seq)
                labels_sel = Tensor(jnp.take_along_axis(lab_arr, order,
                                                        axis=1))
            else:
                h_sel, labels_sel = seq, masked_lm_labels
            h = self.transform_ln(F.gelu(self.transform(h_sel)))
            logits = F.linear(
                h, _t(self.bert.embeddings.word_embeddings.weight),
                self.mlm_bias)
            mlm_loss = F.cross_entropy(
                reshape(logits, [-1, self.config.vocab_size]),
                reshape(labels_sel, [-1]), ignore_index=-100)
            if overflow is not None:
                # budget violation in a traced run: poison instead of
                # silently under-counting (labels carry no gradient, so
                # the multiplier is 1.0 on every legal batch)
                mlm_loss = mlm_loss * Tensor(jnp.where(
                    overflow, jnp.float32(jnp.nan), jnp.float32(1.0)))
            loss = mlm_loss
            if next_sentence_labels is not None:
                loss = loss + F.cross_entropy(nsp_logits,
                                              next_sentence_labels)
            return loss
        h = self.transform_ln(F.gelu(self.transform(seq)))
        logits = F.linear(h, _t(self.bert.embeddings.word_embeddings.weight),
                          self.mlm_bias)
        return logits, nsp_logits


def _t(w):
    return apply_op(lambda a: a.T, w)


class BertForSequenceClassification(Layer):
    def __init__(self, c: BertConfig, num_classes=2):
        super().__init__()
        self.bert = BertModel(c)
        self.dropout = Dropout(c.dropout)
        self.classifier = Linear(c.hidden_size, num_classes,
                                 weight_attr=_attr(c.initializer_range))

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            return F.cross_entropy(logits, labels)
        return logits


def bert_base(**kw):
    return BertConfig(**kw)


def bert_tiny(**kw):
    return BertConfig(vocab_size=1024, hidden_size=64, num_layers=2,
                      num_heads=2, intermediate_size=128, max_position=128,
                      **kw)
