"""LLaMA-2 family (BASELINE configs[2]: 7B/65B hybrid mp·pp·stage3).

Parity target: the PaddleNLP LLaMA implemented on this framework's layers —
RMSNorm, rotary embeddings, GQA attention, SwiGLU MLP, tied-or-untied head.

TPU-first design:
  * attention/projections are mp-annotated (ColumnParallel/RowParallel) so a
    jitted step over the fleet mesh shards them Megatron-style via GSPMD;
  * activations can carry a sequence-parallel ('sep') constraint for
    long-context runs (Ulysses/ring variants live in ops/pallas + parallel/);
  * rotary embedding is computed in fp32 and fused by XLA; flash attention
    via F.scaled_dot_product_attention → Pallas kernel on TPU.
"""
from __future__ import annotations

import math

import jax.numpy as jnp

from ..distributed.fleet.layers.mpu.mp_layers import (ColumnParallelLinear,
                                                      RowParallelLinear,
                                                      VocabParallelEmbedding,
                                                      constraint)
from ..nn import functional as F
from ..nn.initializer import Normal
from ..nn.layer.common import Embedding, Linear
from ..nn.layer.layers import Layer, LayerList
from ..nn.layer.norm import RMSNorm
from ..tensor.manipulation import reshape
from ..tensor.tensor import Tensor, apply_op
from ..incubate.nn.functional import fused_rotary_position_embedding

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM", "llama2_7b",
           "llama2_65b", "llama_tiny"]


class LlamaConfig:
    def __init__(self, vocab_size=32000, hidden_size=4096, num_layers=32,
                 num_heads=32, num_kv_heads=None, intermediate_size=11008,
                 max_position=4096, rms_eps=1e-5, rope_base=10000.0,
                 initializer_range=0.02, tensor_parallel=True,
                 sequence_parallel=False, recompute=False,
                 tie_word_embeddings=False, context_parallel=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.num_kv_heads = num_kv_heads or num_heads
        self.intermediate_size = intermediate_size
        self.max_position = max_position
        self.rms_eps = rms_eps
        self.rope_base = rope_base
        self.initializer_range = initializer_range
        self.tensor_parallel = tensor_parallel
        self.sequence_parallel = sequence_parallel
        self.recompute = recompute
        self.tie_word_embeddings = tie_word_embeddings
        # long-context: shard the sequence over the mesh's 'sep' axis and
        # run exact ring attention (parallel/context_parallel.py) instead of
        # gathering the full sequence per chip
        self.context_parallel = context_parallel


def _attr(std):
    from ..nn.utils_ import ParamAttr
    return ParamAttr(initializer=Normal(0.0, std))


class LlamaAttention(Layer):
    def __init__(self, c: LlamaConfig):
        super().__init__()
        self.num_heads = c.num_heads
        self.num_kv_heads = c.num_kv_heads
        self.head_dim = c.hidden_size // c.num_heads
        self.rope_base = c.rope_base
        self.context_parallel = c.context_parallel
        self._ring_cache = None
        h = c.hidden_size
        kv_out = self.num_kv_heads * self.head_dim
        std = c.initializer_range
        if c.tensor_parallel:
            self.q_proj = ColumnParallelLinear(h, h, weight_attr=_attr(std),
                                               has_bias=False,
                                               gather_output=False)
            self.k_proj = ColumnParallelLinear(h, kv_out,
                                               weight_attr=_attr(std),
                                               has_bias=False,
                                               gather_output=False)
            self.v_proj = ColumnParallelLinear(h, kv_out,
                                               weight_attr=_attr(std),
                                               has_bias=False,
                                               gather_output=False)
            self.o_proj = RowParallelLinear(h, h, weight_attr=_attr(std),
                                            has_bias=False,
                                            input_is_parallel=True)
        else:
            self.q_proj = Linear(h, h, weight_attr=_attr(std),
                                 bias_attr=False)
            self.k_proj = Linear(h, kv_out, weight_attr=_attr(std),
                                 bias_attr=False)
            self.v_proj = Linear(h, kv_out, weight_attr=_attr(std),
                                 bias_attr=False)
            self.o_proj = Linear(h, h, weight_attr=_attr(std),
                                 bias_attr=False)

    def _ring_fn(self):
        """Sequence-parallel attention over the active mesh's 'sep' axis
        (cached per mesh); None when no sep-parallel mesh is active.
        context_parallel=True/'ring' runs exact ring attention (K/V
        chunks rotate on ICI); context_parallel='ulysses' runs the
        reference sep scheme (head-scatter all_to_all, full-sequence
        flash per device) — requires kv_heads % sep == 0, so GQA configs
        with few kv heads use ring."""
        from ..parallel import current_mesh
        mesh = current_mesh()
        if mesh is None or "sep" not in mesh.shape or mesh.shape["sep"] < 2:
            return None
        scheme = ("ulysses" if self.context_parallel == "ulysses"
                  else "ring")
        if getattr(self, "_ring_cache", None) is None or \
                self._ring_cache[0] is not mesh or \
                self._ring_cache[2] != scheme:
            from ..parallel.context_parallel import (
                make_ring_attention_fn, make_ulysses_attention_fn)
            mk = (make_ulysses_attention_fn if scheme == "ulysses"
                  else make_ring_attention_fn)
            self._ring_cache = (mesh, mk(mesh, axis_name="sep",
                                         causal=True), scheme)
        return self._ring_cache[1]

    def forward(self, x, kv_cache=None, time_step=None):
        b, s = x.shape[0], x.shape[1]
        hq = self.num_heads * self.head_dim
        hkv = self.num_kv_heads * self.head_dim
        if type(self.q_proj) is Linear:
            # non-TP fast path: ONE [h, hq+2·hkv] GEMM instead of three
            # narrow ones (shared AMP-aware helper; params stay separate
            # for state-dict parity, grads split through the concat)
            qkv = F.fused_concat_linear(
                x, [self.q_proj.weight, self.k_proj.weight,
                    self.v_proj.weight])
            q = reshape(qkv[:, :, :hq],
                        [b, s, self.num_heads, self.head_dim])
            k = reshape(qkv[:, :, hq:hq + hkv],
                        [b, s, self.num_kv_heads, self.head_dim])
            v = reshape(qkv[:, :, hq + hkv:],
                        [b, s, self.num_kv_heads, self.head_dim])
        else:
            q = reshape(self.q_proj(x),
                        [b, s, self.num_heads, self.head_dim])
            k = reshape(self.k_proj(x),
                        [b, s, self.num_kv_heads, self.head_dim])
            v = reshape(self.v_proj(x),
                        [b, s, self.num_kv_heads, self.head_dim])
        q, k, _ = fused_rotary_position_embedding(
            q, k, None, rotary_emb_base=self.rope_base)
        if self.num_kv_heads != self.num_heads:
            rep = self.num_heads // self.num_kv_heads
            k = apply_op(lambda a: jnp.repeat(a, rep, axis=2), k)
            v = apply_op(lambda a: jnp.repeat(a, rep, axis=2), v)
        if kv_cache is not None:
            k_cat, v_cat, kv_cache = _append_cache(kv_cache, k, v, time_step)
            out = F.scaled_dot_product_attention(q, k_cat, v_cat)
        elif self.context_parallel and self._ring_fn() is not None:
            fn = self._ring_fn()
            out = apply_op(fn, q, k, v)
        else:
            out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        out = reshape(out, [b, s, self.num_heads * self.head_dim])
        return self.o_proj(out), kv_cache


def _append_cache(cache, k, v, time_step):
    kc, vc = cache
    from ..tensor.manipulation import concat
    k_cat = concat([kc, k], axis=1)
    v_cat = concat([vc, v], axis=1)
    return k_cat, v_cat, (k_cat, v_cat)


class LlamaMLP(Layer):
    def __init__(self, c: LlamaConfig):
        super().__init__()
        h, inter = c.hidden_size, c.intermediate_size
        std = c.initializer_range
        if c.tensor_parallel:
            self.gate_proj = ColumnParallelLinear(h, inter,
                                                  weight_attr=_attr(std),
                                                  has_bias=False,
                                                  gather_output=False)
            self.up_proj = ColumnParallelLinear(h, inter,
                                                weight_attr=_attr(std),
                                                has_bias=False,
                                                gather_output=False)
            self.down_proj = RowParallelLinear(inter, h,
                                               weight_attr=_attr(std),
                                               has_bias=False,
                                               input_is_parallel=True)
        else:
            self.gate_proj = Linear(h, inter, weight_attr=_attr(std),
                                    bias_attr=False)
            self.up_proj = Linear(h, inter, weight_attr=_attr(std),
                                  bias_attr=False)
            self.down_proj = Linear(inter, h, weight_attr=_attr(std),
                                    bias_attr=False)

    def forward(self, x):
        if type(self.gate_proj) is Linear:
            # non-TP fast path: gate+up as ONE [h, 2·inter] GEMM (the
            # SwiGLU pair reads the same activations; one wide matmul
            # feeds the MXU better than two narrow ones)
            inter = self.gate_proj.weight.shape[1]
            gu = F.fused_concat_linear(
                x, [self.gate_proj.weight, self.up_proj.weight])
            return self.down_proj(F.silu(gu[:, :, :inter])
                                  * gu[:, :, inter:])
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaBlock(Layer):
    def __init__(self, c: LlamaConfig):
        super().__init__()
        self.input_layernorm = RMSNorm(c.hidden_size, c.rms_eps)
        self.self_attn = LlamaAttention(c)
        self.post_attention_layernorm = RMSNorm(c.hidden_size, c.rms_eps)
        self.mlp = LlamaMLP(c)
        self._recompute = c.recompute

    def _body(self, x):
        attn_out, _ = self.self_attn(self.input_layernorm(x))
        x = x + attn_out
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x

    def forward(self, x):
        if self._recompute and self.training:
            from ..distributed.fleet.utils.recompute_mod import recompute
            return recompute(self._body, x)
        return self._body(x)


class LlamaModel(Layer):
    def __init__(self, c: LlamaConfig):
        super().__init__()
        self.config = c
        if c.tensor_parallel:
            self.embed_tokens = VocabParallelEmbedding(
                c.vocab_size, c.hidden_size,
                weight_attr=_attr(c.initializer_range))
        else:
            self.embed_tokens = Embedding(
                c.vocab_size, c.hidden_size,
                weight_attr=_attr(c.initializer_range))
        self.layers = LayerList([LlamaBlock(c) for _ in range(c.num_layers)])
        self.norm = RMSNorm(c.hidden_size, c.rms_eps)

    def forward(self, input_ids):
        x = self.embed_tokens(input_ids)
        if self.config.sequence_parallel:
            x = constraint(x, None, "sep", None)
        for blk in self.layers:
            x = blk(x)
        return self.norm(x)


class LlamaForCausalLM(Layer):
    def __init__(self, c: LlamaConfig):
        super().__init__()
        self.config = c
        self.llama = LlamaModel(c)
        if not c.tie_word_embeddings:
            # gather_output=False: logits stay mp-sharded on the vocab dim
            # straight into the vocab-parallel CE (a gather here would
            # materialize the full [B*S, V] on every device — the memory
            # blow-up ParallelCrossEntropy exists to avoid)
            self.lm_head = (ColumnParallelLinear(
                c.hidden_size, c.vocab_size, weight_attr=_attr(
                    c.initializer_range), has_bias=False, gather_output=False)
                if c.tensor_parallel else
                Linear(c.hidden_size, c.vocab_size,
                       weight_attr=_attr(c.initializer_range),
                       bias_attr=False))
        if c.tensor_parallel:
            from ..distributed.fleet.layers.mpu.mp_layers import (
                ParallelCrossEntropy)
            self.parallel_loss = ParallelCrossEntropy()

    def forward(self, input_ids, labels=None, loss_mask=None):
        h = self.llama(input_ids)
        if self.config.tie_word_embeddings:
            logits = F.linear(h, _t(self.llama.embed_tokens.weight))
        else:
            logits = self.lm_head(h)
        if labels is not None:
            if self.config.tensor_parallel:
                # vocab-parallel two-pass CE: mp-sharded logits never
                # materialize the full vocab per device (mp_layers ::
                # ParallelCrossEntropy); dense CE off-mesh
                loss = self.parallel_loss(
                    reshape(logits, [-1, self.config.vocab_size]),
                    reshape(labels, [-1]))
            else:
                loss = F.cross_entropy(reshape(logits,
                                               [-1, self.config.vocab_size]),
                                       reshape(labels, [-1]),
                                       reduction="none")
            if loss_mask is not None:
                m = reshape(loss_mask, [-1])
                loss = (loss * m).sum() / m.sum().clip(min=1.0)
            else:
                loss = loss.mean()
            return loss
        return logits


def _t(w):
    return apply_op(lambda a: a.T, w)


def llama2_7b(**kw):
    return LlamaForCausalLM(LlamaConfig(hidden_size=4096, num_layers=32,
                                        num_heads=32,
                                        intermediate_size=11008, **kw))


def llama2_65b(**kw):
    return LlamaForCausalLM(LlamaConfig(hidden_size=8192, num_layers=80,
                                        num_heads=64,
                                        intermediate_size=22016, **kw))


def llama_tiny(vocab_size=256, **kw):
    return LlamaForCausalLM(LlamaConfig(
        vocab_size=vocab_size, hidden_size=64, num_layers=2, num_heads=4,
        intermediate_size=128, max_position=128, **kw))
