"""GPT-2 family (BASELINE configs[0]: GPT-2 124M dygraph LM).

Parity target: the PaddleNLP-style GPT implemented on this framework's
nn.Layer surface (the reference core repo hosts the layers; the model shape
follows GPT-2: learned positions, pre-LN blocks, tied LM head).

TPU-first notes: attention routes through F.scaled_dot_product_attention
(Pallas flash kernel on TPU); all projections are [in,out] single matmuls;
sequence length and batch are static under jit.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..nn import functional as F
from ..nn.initializer import Normal
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.layers import Layer, LayerList
from ..nn.layer.norm import LayerNorm
from ..tensor.tensor import Parameter, Tensor
from ..tensor.manipulation import reshape

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM", "gpt2_124m",
           "gpt2_tiny"]


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=None, max_position=1024,
                 dropout=0.1, layer_norm_eps=1e-5, initializer_range=0.02):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.max_position = max_position
        self.dropout = dropout
        self.layer_norm_eps = layer_norm_eps
        self.initializer_range = initializer_range


class GPTAttention(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        c = config
        self.num_heads = c.num_heads
        self.head_dim = c.hidden_size // c.num_heads
        init = Normal(0.0, c.initializer_range)
        self.qkv_proj = Linear(c.hidden_size, 3 * c.hidden_size,
                               weight_attr=_attr(init))
        self.out_proj = Linear(c.hidden_size, c.hidden_size,
                               weight_attr=_attr(Normal(
                                   0.0, c.initializer_range /
                                   math.sqrt(2 * c.num_layers))))
        self.dropout = c.dropout

    def forward(self, x, kv_cache=None):
        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x)
        qkv = reshape(qkv, [b, s, 3, self.num_heads, self.head_dim])
        q = qkv[:, :, 0]
        k = qkv[:, :, 1]
        v = qkv[:, :, 2]
        out = F.scaled_dot_product_attention(
            q, k, v, is_causal=True,
            dropout_p=self.dropout if self.training else 0.0)
        out = reshape(out, [b, s, self.num_heads * self.head_dim])
        return self.out_proj(out)


class GPTMLP(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        c = config
        init = Normal(0.0, c.initializer_range)
        self.fc1 = Linear(c.hidden_size, c.intermediate_size,
                          weight_attr=_attr(init))
        self.fc2 = Linear(c.intermediate_size, c.hidden_size,
                          weight_attr=_attr(Normal(
                              0.0, c.initializer_range /
                              math.sqrt(2 * c.num_layers))))

    def forward(self, x):
        import os
        if os.environ.get("PADDLE_TPU_FUSED_FFN") == "1" \
                and type(self.fc1) is Linear and type(self.fc2) is Linear:
            # Pallas fused bias+gelu+matmul (ops/pallas/fused_ffn.py):
            # the [M, F] gelu intermediate never touches HBM. Opt-in
            # pending the on-TPU A/B vs the XLA composite (LN lesson:
            # pallas_call is a fusion barrier — measure first). Guarded
            # like the llama fast paths: plain Linear layers only, and
            # no model-parallel mesh — a pallas_call is an SPMD barrier
            # that would force replication of sharded operands. The mesh
            # query lives in ..parallel so the pallas import chain only
            # loads once the flag AND the guard pass.
            from ..parallel import no_mp_mesh
            if no_mp_mesh():
                from ..ops.pallas.fused_ffn import fused_ffn
                from ..tensor.tensor import apply_op
                return apply_op(fused_ffn, x, self.fc1.weight,
                                self.fc1.bias, self.fc2.weight,
                                self.fc2.bias)
        return self.fc2(F.gelu(self.fc1(x), approximate=True))


class GPTBlock(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln1 = LayerNorm(config.hidden_size, config.layer_norm_eps)
        self.attn = GPTAttention(config)
        self.ln2 = LayerNorm(config.hidden_size, config.layer_norm_eps)
        self.mlp = GPTMLP(config)
        self.drop = Dropout(config.dropout)

    def forward(self, x):
        x = x + self.drop(self.attn(self.ln1(x)))
        x = x + self.drop(self.mlp(self.ln2(x)))
        return x


def _attr(init):
    from ..nn.utils_ import ParamAttr
    return ParamAttr(initializer=init)


class GPTModel(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        c = config
        self.config = c
        init = Normal(0.0, c.initializer_range)
        self.wte = Embedding(c.vocab_size, c.hidden_size,
                             weight_attr=_attr(init))
        self.wpe = Embedding(c.max_position, c.hidden_size,
                             weight_attr=_attr(init))
        self.drop = Dropout(c.dropout)
        self.h = LayerList([GPTBlock(c) for _ in range(c.num_layers)])
        self.ln_f = LayerNorm(c.hidden_size, c.layer_norm_eps)

    def forward(self, input_ids, position_ids=None):
        b, s = input_ids.shape[0], input_ids.shape[1]
        if position_ids is None:
            from ..tensor.creation import arange
            position_ids = arange(s, dtype="int32")
        x = self.wte(input_ids) + self.wpe(position_ids)
        x = self.drop(x)
        for blk in self.h:
            x = blk(x)
        return self.ln_f(x)


class GPTForCausalLM(Layer):
    """LM head tied to wte (standard GPT-2 weight tying)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(config)
        self.config = config

    def forward(self, input_ids, labels=None):
        h = self.gpt(input_ids)
        logits = F.linear(h, _transpose_param(self.gpt.wte.weight))
        if labels is not None:
            loss = F.cross_entropy(
                reshape(logits, [-1, self.config.vocab_size]),
                reshape(labels, [-1]))
            return loss
        return logits


def _transpose_param(w):
    from ..tensor.tensor import apply_op
    return apply_op(lambda a: a.T, w)


def gpt2_124m(vocab_size=50304, **kw):
    return GPTForCausalLM(GPTConfig(vocab_size=vocab_size, hidden_size=768,
                                    num_layers=12, num_heads=12, **kw))


def gpt2_tiny(vocab_size=1024, **kw):
    return GPTForCausalLM(GPTConfig(vocab_size=vocab_size, hidden_size=64,
                                    num_layers=2, num_heads=2,
                                    max_position=128, **kw))
