"""nn.utils — weight/spectral norm reparametrizations and param helpers.

Parity: python/paddle/nn/utils/{weight_norm_hook.py :: weight_norm /
remove_weight_norm, spectral_norm_hook.py :: spectral_norm,
clip_grad_norm_.py, clip_grad_value_.py, transform_parameters.py ::
parameters_to_vector / vector_to_parameters}.

TPU-style: reparametrizations are forward-pre-hooks recomputing the
effective weight from the decomposed parameters each call — under
jit.to_static the recompute traces into the step and XLA fuses it; the
decomposed params (g, v / weight_orig) are what the optimizer sees.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...tensor.tensor import Parameter, Tensor, apply_op

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "clip_grad_norm_", "clip_grad_value_", "parameters_to_vector",
           "vector_to_parameters"]


def _norm_except(v, dim):
    """L2 norm over all axes except `dim` (dim=None: over everything)."""
    if dim is None:
        return jnp.sqrt(jnp.sum(v * v))
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(v * v, axis=axes, keepdims=True))


def weight_norm(layer, name: str = "weight", dim: int = 0):
    """Decompose layer.<name> into magnitude g and direction v with
    W = g * v / ||v|| (norm over every axis except `dim`). Returns the
    layer; optimizer trains g and v."""
    w = getattr(layer, name)
    wd = w._data.astype(jnp.float32)
    g0 = _norm_except(wd, dim)
    g = Parameter(g0.astype(w._data.dtype))
    g.name = (getattr(w, "name", None) or name) + "_g"
    v = Parameter(w._data)
    v.name = (getattr(w, "name", None) or name) + "_v"
    # replace the trained param: remove W, add (g, v)
    del layer._parameters[name]
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)

    def _recompute(lay, inputs):
        def f(ga, va):
            va32 = va.astype(jnp.float32)
            nrm = jnp.maximum(_norm_except(va32, dim), 1e-12)
            return (ga.astype(jnp.float32) * va32 / nrm).astype(va.dtype)
        setattr(lay, name, apply_op(f, getattr(lay, name + "_g"),
                                    getattr(lay, name + "_v")))
        return None
    handle = layer.register_forward_pre_hook(_recompute)
    layer._weight_norm_state = (name, dim, handle)
    _recompute(layer, None)            # effective weight valid pre-call too
    return layer


def remove_weight_norm(layer, name: str = "weight"):
    """Fold g*v/||v|| back into a single trained weight."""
    state = getattr(layer, "_weight_norm_state", None)
    if state is None or state[0] != name:
        raise ValueError(f"weight_norm was not applied to '{name}'")
    _, dim, handle = state
    handle.remove()
    g = getattr(layer, name + "_g")
    v = getattr(layer, name + "_v")
    v32 = v._data.astype(jnp.float32)
    w = (g._data.astype(jnp.float32) * v32 /
         jnp.maximum(_norm_except(v32, dim), 1e-12)).astype(v._data.dtype)
    del layer._parameters[name + "_g"]
    del layer._parameters[name + "_v"]
    # the pre-hook stored the effective weight as a PLAIN attr in
    # __dict__; it would shadow the re-registered Parameter on lookup
    layer.__dict__.pop(name, None)
    p = Parameter(w)
    p.name = name
    layer.add_parameter(name, p)
    del layer._weight_norm_state
    return layer


def spectral_norm(layer, name: str = "weight", n_power_iterations: int = 1,
                  eps: float = 1e-12, dim: int = 0):
    """Reparametrize layer.<name> as W / sigma_max(W), sigma estimated by
    power iteration with persistent u (reference spectral_norm_hook).
    The power-iteration state updates eagerly per call (stop-gradient),
    matching the reference's buffer semantics."""
    w = getattr(layer, name)
    shape = w._data.shape
    h = shape[dim]
    u0 = jax.random.normal(jax.random.PRNGKey(0), (h,), jnp.float32)
    u_t = Tensor(u0 / jnp.maximum(jnp.linalg.norm(u0), eps))
    u_t.stop_gradient = True
    layer.register_buffer(name + "_u", u_t) if hasattr(
        layer, "register_buffer") else setattr(layer, name + "_u_buf", u_t)

    orig = Parameter(w._data)
    orig.name = (getattr(w, "name", None) or name) + "_orig"
    del layer._parameters[name]
    layer.add_parameter(name + "_orig", orig)

    def _mat(wd):
        if dim != 0:
            perm = (dim,) + tuple(i for i in range(wd.ndim) if i != dim)
            wd = jnp.transpose(wd, perm)
        return wd.reshape(wd.shape[0], -1)

    def _power_iter(wm, u):
        vv = None
        for _ in range(max(n_power_iterations, 1)):
            vv = wm.T @ u
            vv = vv / jnp.maximum(jnp.linalg.norm(vv), eps)
            u = wm @ vv
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        return u, vv

    def _recompute(lay, inputs):
        wo = getattr(lay, name + "_orig")
        # ONE power iteration per call: advance u eagerly (stop-gradient
        # buffer semantics), then reuse the converged (u, v) inside the
        # traced sigma computation. Under a jit.to_static trace the
        # weight (hence u_new) is a tracer — persisting it into the u
        # buffer would leak the tracer into post-trace calls (the same
        # failure class the jit rollback guards for optimizer slots), so
        # the power-iteration STATE freezes under tracing and only
        # eager/concrete calls advance it.
        wm_host = _mat(jax.lax.stop_gradient(wo._data).astype(jnp.float32))
        u_new, v_new = _power_iter(wm_host, u_t._data)
        if not isinstance(u_new, jax.core.Tracer):
            u_t._data = u_new

        def f(wo_):
            wm = _mat(wo_.astype(jnp.float32))
            sigma = u_new @ (wm @ v_new)
            return (wo_.astype(jnp.float32) / jnp.maximum(sigma, eps)
                    ).astype(wo_.dtype)
        setattr(lay, name, apply_op(f, wo))
        return None
    handle = layer.register_forward_pre_hook(_recompute)
    layer._spectral_norm_state = (name, handle)
    _recompute(layer, None)
    return layer


def clip_grad_norm_(parameters, max_norm, norm_type: float = 2.0,
                    error_if_nonfinite: bool = False):
    """In-place global-norm clip of .grad across parameters; returns the
    total norm (reference clip_grad_norm_)."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros((), jnp.float32))
    max_norm = float(max_norm)
    if math.isinf(norm_type):
        total = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(g._data.astype(jnp.float32))) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g._data.astype(jnp.float32)) ** norm_type)
             for g in grads])) ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError(
            f"the total norm of order {norm_type} is non-finite")
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-12), 1.0)
    for g in grads:
        g._data = (g._data.astype(jnp.float32) * scale).astype(g._data.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    cv = abs(float(clip_value))
    for p in parameters:
        if p.grad is not None:
            p.grad._data = jnp.clip(p.grad._data, -cv, cv)


def parameters_to_vector(parameters, name=None):
    """Flatten-and-concat parameters into one 1-D tensor in the
    parameters' common (promoted) dtype — no forced f32 cast."""
    params = list(parameters)
    dtype = jnp.result_type(*(p._data.dtype for p in params)) if params \
        else jnp.float32
    return Tensor(jnp.concatenate(
        [p._data.reshape(-1).astype(dtype) for p in params]))


def vector_to_parameters(vec, parameters, name=None):
    """Inverse of parameters_to_vector: writes slices back in place.
    Validates the length BEFORE mutating anything — a failed call must
    not leave the model half-overwritten."""
    params = list(parameters)
    data = vec._data if isinstance(vec, Tensor) else jnp.asarray(vec)
    need = sum(p.size for p in params)
    if need != data.size:
        raise ValueError(f"vector has {data.size} elements; parameters "
                         f"need {need}")
    off = 0
    for p in params:
        n = p.size
        p._data = data[off:off + n].reshape(p.shape).astype(p._data.dtype)
        off += n
