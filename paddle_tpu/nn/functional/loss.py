"""Loss functionals. Parity: python/paddle/nn/functional/loss.py."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor.tensor import Tensor, apply_op

__all__ = ["cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
           "binary_cross_entropy_with_logits", "nll_loss", "l1_loss",
           "mse_loss", "smooth_l1_loss", "kl_div", "margin_ranking_loss",
           "cosine_embedding_loss", "ctc_loss", "hinge_embedding_loss",
           "triplet_margin_loss", "log_loss", "square_error_cost",
           "sigmoid_focal_loss", "dice_loss", "multi_margin_loss",
           "margin_cross_entropy", "hsigmoid_loss"]


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    lab = label._data if isinstance(label, Tensor) else jnp.asarray(label)

    def core(logits, *w):
        # HBM discipline: the hard-label softmax path never materializes the
        # full log-softmax (or a one-hot) over the class axis — for an LM
        # head that array is [B*S, vocab] fp32, several GB of traffic per
        # step. loss = logsumexp(row) - logit[label]; autodiff of logsumexp
        # regenerates softmax inside the same fusion.
        lg = logits.astype(jnp.float32)
        n_class = lg.shape[axis]
        if soft_label:
            tgt = lab.astype(jnp.float32)
            if label_smoothing > 0:
                tgt = (1 - label_smoothing) * tgt + label_smoothing / n_class
            if use_softmax:
                lse = jax.scipy.special.logsumexp(lg, axis=axis)
                loss = lse * jnp.sum(tgt, axis=axis) \
                    - jnp.sum(tgt * lg, axis=axis)
            else:
                logp = jnp.log(jnp.clip(lg, 1e-15, None))
                loss = -jnp.sum(tgt * logp, axis=axis)
        else:
            ids = lab
            if ids.ndim == lg.ndim:
                ids = jnp.squeeze(ids, axis=axis)
            if not jnp.issubdtype(ids.dtype, jnp.integer):
                ids = ids.astype(jnp.int32)   # one_hot accepted float labels
            # out-of-range labels (e.g. -1 padding when ignore_index is the
            # default -100) match one_hot semantics: zero hard-label term,
            # smoothing term still applies; they stay in the mean denominator
            in_range = (ids >= 0) & (ids < n_class)
            safe = jnp.clip(ids, 0, n_class - 1)

            def _gather(arr):
                return jnp.squeeze(jnp.take_along_axis(
                    arr, jnp.expand_dims(safe, axis), axis=axis), axis=axis)

            if use_softmax:
                lse = jax.scipy.special.logsumexp(lg, axis=axis)
                loss = jnp.where(in_range, lse - _gather(lg), 0.0)
                if label_smoothing > 0:
                    loss = (1 - label_smoothing) * loss + label_smoothing * (
                        lse - jnp.mean(lg, axis=axis))
            else:
                logp = jnp.log(jnp.clip(lg, 1e-15, None))
                loss = jnp.where(in_range, -_gather(logp), 0.0)
                if label_smoothing > 0:
                    loss = (1 - label_smoothing) * loss - label_smoothing * \
                        jnp.mean(logp, axis=axis)
            valid = (ids != ignore_index)
            loss = jnp.where(valid, loss, 0.0)
            if w:
                wt = jnp.take(w[0], jnp.clip(ids, 0, n_class - 1), axis=0)
                loss = loss * wt
                if reduction == "mean":
                    denom = jnp.sum(jnp.where(valid, wt, 0.0))
                    return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
            if reduction == "mean":
                denom = jnp.sum(valid.astype(loss.dtype))
                return jnp.sum(loss) / jnp.maximum(denom, 1.0)
        return _reduce(loss, reduction)
    if weight is not None:
        return apply_op(core, input, weight)
    return apply_op(core, input)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    if return_softmax:
        from .activation import softmax as _sm
        return loss, _sm(logits, axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def core(p, t, *w):
        p = jnp.clip(p, 1e-12, 1 - 1e-12)
        loss = -(t * jnp.log(p) + (1 - t) * jnp.log(1 - p))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return apply_op(core, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    pw = pos_weight._data if isinstance(pos_weight, Tensor) else pos_weight

    def core(z, t, *w):
        mx = jnp.maximum(z, 0)
        loss = mx - z * t + jnp.log1p(jnp.exp(-jnp.abs(z)))
        if pw is not None:
            logsig = jax.nn.log_sigmoid(z)
            lognegsig = jax.nn.log_sigmoid(-z)
            loss = -(pw * t * logsig + (1 - t) * lognegsig)
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)
    args = [logit, label] + ([weight] if weight is not None else [])
    return apply_op(core, *args)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    lab = label._data if isinstance(label, Tensor) else jnp.asarray(label)

    def core(logp, *w):
        n_class = logp.shape[1]
        onehot = jax.nn.one_hot(lab, n_class, dtype=logp.dtype, axis=1)
        loss = -jnp.sum(onehot * logp, axis=1)
        valid = lab != ignore_index
        loss = jnp.where(valid, loss, 0.0)
        if w:
            wt = jnp.take(w[0], jnp.clip(lab, 0, n_class - 1))
            loss = loss * wt
            if reduction == "mean":
                return jnp.sum(loss) / jnp.sum(jnp.where(valid, wt, 0.0))
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid), 1)
        return _reduce(loss, reduction)
    if weight is not None:
        return apply_op(core, input, weight)
    return apply_op(core, input)


def l1_loss(input, label, reduction="mean", name=None):
    return apply_op(lambda a, b: _reduce(jnp.abs(a - b), reduction),
                    input, label)


def mse_loss(input, label, reduction="mean", name=None):
    return apply_op(lambda a, b: _reduce(jnp.square(a - b), reduction),
                    input, label)


def square_error_cost(input, label):
    return apply_op(lambda a, b: jnp.square(a - b), input, label)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def core(a, b):
        d = a - b
        loss = jnp.where(jnp.abs(d) < delta, 0.5 * d * d / delta,
                         jnp.abs(d) - 0.5 * delta)
        return _reduce(loss, reduction)
    return apply_op(core, input, label)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def core(lp, t):
        if log_target:
            loss = jnp.exp(t) * (t - lp)
        else:
            loss = t * (jnp.log(jnp.clip(t, 1e-12, None)) - lp)
        if reduction == "batchmean":
            return jnp.sum(loss) / lp.shape[0]
        return _reduce(loss, reduction)
    return apply_op(core, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def core(a, b, t):
        return _reduce(jnp.maximum(0.0, -t * (a - b) + margin), reduction)
    return apply_op(core, input, other, label)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    def core(a, b, t):
        cos = jnp.sum(a * b, -1) / (jnp.linalg.norm(a, axis=-1) *
                                    jnp.linalg.norm(b, axis=-1) + 1e-12)
        loss = jnp.where(t == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return apply_op(core, input1, input2, label)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def core(a, t):
        loss = jnp.where(t == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)
    return apply_op(core, input, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def core(a, pos, neg):
        def dist(u, v):
            return jnp.sum(jnp.abs(u - v) ** p + epsilon, axis=-1) ** (1.0 / p)
        dp = dist(a, pos)
        dn = dist(a, neg)
        if swap:
            dn = jnp.minimum(dn, dist(pos, neg))
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)
    return apply_op(core, input, positive, negative)


def log_loss(input, label, epsilon=1e-4, name=None):
    def core(p, t):
        return -(t * jnp.log(p + epsilon) + (1 - t) * jnp.log(1 - p + epsilon))
    return apply_op(core, input, label)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def core(z, t, *n):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * t + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * t + (1 - p) * (1 - t)
        a_t = alpha * t + (1 - alpha) * (1 - t)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if n:
            loss = loss / n[0]
        return _reduce(loss, reduction)
    if normalizer is not None:
        return apply_op(core, logit, label, normalizer)
    return apply_op(core, logit, label)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via jax's optax-style forward algorithm (reference composite)."""
    lp = log_probs._data  # [T, B, C] paddle layout
    lab = labels._data if isinstance(labels, Tensor) else jnp.asarray(labels)
    il = input_lengths._data if isinstance(input_lengths, Tensor) else jnp.asarray(input_lengths)
    ll = label_lengths._data if isinstance(label_lengths, Tensor) else jnp.asarray(label_lengths)

    def core(lp_arr):
        import optax
        # optax expects [B, T, C] logits and [B, N] labels with paddings
        logits = jnp.swapaxes(lp_arr, 0, 1)
        B, T, C = logits.shape
        logit_pad = (jnp.arange(T)[None, :] >= il[:, None]).astype(jnp.float32)
        N = lab.shape[1]
        label_pad = (jnp.arange(N)[None, :] >= ll[:, None]).astype(jnp.float32)
        per_seq = optax.ctc_loss(logits, logit_pad, lab, label_pad,
                                 blank_id=blank)
        return _reduce(per_seq, reduction)
    return apply_op(core, log_probs)


# ---- round-2 breadth: remaining reference losses --------------------------
# Parity: python/paddle/nn/functional/loss.py (2.6 surface).
import math  # noqa: E402

__all__ += ["gaussian_nll_loss", "poisson_nll_loss", "soft_margin_loss",
            "multi_label_soft_margin_loss",
            "triplet_margin_with_distance_loss", "npair_loss"]

def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    """0.5*(log(var) + (x-mu)^2/var) (+ 0.5*log(2π) when full)."""
    def fn(mu, y, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + (y - mu) ** 2 / var)
        if full:
            loss = loss + 0.5 * math.log(2 * math.pi)
        return _reduce(loss, reduction)
    return apply_op(fn, input, label, variance)


def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-8, reduction="mean", name=None):
    def fn(x, y):
        if log_input:
            loss = jnp.exp(x) - y * x
        else:
            loss = x - y * jnp.log(x + epsilon)
        if full:
            # Stirling approximation for log(y!) at y > 1
            stir = (y * jnp.log(y) - y
                    + 0.5 * jnp.log(2 * jnp.pi * y))
            loss = loss + jnp.where(y > 1, stir, 0.0)
        return _reduce(loss, reduction)
    return apply_op(fn, input, label)


def soft_margin_loss(input, label, reduction="mean", name=None):
    """log(1 + exp(-y * x)) with y in {-1, 1}."""
    return apply_op(
        lambda x, y: _reduce(jnp.logaddexp(0.0, -y * x), reduction),
        input, label)


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    def fn(x, y, *w):
        per = (y * jax.nn.log_sigmoid(x)
               + (1 - y) * jax.nn.log_sigmoid(-x))
        if w:
            per = per * w[0]
        return _reduce(-per.mean(axis=-1), reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return apply_op(fn, *args)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    dist = distance_function or (
        lambda a, b: paddle_norm(a - b))
    d_ap = dist(input, positive)
    d_an = dist(input, negative)
    if swap:
        d_pn = dist(positive, negative)
        d_an = apply_op(jnp.minimum, d_an, d_pn)
    return apply_op(
        lambda ap, an: _reduce(jnp.maximum(ap - an + margin, 0.0),
                               reduction), d_ap, d_an)


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """N-pair loss (reference npair_loss): softmax CE over anchor·posᵀ
    similarity with label-equality targets + L2 on embeddings."""
    def fn(a, p, lab):
        sim = a @ p.T                                   # [B,B]
        same = (lab[:, None] == lab[None, :]).astype(sim.dtype)
        tgt = same / same.sum(-1, keepdims=True)
        logp = jax.nn.log_softmax(sim, axis=-1)
        ce = -(tgt * logp).sum(-1).mean()
        reg = l2_reg * ((a * a).sum(-1) + (p * p).sum(-1)).mean() * 0.25
        return ce + reg
    return apply_op(fn, anchor, positive, labels)


def paddle_norm(t):
    return apply_op(lambda a: jnp.sqrt((a * a).sum(-1) + 1e-12), t)


def dice_loss(input, label, epsilon=1e-5, name=None):
    """Dice loss over class probabilities (reference dice_loss): label is
    one-hotted in-graph; per-sample dice over all non-batch dims, then
    mean. input [N,...,C] probabilities, label [N,...,1] int."""
    lab = label._data if isinstance(label, Tensor) else jnp.asarray(label)

    def fn(p):
        lz = jnp.squeeze(lab, -1) if lab.shape[-1:] == (1,) else lab
        oh = jax.nn.one_hot(lz, p.shape[-1], dtype=p.dtype)
        red = tuple(range(1, p.ndim))
        inse = jnp.sum(p * oh, axis=red)
        denom = jnp.sum(p, axis=red) + jnp.sum(oh, axis=red)
        return jnp.mean(1.0 - 2.0 * inse / (denom + epsilon))
    return apply_op(fn, input)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """Multi-class margin loss: mean_j max(0, margin - x[y] + x[j])^p over
    j != y (reference multi_margin_loss)."""
    lab = label._data if isinstance(label, Tensor) else jnp.asarray(label)

    def core(x, *w):
        n, c = x.shape
        xy = jnp.take_along_axis(x, lab[:, None], axis=1)       # [N,1]
        m = jnp.maximum(margin - xy + x, 0.0) ** p
        if w:
            m = m * w[0][lab][:, None]
        # the j == y term is margin^p exactly; drop it from the mean
        m = m * (1.0 - jax.nn.one_hot(lab, c, dtype=x.dtype))
        return _reduce(jnp.sum(m, axis=1) / c, reduction)
    if weight is not None:
        return apply_op(core, input, weight)
    return apply_op(core, input)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean", name=None):
    """Combined-margin (ArcFace-family) softmax CE on cosine logits:
    target-class logit cos(t) -> cos(m1*t + m2) - m3, all scaled by s
    (reference margin_cross_entropy). Single-shard path; for a
    vocab/class-parallel variant compose with mp_ops' parallel CE."""
    if group is not None:
        raise NotImplementedError(
            "margin_cross_entropy(group=...) model-parallel class split is "
            "not wired; shard classes with fleet mp_ops instead")
    lab = label._data if isinstance(label, Tensor) else jnp.asarray(label)

    def core(x):
        xf = x.astype(jnp.float32)
        n, c = xf.shape
        oh = jax.nn.one_hot(lab, c, dtype=jnp.float32)
        # clip strictly inside (-1, 1): arccos' derivative is infinite at
        # +/-1, and a saturated cosine logit (common in ArcFace training)
        # would otherwise produce NaN gradients for the whole row
        eps = 1e-6
        cos_t = jnp.clip(xf, -1.0 + eps, 1.0 - eps)
        theta = jnp.arccos(cos_t)
        modified = jnp.cos(margin1 * theta + margin2) - margin3
        z = scale * jnp.where(oh > 0, modified, xf)
        logp = jax.nn.log_softmax(z, axis=-1)
        loss = -jnp.sum(oh * logp, axis=-1)
        loss = _reduce(loss, reduction)
        if return_softmax:
            return loss, jnp.exp(logp)
        return loss
    if return_softmax:
        return apply_op(core, logits, n_outputs=2)
    return apply_op(core, logits)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference hsigmoid_loss). Default mode
    walks the complete binary tree over num_classes leaves (internal nodes
    1..num_classes-1, weight row = node-1) with a STATIC ceil(log2)-length
    loop so the walk traces into one fused program; custom path_table /
    path_code rows (negative entries = padding) cover Huffman trees.
    Returns [N, 1] per-sample losses like the reference."""
    lab = label._data if isinstance(label, Tensor) else jnp.asarray(label)
    pt = path_table._data if isinstance(path_table, Tensor) else path_table
    pc = path_code._data if isinstance(path_code, Tensor) else path_code

    def core(x, w, *b):
        bv = b[0] if b else None
        xf = x.astype(jnp.float32)
        if pt is not None:
            rows = jnp.asarray(pt)                      # [N, L] node ids
            codes = jnp.asarray(pc).astype(jnp.float32)
            active = (rows >= 0).astype(jnp.float32)
            safe = jnp.maximum(rows, 0)
            logits = jnp.einsum("nd,nld->nl", xf,
                                w[safe].astype(jnp.float32))
            if bv is not None:
                logits = logits + bv[safe].astype(jnp.float32)
            sign = 1.0 - 2.0 * codes
            loss = jnp.sum(active * jax.nn.softplus(-sign * logits), axis=1)
            return loss[:, None]
        steps = max(1, int(math.ceil(math.log2(max(num_classes, 2)))) + 1)
        c = lab.astype(jnp.int32) + num_classes         # leaf node ids
        loss = jnp.zeros(xf.shape[0], jnp.float32)
        for _ in range(steps):
            parent = c >> 1
            active = (c > 1) & (parent >= 1)
            row = jnp.maximum(parent - 1, 0)
            logit = jnp.sum(xf * w[row].astype(jnp.float32), axis=-1)
            if bv is not None:
                logit = logit + bv[row].astype(jnp.float32)
            sign = 1.0 - 2.0 * (c & 1).astype(jnp.float32)
            loss = loss + active.astype(jnp.float32) * \
                jax.nn.softplus(-sign * logit)
            c = parent
        return loss[:, None]
    if bias is not None:
        return apply_op(core, input, weight, bias)
    return apply_op(core, input, weight)
