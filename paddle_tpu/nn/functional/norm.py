"""Normalization functionals. Parity: python/paddle/nn/functional/norm.py.

layer_norm here is the reference's north-star Phi kernel
(paddle/phi/kernels/gpu/layer_norm_kernel.cu :: LayerNormKernel); on TPU the
fused path is the Pallas kernel in paddle_tpu.ops.pallas.layer_norm, with this
jnp composite as the autodiff-friendly fallback (XLA fuses it well already).
"""
from __future__ import annotations

import jax.numpy as jnp

from ...tensor.tensor import Tensor, apply_op

__all__ = ["layer_norm", "batch_norm", "instance_norm", "group_norm",
           "local_response_norm", "rms_norm"]


def _pallas_ln_ok(x, normalized_shape, weight, bias, need_bias=True) -> bool:
    """Fused-kernel gate: last-dim norm, affine params matching x's dtype,
    on TPU (the composite promotes mixed dtypes; the kernel keeps x.dtype,
    so mixed-dtype configs must take the composite for backend parity).

    OPT-IN (PADDLE_TPU_PALLAS_LN=1), and the gate covers BOTH F.layer_norm
    and F.rms_norm: a pallas_call is a fusion barrier, so every norm pays
    its own HBM round-trip, while XLA fuses the composite into the
    surrounding matmul/elementwise epilogues. Measured r3 s4: the LLaMA
    stage3 config (rms_norm hot path) gained 31.8k -> 38.2k tok/s with
    the composite default + fused flash bwd in the same run; GPT-2
    (layer_norm) was neutral-to-positive. The kernels stay (capability
    parity for layer_norm_kernel.cu + direct callers/tests)."""
    try:
        import jax
        import os
        if os.environ.get("PADDLE_TPU_PALLAS_LN") != "1" and \
                os.environ.get("PADDLE_TPU_FORCE_PALLAS") != "1":
            return False
        if jax.default_backend() != "tpu" and \
                os.environ.get("PADDLE_TPU_FORCE_PALLAS") != "1":
            return False
        from ...ops.pallas import layer_norm as pln
        if len(tuple(normalized_shape)) != 1 or weight is None:
            return False
        if need_bias and bias is None:
            return False
        if weight.dtype != x.dtype or (bias is not None
                                       and bias.dtype != x.dtype):
            return False
        return pln.is_supported(tuple(x.shape), x.dtype)
    except Exception:
        return False


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    n_axes = len(tuple(normalized_shape))

    if _pallas_ln_ok(x, normalized_shape, weight, bias):
        from ...ops.pallas import layer_norm as pln
        return apply_op(lambda a, w, b: pln.layer_norm(a, w, b, epsilon),
                        x, weight, bias)

    def core(a, *wb):
        axes = tuple(range(a.ndim - n_axes, a.ndim))
        mean = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = (a.astype(jnp.float32) - mean) * jnp.reciprocal(jnp.sqrt(var + epsilon))
        out = out.astype(a.dtype)
        i = 0
        if weight is not None:
            out = out * wb[i]
            i += 1
        if bias is not None:
            out = out + wb[i]
        return out
    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return apply_op(core, *args)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (LLaMA-family). Stats in fp32, output in input dtype."""
    if weight is not None and _pallas_ln_ok(x, (x.shape[-1],), weight, None,
                                            need_bias=False):
        from ...ops.pallas import layer_norm as pln
        return apply_op(lambda a, w: pln.rms_norm(a, w, epsilon), x, weight)

    def core(a, *w):
        var = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=-1, keepdims=True)
        out = a.astype(jnp.float32) * jnp.reciprocal(jnp.sqrt(var + epsilon))
        out = out.astype(a.dtype)
        if w:
            out = out * w[0]
        return out
    if weight is not None:
        return apply_op(core, x, weight)
    return apply_op(core, x)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    c_axis = 1 if data_format.upper().startswith("NC") else -1

    def stats_axes(nd):
        ax = list(range(nd))
        ax.remove(c_axis % nd)
        return tuple(ax)

    use_batch_stats = training and not use_global_stats
    if use_batch_stats:
        axes = stats_axes(x.ndim)
        batch_mean = jnp.mean(x._data.astype(jnp.float32), axis=axes)
        batch_var = jnp.var(x._data.astype(jnp.float32), axis=axes)
        # update running stats in place (buffer semantics)
        if running_mean is not None:
            running_mean._data = (momentum * running_mean._data +
                                  (1 - momentum) * batch_mean.astype(running_mean.dtype))
        if running_var is not None:
            n = x.size / batch_var.size
            unbiased = batch_var * (n / max(n - 1, 1))
            running_var._data = (momentum * running_var._data +
                                 (1 - momentum) * unbiased.astype(running_var.dtype))
        mean_used, var_used = batch_mean, batch_var

        def core(a, *wb):
            shape = [1] * a.ndim
            shape[c_axis % a.ndim] = a.shape[c_axis % a.ndim]
            ax = stats_axes(a.ndim)
            m = jnp.mean(a.astype(jnp.float32), axis=ax, keepdims=True)
            v = jnp.var(a.astype(jnp.float32), axis=ax, keepdims=True)
            out = (a.astype(jnp.float32) - m) / jnp.sqrt(v + epsilon)
            out = out.astype(a.dtype)
            i = 0
            if weight is not None:
                out = out * wb[i].reshape(shape)
                i += 1
            if bias is not None:
                out = out + wb[i].reshape(shape)
            return out
    else:
        rm = running_mean._data
        rv = running_var._data

        def core(a, *wb):
            shape = [1] * a.ndim
            shape[c_axis % a.ndim] = a.shape[c_axis % a.ndim]
            out = (a - rm.reshape(shape)) / jnp.sqrt(rv.reshape(shape) + epsilon)
            i = 0
            if weight is not None:
                out = out * wb[i].reshape(shape)
                i += 1
            if bias is not None:
                out = out + wb[i].reshape(shape)
            return out
    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return apply_op(core, *args)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    def core(a, *wb):
        axes = tuple(range(2, a.ndim))
        m = jnp.mean(a, axis=axes, keepdims=True)
        v = jnp.var(a, axis=axes, keepdims=True)
        out = (a - m) / jnp.sqrt(v + eps)
        shape = [1, a.shape[1]] + [1] * (a.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out
    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return apply_op(core, *args)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    def core(a, *wb):
        n, c = a.shape[0], a.shape[1]
        rest = a.shape[2:]
        g = a.reshape(n, num_groups, c // num_groups, *rest)
        axes = tuple(range(2, g.ndim))
        m = jnp.mean(g, axis=axes, keepdims=True)
        v = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - m) / jnp.sqrt(v + epsilon)).reshape(a.shape)
        shape = [1, c] + [1] * (a.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out
    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return apply_op(core, *args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def core(a):
        sq = jnp.square(a)
        c = a.shape[1]
        half = size // 2
        padded = jnp.pad(sq, ((0, 0), (half, size - 1 - half)) +
                         ((0, 0),) * (a.ndim - 2))
        acc = jnp.zeros_like(a)
        for i in range(size):
            acc = acc + jax_slice_channel(padded, i, c)
        return a / (k + alpha * acc) ** beta
    return apply_op(core, x)


def jax_slice_channel(a, start, length):
    import jax.lax as lax
    return lax.slice_in_dim(a, start, start + length, axis=1)
