"""Pooling functionals over lax.reduce_window. Parity: nn/functional/pooling.py."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor.tensor import Tensor, apply_op

__all__ = ["avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d",
           "max_pool2d", "max_pool3d", "adaptive_avg_pool1d",
           "adaptive_avg_pool2d", "adaptive_avg_pool3d", "adaptive_max_pool1d",
           "adaptive_max_pool2d", "adaptive_max_pool3d"]


def _tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(i) for i in v)


def _pool(x, kernel, stride, padding, n, op, ceil_mode=False,
          exclusive=True, data_format="NCHW"):
    k = _tuple(kernel, n)
    s = _tuple(stride if stride is not None else kernel, n)
    if isinstance(padding, str):
        pad_same = padding.upper() == "SAME"
        p = None
    else:
        pad_same = False
        p = _tuple(padding, n) if not isinstance(padding, (list, tuple)) or \
            len(padding) == n else tuple(padding)
        if isinstance(p[0], (list, tuple)):
            p = tuple(tuple(i) for i in p)
        else:
            p = tuple((i, i) for i in p)
    is_nc = data_format.upper().startswith("NC")

    def f(a):
        nd = a.ndim
        if is_nc:
            window = (1, 1) + k
            strides = (1, 1) + s
            pads = ((0, 0), (0, 0)) + (p if p else ((0, 0),) * n)
        else:
            window = (1,) + k + (1,)
            strides = (1,) + s + (1,)
            pads = ((0, 0),) + (p if p else ((0, 0),) * n) + ((0, 0),)
        if pad_same:
            pads = "SAME"
        if op == "max":
            init = -jnp.inf
            out = jax.lax.reduce_window(a, init, jax.lax.max, window, strides,
                                        pads)
            return out
        # avg
        out = jax.lax.reduce_window(a, 0.0, jax.lax.add,
                                    window, strides, pads)
        if exclusive and not pad_same and p is not None and any(
                pi != (0, 0) for pi in (p or ())):
            ones = jnp.ones_like(a)
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                           strides, pads)
            return out / counts
        denom = 1
        for kk in k:
            denom *= kk
        return out / denom
    return apply_op(f, x)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, "avg", ceil_mode,
                 exclusive, "NCH")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, "avg", ceil_mode,
                 exclusive, data_format)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, "avg", ceil_mode,
                 exclusive, data_format)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    if return_mask:
        assert not ceil_mode, "return_mask supports ceil_mode=False"
        xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        pooled, mask = max_pool2d_with_mask(
            Tensor(xd[:, :, None, :]), (1, _tuple(kernel_size, 1)[0]),
            (1, _tuple(stride if stride is not None else kernel_size, 1)[0]),
            (0, _tuple(padding, 1)[0]))
        return (apply_op(lambda a: a[:, :, 0, :], pooled),
                apply_op(lambda a: a[:, :, 0, :], mask))
    return _pool(x, kernel_size, stride, padding, 1, "max", ceil_mode,
                 data_format="NCH")


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        assert data_format == "NCHW" and not ceil_mode, \
            "return_mask supports NCHW, ceil_mode=False"
        assert not isinstance(padding, str) and not (
            isinstance(padding, (list, tuple)) and padding
            and isinstance(padding[0], (list, tuple))), \
            "return_mask supports int / (int, int) padding"
        return max_pool2d_with_mask(x, kernel_size, stride, padding)
    return _pool(x, kernel_size, stride, padding, 2, "max", ceil_mode,
                 data_format=data_format)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    if return_mask:
        assert data_format == "NCDHW" and not ceil_mode, \
            "return_mask supports NCDHW, ceil_mode=False"
        return max_pool3d_with_mask(x, kernel_size, stride, padding)
    return _pool(x, kernel_size, stride, padding, 3, "max", ceil_mode,
                 data_format=data_format)


def _adaptive(x, output_size, n, op):
    out_sz = _tuple(output_size, n)

    def f(a):
        spatial = a.shape[2:]
        res = a
        # decompose into per-axis adaptive windows
        for i, (dim, osz) in enumerate(zip(spatial, out_sz)):
            ax = 2 + i
            starts = (jnp.arange(osz) * dim) // osz
            ends = ((jnp.arange(osz) + 1) * dim + osz - 1) // osz
            segs = []
            for j in range(osz):
                sl = jax.lax.slice_in_dim(res, int(starts[j]), int(ends[j]),
                                          axis=ax)
                red = jnp.max(sl, axis=ax, keepdims=True) if op == "max" else \
                    jnp.mean(sl, axis=ax, keepdims=True)
                segs.append(red)
            res = jnp.concatenate(segs, axis=ax)
        return res
    return apply_op(f, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, "avg")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, "avg")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 1, "max")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 2, "max")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 3, "max")


# ---- round-2 breadth: mask-returning max pool, unpool, lp_pool ------------
# Parity: python/paddle/nn/functional/pooling.py :: max_pool2d(return_mask),
# max_unpool2d, lp_pool2d (+ MaxUnPool2D/LPPool2D layers in nn/layer).

def _patches2d(a, kh, kw, sh, sw, ph, pw, pad_value):
    """a [N,C,H,W] → patches [N,C,Ho,Wo,kh*kw] + flat input index per tap."""
    N, C, H, W = a.shape
    ap = jnp.pad(a, ((0, 0), (0, 0), (ph, ph), (pw, pw)),
                 constant_values=pad_value)
    Ho = (H + 2 * ph - kh) // sh + 1
    Wo = (W + 2 * pw - kw) // sw + 1
    iy = jnp.arange(Ho)[:, None] * sh + jnp.arange(kh)[None, :]  # [Ho,kh]
    ix = jnp.arange(Wo)[:, None] * sw + jnp.arange(kw)[None, :]  # [Wo,kw]
    pat = ap[:, :, iy[:, None, :, None], ix[None, :, None, :]]
    # → [N,C,Ho,Wo,kh,kw]
    pat = pat.reshape(N, C, Ho, Wo, kh * kw)
    # flat index into the UNPADDED input for each tap (clip to borders)
    yy = jnp.clip(iy - ph, 0, H - 1)[:, None, :, None]
    xx = jnp.clip(ix - pw, 0, W - 1)[None, :, None, :]
    flat = (yy * W + xx).reshape(Ho, Wo, kh * kw)
    return pat, flat, Ho, Wo


def max_pool2d_with_mask(x, kernel_size, stride=None, padding=0, name=None):
    """→ (pooled, mask) where mask holds flat H*W argmax positions (the
    reference's return_mask=True contract, consumed by max_unpool2d)."""
    kh, kw = _tuple(kernel_size, 2)
    sh, sw = _tuple(stride if stride is not None else kernel_size, 2)
    ph, pw = _tuple(padding, 2)

    def fn(a):
        pat, flat, Ho, Wo = _patches2d(a, kh, kw, sh, sw, ph, pw, -jnp.inf)
        best = jnp.argmax(pat, axis=-1)                   # [N,C,Ho,Wo]
        pooled = jnp.take_along_axis(pat, best[..., None], axis=-1)[..., 0]
        mask = flat[jnp.arange(Ho)[:, None], jnp.arange(Wo)[None, :],
                    best]                                  # [N,C,Ho,Wo]
        return pooled, mask.astype(jnp.int32)
    return apply_op(fn, x, n_outputs=2)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    """Scatter pooled values back to their argmax positions; everything
    else zero (reference max_unpool2d)."""
    assert data_format == "NCHW", "max_unpool2d supports NCHW"
    kh, kw = _tuple(kernel_size, 2)
    sh, sw = _tuple(stride if stride is not None else kernel_size, 2)
    ph, pw = _tuple(padding, 2)
    idx = indices._data if isinstance(indices, Tensor) else jnp.asarray(
        indices)

    def fn(a):
        N, C, Ho, Wo = a.shape
        if output_size is not None:
            H, W = output_size[-2:]
        else:
            H = (Ho - 1) * sh - 2 * ph + kh
            W = (Wo - 1) * sw - 2 * pw + kw
        flat = jnp.zeros((N, C, H * W), a.dtype)
        # .set, not .add: overlapping windows whose argmax is the same
        # input cell all carry that cell's value — writing once is the
        # reference semantics (summing would multiply it)
        out = flat.at[
            jnp.arange(N)[:, None, None],
            jnp.arange(C)[None, :, None],
            idx.reshape(N, C, -1)].set(a.reshape(N, C, -1))
        return out.reshape(N, C, H, W)
    return apply_op(fn, x)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    """(sum over window |x|^p)^(1/p) (reference lp_pool2d). ceil_mode pads
    zeros on the bottom/right (|0|^p adds nothing to the window sum)."""
    assert data_format == "NCHW", "lp_pool2d supports NCHW"
    p = float(norm_type)
    kh, kw = _tuple(kernel_size, 2)
    sh, sw = _tuple(stride if stride is not None else kernel_size, 2)
    ph, pw = _tuple(padding, 2)

    def fn(a):
        H, W = a.shape[-2:]
        extra_h = extra_w = 0
        if ceil_mode:
            out_h = -(-(H + 2 * ph - kh) // sh) + 1
            out_w = -(-(W + 2 * pw - kw) // sw) + 1
            extra_h = max((out_h - 1) * sh + kh - (H + 2 * ph), 0)
            extra_w = max((out_w - 1) * sw + kw - (W + 2 * pw), 0)
        powd = jnp.abs(a) ** p
        s = jax.lax.reduce_window(
            powd, 0.0, jax.lax.add, (1, 1, kh, kw), (1, 1, sh, sw),
            ((0, 0), (0, 0), (ph, ph + extra_h), (pw, pw + extra_w)))
        return s ** (1.0 / p)
    return apply_op(fn, x)


__all__ += ["max_pool2d_with_mask", "max_pool3d_with_mask", "max_unpool2d", "lp_pool2d",
            "max_unpool1d", "max_unpool3d"]


def max_pool3d_with_mask(x, kernel_size, stride=None, padding=0, name=None):
    """→ (pooled, mask) with flat D*H*W argmax positions, consumed by
    max_unpool3d (reference max_pool3d return_mask=True contract)."""
    kd, kh, kw = _tuple(kernel_size, 3)
    sd, sh, sw = _tuple(stride if stride is not None else kernel_size, 3)
    pd, ph, pw = _tuple(padding, 3)

    def fn(a):
        N, C, D, H, W = a.shape
        ap = jnp.pad(a, ((0, 0), (0, 0), (pd, pd), (ph, ph), (pw, pw)),
                     constant_values=-jnp.inf)
        Do = (D + 2 * pd - kd) // sd + 1
        Ho = (H + 2 * ph - kh) // sh + 1
        Wo = (W + 2 * pw - kw) // sw + 1
        iz = jnp.arange(Do)[:, None] * sd + jnp.arange(kd)[None, :]
        iy = jnp.arange(Ho)[:, None] * sh + jnp.arange(kh)[None, :]
        ix = jnp.arange(Wo)[:, None] * sw + jnp.arange(kw)[None, :]
        pat = ap[:, :,
                 iz[:, None, None, :, None, None],
                 iy[None, :, None, None, :, None],
                 ix[None, None, :, None, None, :]]
        # → [N,C,Do,Ho,Wo,kd,kh,kw]
        pat = pat.reshape(N, C, Do, Ho, Wo, kd * kh * kw)
        best = jnp.argmax(pat, axis=-1)
        pooled = jnp.take_along_axis(pat, best[..., None], axis=-1)[..., 0]
        zz = jnp.clip(iz - pd, 0, D - 1)[:, None, None, :, None, None]
        yy = jnp.clip(iy - ph, 0, H - 1)[None, :, None, None, :, None]
        xx = jnp.clip(ix - pw, 0, W - 1)[None, None, :, None, None, :]
        flat = ((zz * H + yy) * W + xx).reshape(Do, Ho, Wo, kd * kh * kw)
        mask = flat[jnp.arange(Do)[:, None, None],
                    jnp.arange(Ho)[None, :, None],
                    jnp.arange(Wo)[None, None, :], best]
        return pooled, mask.astype(jnp.int32)
    return apply_op(fn, x, n_outputs=2)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    """1-D unpool via the 2-D scatter path on a width-1 spatial axis."""
    assert data_format == "NCL", "max_unpool1d supports NCL"
    xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    idx = indices._data if isinstance(indices, Tensor) else jnp.asarray(
        indices)
    out2 = max_unpool2d(
        Tensor(xd[:, :, None, :]), Tensor(idx[:, :, None, :]),
        (1, _tuple(kernel_size, 1)[0]),
        (1, _tuple(stride if stride is not None else kernel_size, 1)[0]),
        (0, _tuple(padding, 1)[0]),
        output_size=(1, output_size[-1]) if output_size is not None else None)
    return apply_op(lambda a: a[:, :, 0, :], out2)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    """Scatter pooled values back to their argmax positions in D*H*W."""
    assert data_format == "NCDHW", "max_unpool3d supports NCDHW"
    kd, kh, kw = _tuple(kernel_size, 3)
    sd, sh, sw = _tuple(stride if stride is not None else kernel_size, 3)
    pd, ph, pw = _tuple(padding, 3)
    idx = indices._data if isinstance(indices, Tensor) else jnp.asarray(
        indices)

    def fn(a):
        N, C, Do, Ho, Wo = a.shape
        if output_size is not None:
            D, H, W = output_size[-3:]
        else:
            D = (Do - 1) * sd - 2 * pd + kd
            H = (Ho - 1) * sh - 2 * ph + kh
            W = (Wo - 1) * sw - 2 * pw + kw
        flat = jnp.zeros((N, C, D * H * W), a.dtype)
        out = flat.at[
            jnp.arange(N)[:, None, None],
            jnp.arange(C)[None, :, None],
            idx.reshape(N, C, -1)].set(a.reshape(N, C, -1))
        return out.reshape(N, C, D, H, W)
    return apply_op(fn, x)
