"""Convolutions over jax.lax.conv_general_dilated (XLA lowers these to the MXU).

Parity: python/paddle/nn/functional/conv.py (conv1d/2d/3d + transpose).
Weight layout [out_c, in_c/groups, *k] as in the reference.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor.tensor import Tensor, apply_op

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose",
           "conv2d_transpose", "conv3d_transpose"]


def _promote(a, w):
    """lax.conv requires equal dtypes; apply numpy-style promotion to match
    the jnp.dot path in Linear instead of raising."""
    if a.dtype != w.dtype:
        ct = jnp.result_type(a, w)
        a, w = a.astype(ct), w.astype(ct)
    return a, w


def _tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(i) for i in v)


def _padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    p = list(padding)
    if len(p) == n:
        return [(int(i), int(i)) for i in p]
    if len(p) == 2 * n:
        return [(int(p[2 * i]), int(p[2 * i + 1])) for i in range(n)]
    if len(p) == n and isinstance(p[0], (list, tuple)):
        return [tuple(i) for i in p]
    raise ValueError(f"bad padding {padding}")


def _conv(x, weight, bias, stride, padding, dilation, groups, n,
          data_format):
    strides = _tuple(stride, n)
    dil = _tuple(dilation, n)
    pad = _padding(padding, n)
    chars = "DHW"[3 - n:]
    if data_format.upper().startswith("NC"):
        lhs_spec = "NC" + chars
    else:
        lhs_spec = "N" + chars + "C"
    dn = (lhs_spec, "OI" + chars, lhs_spec)

    def f(a, w, *b):
        from ...amp.auto_cast import cast_if_amp
        a, w = cast_if_amp("conv", a, w)
        a, w = _promote(a, w)
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=strides, padding=pad,
            rhs_dilation=dil, dimension_numbers=dn,
            feature_group_count=groups,
            preferred_element_type=None)
        if b:
            bshape = [1] * out.ndim
            c_axis = 1 if lhs_spec.startswith("NC") else out.ndim - 1
            bshape[c_axis] = b[0].shape[0]
            out = out + b[0].reshape(bshape)
        return out
    if bias is not None:
        return apply_op(f, x, weight, bias)
    return apply_op(f, x, weight)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    fmt = "NCH" if data_format.upper() in ("NCL", "NCH") else "NHC"
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, fmt)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, n, data_format):
    strides = _tuple(stride, n)
    dil = _tuple(dilation, n)
    opad = _tuple(output_padding, n)
    chars = "DHW"[3 - n:]
    lhs_spec = ("NC" + chars) if data_format.upper().startswith("NC") else ("N" + chars + "C")
    dn = (lhs_spec, "IO" + chars, lhs_spec)

    if isinstance(padding, str):
        pads = padding.upper()
    else:
        p = _padding(padding, n)
        # transposed conv padding: XLA wants (k-1)*d - p low/high with output_padding on high
        pads = []
        for i in range(n):
            k = weight.shape[2 + i]
            eff = (k - 1) * dil[i]
            pads.append((eff - p[i][0], eff - p[i][1] + opad[i]))

    def f(a, w, *b):
        a, w = _promote(a, w)
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=(1,) * n, padding=pads,
            lhs_dilation=strides, rhs_dilation=dil, dimension_numbers=dn,
            feature_group_count=1)
        if b:
            bshape = [1] * out.ndim
            c_axis = 1 if lhs_spec.startswith("NC") else out.ndim - 1
            bshape[c_axis] = b[0].shape[0]
            out = out + b[0].reshape(bshape)
        return out

    # weight layout [in_c, out_c/groups, *k]; flip spatial for transpose conv
    def prep(w):
        return jnp.flip(w, axis=tuple(range(2, 2 + n)))

    if groups > 1:
        def fg(a, w, *b):
            a, w = _promote(a, w)
            a_gs = jnp.split(a, groups, axis=1)
            w_gs = jnp.split(w, groups, axis=0)
            outs = []
            for ag, wg in zip(a_gs, w_gs):
                outs.append(jax.lax.conv_general_dilated(
                    ag, prep(wg), window_strides=(1,) * n, padding=pads,
                    lhs_dilation=strides, rhs_dilation=dil,
                    dimension_numbers=dn))
            out = jnp.concatenate(outs, axis=1)
            if b:
                bshape = [1] * out.ndim
                bshape[1] = b[0].shape[0]
                out = out + b[0].reshape(bshape)
            return out
        if bias is not None:
            return apply_op(fg, x, weight, bias)
        return apply_op(fg, x, weight)

    def f2(a, w, *b):
        return f(a, prep(w), *b)
    if bias is not None:
        return apply_op(f2, x, weight, bias)
    return apply_op(f2, x, weight)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     data_format="NCL", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, "NCH")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     data_format="NCHW", output_size=None, name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     data_format="NCDHW", output_size=None, name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format)
