"""Common functionals: linear, dropout, embedding, one_hot, interpolate, etc.

Parity: python/paddle/nn/functional/common.py + input.py + extension bits.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.rng import next_key
from ...tensor.tensor import Tensor, apply_op

__all__ = ["linear", "fused_concat_linear", "dropout", "dropout2d",
           "dropout3d", "alpha_dropout",
           "embedding", "one_hot", "label_smooth", "unfold", "fold",
           "interpolate", "upsample", "bilinear", "cosine_similarity",
           "pixel_shuffle", "pixel_unshuffle", "channel_shuffle", "zeropad2d",
           "class_center_sample", "normalize"]


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b.  Weight layout [in, out] (paddle convention) — feeds the
    MXU directly as a single jnp.dot; XLA fuses the bias add. Under amp O1 the
    matmul runs in the amp dtype (bf16 on TPU)."""
    from ...amp.auto_cast import cast_if_amp

    if bias is None:
        def f(a, w):
            a, w = cast_if_amp("linear", a, w)
            return jnp.matmul(a, w)
        return apply_op(f, x, weight)

    def f(a, w, b):
        a, w = cast_if_amp("linear", a, w)
        out = jnp.matmul(a, w)
        return out + b.astype(out.dtype)
    return apply_op(f, x, weight, bias)


def fused_concat_linear(x, weights, biases=None):
    """ONE GEMM over horizontally-concatenated projection weights — the
    compute-time fusion behind the self-attention QKV and SwiGLU gate/up
    fast paths (MultiHeadAttention, LlamaAttention, LlamaMLP). The
    parameters stay separate (state-dict parity with the reference
    layers); autograd splits the grads back through the concat. AMP
    semantics are EXACTLY F.linear's (cast_if_amp 'linear'), so the
    fused matmul runs in the amp dtype under auto_cast instead of
    silently upcasting to fp32."""
    from ...amp.auto_cast import cast_if_amp
    if biases is not None:
        n_none = sum(1 for b in biases if b is None)
        if n_none == len(biases):
            biases = None
        elif n_none:
            # a mixed list would previously drop ALL biases silently —
            # wrong result with no error. Refuse instead; callers with a
            # genuinely mixed layout should pass explicit zeros.
            raise ValueError(
                "fused_concat_linear: biases must be all None or all "
                f"set, got {n_none}/{len(biases)} None. Pass explicit "
                "zero biases for the bias-less projections.")
    n = len(weights)

    if biases is None:
        def f(a, *ws):
            w = jnp.concatenate(ws, axis=1)
            a, w = cast_if_amp("linear", a, w)
            return jnp.matmul(a, w)
        return apply_op(f, x, *weights)

    def f(a, *wbs):
        w = jnp.concatenate(wbs[:n], axis=1)
        b = jnp.concatenate(wbs[n:])
        a, w = cast_if_amp("linear", a, w)
        out = jnp.matmul(a, w)
        return out + b.astype(out.dtype)
    return apply_op(f, x, *weights, *biases)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply_op(lambda a: a * (1.0 - p), x)
        return x
    key = next_key()

    def f(a):
        if axis is None:
            mask_shape = a.shape
        else:
            axes = (axis,) if isinstance(axis, int) else tuple(axis)
            mask_shape = tuple(s if i in [ax % a.ndim for ax in axes] else 1
                               for i, s in enumerate(a.shape))
        keep = jax.random.bernoulli(key, 1.0 - p, mask_shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), jnp.zeros((), a.dtype))
        return jnp.where(keep, a, jnp.zeros((), a.dtype))
    return apply_op(f, x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = (0, 1) if data_format == "NCHW" else (0, 3)
    return dropout(x, p, axis=list(ax), training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = (0, 1) if data_format == "NCDHW" else (0, 4)
    return dropout(x, p, axis=list(ax), training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    key = next_key()

    def f(a):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        coef_a = (1.0 - p + p * alpha_p ** 2) ** -0.5
        coef_b = -coef_a * p * alpha_p
        return coef_a * jnp.where(keep, a, alpha_p) + coef_b
    return apply_op(f, x)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    # ids ride as a real apply_op INPUT (not a closure capture): a Tensor
    # input replays with fresh feeds under static Program capture, while a
    # closure would pin the capture-time ids forever. Integer inputs are
    # grad-safe (vjp cotangent is float0; the engine skips stop_gradient
    # inputs).
    if isinstance(x, Tensor):
        def f2(ids, w):
            out = jnp.take(w, ids, axis=0)
            if padding_idx is not None:
                out = jnp.where((ids == padding_idx)[..., None], 0.0, out)
            return out
        return apply_op(f2, x, weight)
    ids = jnp.asarray(x)

    def f(w):
        out = jnp.take(w, ids, axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return apply_op(f, weight)


def one_hot(x, num_classes, name=None):
    ids = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.nn.one_hot(ids, num_classes, dtype=jnp.float32))


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def f(l):
        k = l.shape[-1]
        if prior_dist is not None:
            pd = prior_dist._data if isinstance(prior_dist, Tensor) else prior_dist
            return (1 - epsilon) * l + epsilon * pd
        return (1 - epsilon) * l + epsilon / k
    return apply_op(f, label)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    k = _pair(kernel_sizes)
    s = _pair(strides)
    p = _pair(paddings) if not (isinstance(paddings, (list, tuple)) and len(paddings) == 4) else paddings
    d = _pair(dilations)

    def f(a):
        n, c, h, w = a.shape
        if len(p) == 2:
            pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
        else:
            pads = ((0, 0), (0, 0), (p[0], p[1]), (p[2], p[3]))
        a = jnp.pad(a, pads)
        patches = jax.lax.conv_general_dilated_patches(
            a, filter_shape=k, window_strides=s, padding="VALID",
            rhs_dilation=d, dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return patches.reshape(n, patches.shape[1], -1)
    return apply_op(f, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    out_hw = _pair(output_sizes)
    k = _pair(kernel_sizes)
    s = _pair(strides)
    p = _pair(paddings)
    d = _pair(dilations)

    def f(a):
        n, ckk, L = a.shape
        c = ckk // (k[0] * k[1])
        H = out_hw[0] + 2 * p[0]
        W = out_hw[1] + 2 * p[1]
        oh = (H - d[0] * (k[0] - 1) - 1) // s[0] + 1
        ow = (W - d[1] * (k[1] - 1) - 1) // s[1] + 1
        out = jnp.zeros((n, c, H, W), a.dtype)
        a_r = a.reshape(n, c, k[0], k[1], oh, ow)
        for i in range(k[0]):
            for j in range(k[1]):
                hi = i * d[0]
                wj = j * d[1]
                patch = a_r[:, :, i, j]
                out = out.at[:, :, hi:hi + oh * s[0]:s[0],
                             wj:wj + ow * s[1]:s[1]].add(patch)
        return out[:, :, p[0]:H - p[0], p[1]:W - p[1]] if (p[0] or p[1]) else out
    return apply_op(f, x)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    def f(a):
        is_nchw = data_format.upper().startswith("NC")
        spatial = a.shape[2:] if is_nchw else a.shape[1:-1]
        if size is not None:
            tgt = tuple(int(s.item()) if isinstance(s, Tensor) else int(s)
                        for s in (size if isinstance(size, (list, tuple)) else [size]))
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) else \
                [scale_factor] * len(spatial)
            tgt = tuple(int(dim * f_) for dim, f_ in zip(spatial, sf))
        method = {"nearest": "nearest", "bilinear": "bilinear",
                  "trilinear": "trilinear", "bicubic": "cubic",
                  "linear": "linear", "area": "linear"}[mode]
        if is_nchw:
            new_shape = a.shape[:2] + tgt
        else:
            new_shape = (a.shape[0],) + tgt + (a.shape[-1],)
        return jax.image.resize(a, new_shape, method=method)
    return apply_op(f, x)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def bilinear(x1, x2, weight, bias=None, name=None):
    def f(a, b, w, *bb):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bb:
            out = out + bb[0]
        return out
    if bias is not None:
        return apply_op(f, x1, x2, weight, bias)
    return apply_op(f, x1, x2, weight)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def f(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis)
        return num / jnp.maximum(den, eps)
    return apply_op(f, x1, x2)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def f(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c // (r * r), r, r, h, w)
        a = jnp.transpose(a, (0, 1, 4, 2, 5, 3))
        return a.reshape(n, c // (r * r), h * r, w * r)
    return apply_op(f, x)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def f(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c, h // r, r, w // r, r)
        a = jnp.transpose(a, (0, 1, 3, 5, 2, 4))
        return a.reshape(n, c * r * r, h // r, w // r)
    return apply_op(f, x)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def f(a):
        n, c, h, w = a.shape
        a = a.reshape(n, groups, c // groups, h, w)
        a = jnp.swapaxes(a, 1, 2)
        return a.reshape(n, c, h, w)
    return apply_op(f, x)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    p = padding

    def f(a):
        return jnp.pad(a, ((0, 0), (0, 0), (p[2], p[3]), (p[0], p[1])))
    return apply_op(f, x)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def f(a):
        nrm = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(nrm, epsilon)
    return apply_op(f, x)


def class_center_sample(label, num_classes, num_samples, group=None):
    lab = label._data
    uniq = jnp.unique(lab, size=min(num_samples, num_classes),
                      fill_value=num_classes)
    remap = jnp.searchsorted(uniq, lab)
    return Tensor(remap), Tensor(uniq)


# ---- round-2 breadth: spatial sampling + temporal shift -------------------
# Parity: python/paddle/nn/functional/vision.py :: grid_sample, affine_grid,
# temporal_shift (CUDA kernels under paddle/phi/kernels/gpu/grid_sample*).
import numpy as np  # noqa: E402

def affine_grid(theta, out_shape, align_corners=True, name=None):
    """theta [N,2,3] → sampling grid [N,H,W,2] in [-1,1] coords."""
    N, C, H, W = [int(v) for v in (out_shape if not isinstance(
        out_shape, Tensor) else np.asarray(out_shape._data))]

    def fn(th):
        if align_corners:
            ys = jnp.linspace(-1.0, 1.0, H)
            xs = jnp.linspace(-1.0, 1.0, W)
        else:
            ys = (jnp.arange(H) * 2 + 1) / H - 1.0
            xs = (jnp.arange(W) * 2 + 1) / W - 1.0
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [H,W,3]
        return jnp.einsum("nij,hwj->nhwi", th, base)
    return apply_op(fn, theta if isinstance(theta, Tensor)
                    else Tensor(jnp.asarray(theta)))


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """x [N,C,H,W], grid [N,Ho,Wo,2] (x,y in [-1,1]) → [N,C,Ho,Wo]."""
    assert mode in ("bilinear", "nearest")
    assert padding_mode in ("zeros", "border", "reflection")

    def fn(a, g):
        N, C, H, W = a.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            fx = (gx + 1) * (W - 1) / 2
            fy = (gy + 1) * (H - 1) / 2
        else:
            fx = ((gx + 1) * W - 1) / 2
            fy = ((gy + 1) * H - 1) / 2

        def reflect(v, lo, hi):
            rng_ = hi - lo
            v = jnp.abs((v - lo) % (2 * rng_ + 1e-12))
            return lo + jnp.minimum(v, 2 * rng_ - v)

        if padding_mode == "reflection":
            if align_corners:
                fx = reflect(fx, 0.0, W - 1.0)
                fy = reflect(fy, 0.0, H - 1.0)
            else:
                # half-pixel convention reflects over the pixel-edge box
                fx = jnp.clip(reflect(fx, -0.5, W - 0.5), 0, W - 1)
                fy = jnp.clip(reflect(fy, -0.5, H - 0.5), 0, H - 1)

        def gather(yi, xi):
            yc = jnp.clip(yi, 0, H - 1)
            xc = jnp.clip(xi, 0, W - 1)
            vals = jax.vmap(lambda f, yy, xx: f[:, yy, xx])(a, yc, xc)
            if padding_mode == "zeros":
                inb = ((yi >= 0) & (yi <= H - 1)
                       & (xi >= 0) & (xi <= W - 1))
                vals = vals * inb[:, None]
            return vals                                   # [N,C,Ho,Wo]

        if mode == "nearest":
            return gather(jnp.round(fy).astype(jnp.int32),
                          jnp.round(fx).astype(jnp.int32))
        y0 = jnp.floor(fy)
        x0 = jnp.floor(fx)
        wy = fy - y0
        wx = fx - x0
        y0i, x0i = y0.astype(jnp.int32), x0.astype(jnp.int32)
        out = (gather(y0i, x0i) * ((1 - wy) * (1 - wx))[:, None]
               + gather(y0i, x0i + 1) * ((1 - wy) * wx)[:, None]
               + gather(y0i + 1, x0i) * (wy * (1 - wx))[:, None]
               + gather(y0i + 1, x0i + 1) * (wy * wx)[:, None])
        return out
    return apply_op(fn, x, grid)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """TSM shift: first `ratio` channels shift t-1, next `ratio` shift t+1
    (reference temporal_shift op). x: [N*T, C, H, W]."""
    assert data_format == "NCHW"

    def fn(a):
        NT, C, H, W = a.shape
        T = seg_num
        N = NT // T
        v = a.reshape(N, T, C, H, W)
        c1 = int(C * shift_ratio)
        c2 = int(C * 2 * shift_ratio)
        fwd = jnp.concatenate(
            [v[:, 1:, :c1], jnp.zeros_like(v[:, :1, :c1])], axis=1)
        bwd = jnp.concatenate(
            [jnp.zeros_like(v[:, :1, c1:c2]), v[:, :-1, c1:c2]], axis=1)
        out = jnp.concatenate([fwd, bwd, v[:, :, c2:]], axis=2)
        return out.reshape(NT, C, H, W)
    return apply_op(fn, x)


__all__ += ["affine_grid", "grid_sample", "temporal_shift"]


# paddle exposes pad both as paddle.pad and nn.functional.pad — same op
from ...tensor.manipulation import pad  # noqa: E402,F401

__all__ += ["pad", "pairwise_distance", "sequence_mask", "gather_tree"]


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """||x - y + eps||_p along the last axis (reference pairwise_distance)."""
    def fn(a, b):
        d = jnp.abs(a - b + epsilon)
        if p == float("inf"):
            out = jnp.max(d, axis=-1, keepdims=keepdim)
        elif p == float("-inf"):
            out = jnp.min(d, axis=-1, keepdims=keepdim)
        else:
            out = jnp.sum(d ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)
        return out
    return apply_op(fn, x, y)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """mask[..., j] = j < x[...] (reference sequence_mask). maxlen defaults
    to max(x) — which forces a host sync for the output shape, so pass a
    static maxlen under jit."""
    xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    if maxlen is None:
        maxlen = int(jnp.max(xd))
    from ...core.dtype import convert_dtype
    jdt = convert_dtype(dtype)

    def fn(lens):
        j = jnp.arange(maxlen, dtype=lens.dtype)
        return (j < lens[..., None]).astype(jdt)
    return apply_op(fn, x if isinstance(x, Tensor) else Tensor(xd))


def gather_tree(ids, parents):
    """Beam-search ancestry walk (reference gather_tree): from the last
    step, follow parent pointers backwards so each beam's output is its
    full token path. ids/parents: [max_time, batch, beam_size]. The walk
    is a reversed lax.scan — one fused program, no host loop."""
    def fn(idv, par):
        t = idv.shape[0]
        beams = jnp.arange(idv.shape[2])

        def step(carry, xs):
            idv_t, par_t = xs            # [batch, beam]
            tok = jnp.take_along_axis(idv_t, carry, axis=1)
            nxt = jnp.take_along_axis(par_t, carry, axis=1)
            return nxt, tok

        init = jnp.broadcast_to(beams[None, :], idv.shape[1:]).astype(
            par.dtype)
        _, toks = jax.lax.scan(step, init, (idv, par), reverse=True)
        return toks                      # [max_time, batch, beam]
    return apply_op(fn, ids, parents)
