"""Recurrent layers. Parity: python/paddle/nn/layer/rnn.py ::
RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN, BiRNN, SimpleRNN,
LSTM, GRU.

TPU-first: the time loop is one `lax.scan` per (layer, direction) — a
single compiled loop whose body is an MXU matmul pair, not a Python loop
of ops (the reference's CUDA path is cuDNN's fused RNN; scan + XLA fusion
is the TPU analogue). Variable-length sequences mask state updates inside
the scan body, so shapes stay static. Built-in cells expose a pure-array
step (`_step`/`_params`) that RNN scans; custom RNNCellBase subclasses
without one fall back to an eager per-timestep loop through the tape."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...tensor.tensor import Tensor, apply_op
from ..initializer import Uniform
from .common import _resolve_init
from .layers import Layer, LayerList

__all__ = ["RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN",
           "BiRNN", "SimpleRNN", "LSTM", "GRU"]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        from ...core.dtype import convert_dtype
        b = batch_ref.shape[batch_dim_idx]
        dt = convert_dtype(dtype)
        if dt is None:
            w = getattr(self, "weight_hh", None)
            dt = w._data.dtype if w is not None else jnp.float32
        state_shape = shape or self.state_shape
        if isinstance(state_shape[0], (list, tuple)):
            return tuple(Tensor(jnp.full((b, *s), init_value, dt))
                         for s in state_shape)
        return Tensor(jnp.full((b, *state_shape), init_value, dt))


def _make_cell_params(layer, input_size, hidden_size, gates,
                      weight_ih_attr=None, weight_hh_attr=None,
                      bias_ih_attr=None, bias_hh_attr=None):
    k = 1.0 / math.sqrt(hidden_size)
    default = Uniform(-k, k)
    dt = layer._dtype
    wi_init, wi_name = _resolve_init(weight_ih_attr, default)
    wh_init, wh_name = _resolve_init(weight_hh_attr, default)
    from ...tensor.tensor import Parameter
    layer.weight_ih = Parameter(
        wi_init((gates * hidden_size, input_size), dt), name=wi_name)
    layer.weight_hh = Parameter(
        wh_init((gates * hidden_size, hidden_size), dt), name=wh_name)
    for attr, name in ((bias_ih_attr, "bias_ih"),
                       (bias_hh_attr, "bias_hh")):
        if attr is False:
            setattr(layer, name, None)
        else:
            b_init, b_name = _resolve_init(attr, default)
            setattr(layer, name,
                    Parameter(b_init((gates * hidden_size,), dt),
                              name=b_name))


class SimpleRNNCell(RNNCellBase):
    """h' = act(W_ih x + b_ih + W_hh h + b_hh)."""

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        if activation not in ("tanh", "relu"):
            raise ValueError(
                f"SimpleRNNCell activation must be 'tanh' or 'relu', got "
                f"{activation!r}")
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        _make_cell_params(self, input_size, hidden_size, 1,
                          weight_ih_attr, weight_hh_attr, bias_ih_attr,
                          bias_hh_attr)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def _params(self):
        return tuple(p for p in (self.weight_ih, self.weight_hh,
                                 self.bias_ih, self.bias_hh)
                     if p is not None)

    def _make_step(self):
        act = jnp.tanh if self.activation == "tanh" else (
            lambda v: jnp.maximum(v, 0))
        has_bi = self.bias_ih is not None
        has_bh = self.bias_hh is not None

        def step(x, h, *params):
            it = iter(params)
            wi, wh = next(it), next(it)
            bi = next(it) if has_bi else 0.0
            bh = next(it) if has_bh else 0.0
            return (act(x @ wi.T + bi + h @ wh.T + bh),)
        return step

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h = apply_op(lambda *a: self._make_step()(*a)[0], inputs, states,
                     *self._params())
        return h, h


class LSTMCell(RNNCellBase):
    """Gates i,f,g,o in the reference's chunk order; states (h, c).
    proj_size adds the output projection h = (o*tanh(c)) @ W_ho^T."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=0, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.proj_size = int(proj_size or 0)
        h_in = self.proj_size if self.proj_size else hidden_size
        k = 1.0 / math.sqrt(hidden_size)
        default = Uniform(-k, k)
        from ...tensor.tensor import Parameter
        dt = self._dtype
        wi_init, wi_name = _resolve_init(weight_ih_attr, default)
        wh_init, wh_name = _resolve_init(weight_hh_attr, default)
        self.weight_ih = Parameter(
            wi_init((4 * hidden_size, input_size), dt), name=wi_name)
        self.weight_hh = Parameter(
            wh_init((4 * hidden_size, h_in), dt), name=wh_name)
        for attr, name_ in ((bias_ih_attr, "bias_ih"),
                            (bias_hh_attr, "bias_hh")):
            if attr is False:
                setattr(self, name_, None)
            else:
                b_init, b_name = _resolve_init(attr, default)
                setattr(self, name_,
                        Parameter(b_init((4 * hidden_size,), dt),
                                  name=b_name))
        if self.proj_size:
            self.weight_ho = Parameter(
                default((self.proj_size, hidden_size), dt))

    @property
    def state_shape(self):
        h = self.proj_size if self.proj_size else self.hidden_size
        return ((h,), (self.hidden_size,))

    def _params(self):
        ps = [self.weight_ih, self.weight_hh]
        if self.bias_ih is not None:
            ps.append(self.bias_ih)
        if self.bias_hh is not None:
            ps.append(self.bias_hh)
        if self.proj_size:
            ps.append(self.weight_ho)
        return tuple(ps)

    def _make_step(self):
        has_bi = self.bias_ih is not None
        has_bh = self.bias_hh is not None
        proj = bool(self.proj_size)

        def step(x, h, c, *params):
            it = iter(params)
            wi, wh = next(it), next(it)
            bi = next(it) if has_bi else 0.0
            bh = next(it) if has_bh else 0.0
            z = x @ wi.T + bi + h @ wh.T + bh
            i, f, g, o = jnp.split(z, 4, axis=-1)
            i, f, o = (jax.nn.sigmoid(i), jax.nn.sigmoid(f),
                       jax.nn.sigmoid(o))
            c2 = f * c + i * jnp.tanh(g)
            h2 = o * jnp.tanh(c2)
            if proj:
                h2 = h2 @ next(it).T
            return (h2, c2)
        return step

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h0, c0 = states
        h, c = apply_op(lambda *a: self._make_step()(*a), inputs, h0, c0,
                        *self._params(), n_outputs=2)
        return h, (h, c)


class GRUCell(RNNCellBase):
    """Gates r,z,c in the reference's chunk order;
    h' = z*h + (1-z)*tanh(W_ic x + r*(W_hc h))."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        _make_cell_params(self, input_size, hidden_size, 3,
                          weight_ih_attr, weight_hh_attr, bias_ih_attr,
                          bias_hh_attr)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def _params(self):
        return tuple(p for p in (self.weight_ih, self.weight_hh,
                                 self.bias_ih, self.bias_hh)
                     if p is not None)

    def _make_step(self):
        has_bi = self.bias_ih is not None
        has_bh = self.bias_hh is not None

        def step(x, h, *params):
            it = iter(params)
            wi, wh = next(it), next(it)
            bi = next(it) if has_bi else 0.0
            bh = next(it) if has_bh else 0.0
            xz = x @ wi.T + bi
            hz = h @ wh.T + bh
            xr, xu, xc = jnp.split(xz, 3, axis=-1)
            hr, hu, hc = jnp.split(hz, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            u = jax.nn.sigmoid(xu + hu)
            c = jnp.tanh(xc + r * hc)
            return (u * h + (1.0 - u) * c,)
        return step

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h = apply_op(lambda *a: self._make_step()(*a)[0], inputs, states,
                     *self._params())
        return h, h


def _scan_layer(step, x_tbi, init_states, params, reverse, seq_lens):
    """One lax.scan over time. x_tbi: [T, B, I] (time-major inside).
    seq_lens: [B] int or None — beyond-length steps keep state and emit 0."""
    T = x_tbi.shape[0]
    ts = jnp.arange(T)
    if reverse:
        x_tbi = x_tbi[::-1]
        ts = ts[::-1]

    def body(carry, xt):
        x_t, t = xt
        new = step(x_t, *carry, *params)
        if seq_lens is not None:
            valid = (t < seq_lens)[:, None]
            new = tuple(jnp.where(valid, n, c) for n, c in zip(new, carry))
            out = jnp.where(valid, new[0], jnp.zeros_like(new[0]))
        else:
            out = new[0]
        return new, out

    final, outs = jax.lax.scan(body, tuple(init_states), (x_tbi, ts))
    if reverse:
        outs = outs[::-1]
    return outs, final


class RNN(Layer):
    """Run a cell over a sequence (reference rnn.py :: RNN). Built-in cells
    run as one compiled scan; custom cells (no `_make_step`) fall back to an
    eager per-timestep loop through the cell's forward."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def _eager_loop(self, inputs, states, sequence_length):
        from ...tensor.manipulation import stack, unbind
        steps = unbind(inputs, axis=0 if self.time_major else 1)
        if self.is_reverse:
            steps = steps[::-1]
        outs = []
        for x_t in steps:
            out, states = self.cell(x_t, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        return stack(outs, axis=0 if self.time_major else 1), states

    def forward(self, inputs, initial_states=None, sequence_length=None):
        cell = self.cell
        if initial_states is None:
            batch_idx = 1 if self.time_major else 0
            initial_states = cell.get_initial_states(
                inputs, batch_dim_idx=batch_idx)
        states = tuple(initial_states) if isinstance(
            initial_states, (tuple, list)) else (initial_states,)
        if not hasattr(cell, "_make_step"):
            if sequence_length is not None:
                raise ValueError(
                    "sequence_length requires a built-in cell (scan path)")
            st = states if len(states) > 1 else states[0]
            return self._eager_loop(inputs, st, sequence_length)

        time_major, reverse = self.time_major, self.is_reverse
        step = cell._make_step()
        seq = None if sequence_length is None else (
            sequence_length._data if isinstance(sequence_length, Tensor)
            else jnp.asarray(sequence_length))

        def fn(x, *state_and_params):
            n_s = len(states)
            init = state_and_params[:n_s]
            params = state_and_params[n_s:]
            x_t = x if time_major else jnp.swapaxes(x, 0, 1)
            outs, final = _scan_layer(step, x_t, init, params, reverse, seq)
            outs = outs if time_major else jnp.swapaxes(outs, 0, 1)
            return (outs, *final)

        res = apply_op(fn, inputs, *states, *cell._params(),
                       n_outputs=1 + len(states))
        outs, final = res[0], res[1:]
        final_states = tuple(final) if len(states) > 1 else final[0]
        return outs, final_states


class BiRNN(Layer):
    """Forward + backward cells over the same sequence, outputs
    concatenated on the feature dim."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if initial_states is None:
            states_fw = states_bw = None
        else:
            states_fw, states_bw = initial_states
        out_fw, st_fw = self.rnn_fw(inputs, states_fw, sequence_length)
        out_bw, st_bw = self.rnn_bw(inputs, states_bw, sequence_length)
        outs = apply_op(lambda a, b: jnp.concatenate([a, b], axis=-1),
                        out_fw, out_bw)
        return outs, (st_fw, st_bw)


class _StackedRNNBase(Layer):
    _cell_cls: type = SimpleRNNCell
    _n_states = 1

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **cell_kwargs):
        super().__init__()
        assert direction in ("forward", "bidirect", "bidirectional")
        self.bidirect = direction != "forward"
        self.num_layers = num_layers
        self.hidden_size = hidden_size
        self.time_major = time_major
        self.dropout = float(dropout)
        ndir = 2 if self.bidirect else 1
        rnns = []
        for layer in range(num_layers):
            in_sz = input_size if layer == 0 else hidden_size * ndir
            if self.bidirect:
                rnns.append(BiRNN(self._cell_cls(in_sz, hidden_size,
                                                 **cell_kwargs),
                                  self._cell_cls(in_sz, hidden_size,
                                                 **cell_kwargs),
                                  time_major=time_major))
            else:
                rnns.append(RNN(self._cell_cls(in_sz, hidden_size,
                                               **cell_kwargs),
                                time_major=time_major))
        self.rnns = LayerList(rnns)

    def _layer_states(self, initial_states, layer):
        """Slice stacked [L*D, B, H] paddle-layout initial states into this
        layer's per-cell states (fw, or ((fw),(bw)) when bidirectional)."""
        if initial_states is None:
            return None
        stacked = initial_states if isinstance(
            initial_states, (tuple, list)) else (initial_states,)
        ndir = 2 if self.bidirect else 1

        def pick(i):
            return tuple(s[layer * ndir + i] for s in stacked)

        def unwrap(t):
            return t if len(t) > 1 else t[0]

        if self.bidirect:
            return (unwrap(pick(0)), unwrap(pick(1)))
        return unwrap(pick(0))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from .. import functional as F
        x = inputs
        finals = []
        for i, rnn in enumerate(self.rnns):
            x, st = rnn(x, self._layer_states(initial_states, i),
                        sequence_length)
            finals.append(st)
            if self.dropout and i < self.num_layers - 1:
                x = F.dropout(x, self.dropout, training=self.training)
        # stack finals into the reference layout [L*D, B, H]
        if self._n_states == 1:
            hs = []
            for st in finals:
                if self.bidirect:
                    hs += [st[0], st[1]]
                else:
                    hs.append(st)
            h = apply_op(lambda *a: jnp.stack(a), *hs)
            return x, h
        hs, cs = [], []
        for st in finals:
            if self.bidirect:
                (h_f, c_f), (h_b, c_b) = st
                hs += [h_f, h_b]
                cs += [c_f, c_b]
            else:
                hs.append(st[0])
                cs.append(st[1])
        h = apply_op(lambda *a: jnp.stack(a), *hs)
        c = apply_op(lambda *a: jnp.stack(a), *cs)
        return x, (h, c)


class SimpleRNN(_StackedRNNBase):
    _cell_cls = SimpleRNNCell
    _n_states = 1


class LSTM(_StackedRNNBase):
    _cell_cls = LSTMCell
    _n_states = 2


class GRU(_StackedRNNBase):
    _cell_cls = GRUCell
    _n_states = 1
