"""Common layers. Parity: python/paddle/nn/layer/common.py."""
from __future__ import annotations

import math

import jax.numpy as jnp

from ...tensor.tensor import Parameter
from .. import functional as F
from ..initializer import Constant, XavierNormal, Normal, Uniform, KaimingUniform
from .layers import Layer

__all__ = ["Linear", "Dropout", "Dropout2D", "Dropout3D", "AlphaDropout",
           "Embedding", "Flatten", "Upsample", "UpsamplingBilinear2D",
           "UpsamplingNearest2D", "Pad1D", "Pad2D", "Pad3D", "ZeroPad2D",
           "CosineSimilarity", "Bilinear", "Identity", "Unfold", "Fold",
           "PixelShuffle", "PixelUnshuffle", "ChannelShuffle",
           "Unflatten", "PairwiseDistance"]


def _resolve_init(attr, default):
    if attr is None or attr is True:
        return default, None
    if attr is False:
        return None, None
    init = getattr(attr, "initializer", None) or default
    name = getattr(attr, "name", None)
    return init, name


class Linear(Layer):
    """y = xW + b with W:[in, out] — a single MXU matmul on TPU.

    Parity: python/paddle/nn/layer/common.py :: Linear.
    """

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        w_init, w_name = _resolve_init(weight_attr, XavierNormal())
        self.weight = Parameter(w_init((in_features, out_features),
                                       self._dtype), name=w_name)
        if bias_attr is False:
            self.bias = None
        else:
            b_init, b_name = _resolve_init(bias_attr, Constant(0.0))
            self.bias = Parameter(b_init((out_features,), self._dtype),
                                  name=b_name)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Identity(Layer):
    def __init__(self, *a, **k):
        super().__init__()

    def forward(self, x):
        return x


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training,
                         mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, self.training, self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, self.training, self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, self.training)


class Embedding(Layer):
    """Token embedding. Parity: nn/layer/common.py :: Embedding."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        w_init, w_name = _resolve_init(weight_attr, Normal(0.0, 1.0))
        w = w_init((num_embeddings, embedding_dim), self._dtype)
        if padding_idx is not None:
            w = w.at[padding_idx].set(0.0)
        self.weight = Parameter(w, name=w_name)

    def forward(self, x):
        return F.embedding(x, self.weight, self.padding_idx)

    def extra_repr(self):
        return f"{self.num_embeddings}, {self.embedding_dim}"


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ...tensor.manipulation import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners = mode, align_corners
        self.align_mode, self.data_format = align_mode, data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode,
                             self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class _PadN(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.padding, self.mode, self.value = padding, mode, value
        self.data_format = data_format

    def forward(self, x):
        from ...tensor.manipulation import pad
        return pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad1D(_PadN):
    pass


class Pad2D(_PadN):
    pass


class Pad3D(_PadN):
    pass


class ZeroPad2D(Layer):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__()
        self.padding = padding
        self.data_format = data_format

    def forward(self, x):
        return F.zeropad2d(x, self.padding, self.data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        bound = 1.0 / math.sqrt(in1_features)
        w_init, _ = _resolve_init(weight_attr, Uniform(-bound, bound))
        self.weight = Parameter(w_init((out_features, in1_features,
                                        in2_features), self._dtype))
        if bias_attr is False:
            self.bias = None
        else:
            b_init, _ = _resolve_init(bias_attr, Uniform(-bound, bound))
            self.bias = Parameter(b_init((out_features,), self._dtype))

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.args = (output_sizes, kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, *self.args)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.r = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.r, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.r = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.r, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups
        self.data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class Unflatten(Layer):
    """Reshape one axis into the given shape (reference: nn.Unflatten)."""

    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape_ = int(axis), tuple(int(s) for s in shape)

    def forward(self, x):
        from ...tensor.manipulation import unflatten
        return unflatten(x, self.axis, self.shape_)


class PairwiseDistance(Layer):
    """p-norm distance between row vectors (reference: nn.PairwiseDistance)."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = float(p), epsilon, keepdim

    def forward(self, x, y):
        from ...tensor.tensor import apply_op
        import jax.numpy as jnp

        def f(a, b):
            d = (a - b).astype(jnp.float32) + self.epsilon
            if self.p == float("inf"):
                out = jnp.max(jnp.abs(d), axis=-1, keepdims=self.keepdim)
            else:
                out = jnp.sum(jnp.abs(d) ** self.p, axis=-1,
                              keepdims=self.keepdim) ** (1.0 / self.p)
            return out.astype(a.dtype)
        return apply_op(f, x, y)
