"""Loss layers. Parity: python/paddle/nn/layer/loss.py."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import functional as F
from .layers import Layer, LayerList, Sequential

__all__ = ["CrossEntropyLoss", "NLLLoss", "BCELoss", "BCEWithLogitsLoss",
           "L1Loss", "MSELoss", "SmoothL1Loss", "KLDivLoss",
           "MarginRankingLoss", "CosineEmbeddingLoss", "CTCLoss",
           "HingeEmbeddingLoss", "TripletMarginLoss"]


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):
        return F.cross_entropy(input, label, self.weight, self.ignore_index,
                               self.reduction, self.soft_label, self.axis,
                               self.use_softmax, self.label_smoothing)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 name=None):
        super().__init__()
        self.weight, self.ignore_index, self.reduction = weight, ignore_index, reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index,
                          self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight,
                                      self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None,
                 name=None):
        super().__init__()
        self.weight, self.reduction, self.pos_weight = weight, reduction, pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.reduction, self.pos_weight)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class MSELoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean", log_target=False, name=None):
        super().__init__()
        self.reduction, self.log_target = reduction, log_target

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction, self.log_target)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean", name=None):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin,
                                      self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (margin, p, epsilon, swap, reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, *self.args)


# ---- round-2 breadth -------------------------------------------------------

class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean",
                 name=None):
        super().__init__()
        self.args = (full, epsilon, reduction)

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, *self.args)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (log_input, full, epsilon, reduction)

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, *self.args)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self.weight,
                                              self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (distance_function, margin, swap, reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(input, positive,
                                                   negative, *self.args)


__all__ += ["GaussianNLLLoss", "PoissonNLLLoss", "SoftMarginLoss",
            "MultiLabelSoftMarginLoss", "TripletMarginWithDistanceLoss"]


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p, self.margin = p, margin
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, self.p, self.margin,
                                   self.weight, self.reduction)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Hierarchical (adaptive) softmax (reference:
    nn.AdaptiveLogSoftmaxWithLoss): frequent classes in a head softmax,
    rare classes in down-projected tail clusters entered through one head
    slot each. TPU-first: every token computes head + ALL tail clusters
    (static shapes — no data-dependent gather of "which cluster"), with
    the per-token cluster selected by jnp.where masks; the extra tail
    FLOPs are dwarfed by the head matmul at realistic cutoffs and keep
    the step jit-compilable."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        from .common import Linear
        cutoffs = list(cutoffs)
        if (cutoffs != sorted(cutoffs) or min(cutoffs) <= 0
                or max(cutoffs) > n_classes - 1
                or len(set(cutoffs)) != len(cutoffs)):
            raise ValueError("cutoffs must be unique, positive, "
                             "increasing, and < n_classes")
        self.in_features = in_features
        self.n_classes = n_classes
        self.cutoffs = cutoffs + [n_classes]
        self.div_value = float(div_value)
        self.n_clusters = len(self.cutoffs) - 1
        self.head_size = self.cutoffs[0] + self.n_clusters
        self.head = Linear(in_features, self.head_size,
                           bias_attr=head_bias)
        self.tail = LayerList()
        for i in range(self.n_clusters):
            hsz = max(1, int(in_features // (self.div_value ** (i + 1))))
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            self.tail.append(Sequential(
                ("proj", Linear(in_features, hsz, bias_attr=False)),
                ("out", Linear(hsz, osz, bias_attr=False)),
            ))

    def log_prob(self, input):
        """Full [N, n_classes] log-probabilities."""
        from ...tensor.tensor import apply_op
        head_out = self.head(input)
        tails = [t(input) for t in self.tail]

        def f(h, *ts):
            hl = jax.nn.log_softmax(h.astype(jnp.float32), axis=-1)
            parts = [hl[..., : self.cutoffs[0]]]
            for i, t in enumerate(ts):
                tl = jax.nn.log_softmax(t.astype(jnp.float32), axis=-1)
                parts.append(tl + hl[..., self.cutoffs[0] + i:
                                     self.cutoffs[0] + i + 1])
            return jnp.concatenate(parts, axis=-1)
        return apply_op(f, head_out, *tails)

    def forward(self, input, label):
        """Returns (output [N] = per-sample TARGET log-prob, scalar mean
        NLL) — the reference's contract (output is not the full
        distribution; use log_prob for that)."""
        from ...tensor.tensor import apply_op
        logp = self.log_prob(input)

        def tok_logp(lp, y):
            return jnp.take_along_axis(
                lp, y.astype(jnp.int32)[..., None], axis=-1)[..., 0]
        out = apply_op(tok_logp, logp, label)
        loss = apply_op(lambda t: -jnp.mean(t), out)
        return out, loss

    def predict(self, input):
        from ...tensor.tensor import apply_op
        logp = self.log_prob(input)
        return apply_op(lambda lp: jnp.argmax(lp, axis=-1).astype(
            jnp.int32), logp)


__all__ += ["MultiMarginLoss", "AdaptiveLogSoftmaxWithLoss"]
