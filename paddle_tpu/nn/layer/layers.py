"""Layer base: parameter registration, sublayers, state_dict, hooks.

Parity: python/paddle/nn/layer/layers.py :: Layer, LayerList, ParameterList,
Sequential. TPU-first: parameters are jax-array-backed Parameters in a pytree;
``to(dtype)`` recasts arrays; there is no device copy (XLA places data).
"""
from __future__ import annotations

import collections
from typing import Callable, Iterator, Optional

import jax.numpy as jnp
import numpy as np

from ...core.dtype import convert_dtype
from ...tensor.tensor import Parameter, Tensor, no_grad

__all__ = ["Layer", "LayerList", "LayerDict", "ParameterList", "Sequential",
           "enable_static", "disable_static", "in_dynamic_mode"]

_dynamic_mode = [True]


def enable_static():
    _dynamic_mode[0] = False
    from ...static import _install_capture
    _install_capture()


def disable_static():
    _dynamic_mode[0] = True
    from ...static import _remove_capture
    _remove_capture()


def in_dynamic_mode() -> bool:
    return _dynamic_mode[0]


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    """Base class for all network layers (paddle.nn.Layer parity)."""

    def __init__(self, name_scope: Optional[str] = None, dtype="float32"):
        self._parameters: "collections.OrderedDict[str, Parameter]" = collections.OrderedDict()
        self._sub_layers: "collections.OrderedDict[str, Layer]" = collections.OrderedDict()
        self._buffers: "collections.OrderedDict[str, Tensor]" = collections.OrderedDict()
        self._non_persistable_buffer_names: set[str] = set()
        self._forward_pre_hooks: "collections.OrderedDict[int, Callable]" = collections.OrderedDict()
        self._forward_post_hooks: "collections.OrderedDict[int, Callable]" = collections.OrderedDict()
        self.training = True
        self._dtype = convert_dtype(dtype)
        self._name_scope = name_scope or type(self).__name__.lower()
        self._hook_id = 0

    # ------------------------------------------------------------ attribute
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning params")
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning layers")
            layers[name] = value
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    del params[name]
                else:
                    params[name] = value
                    return
            if layers is not None and name in layers:
                if value is None:
                    del layers[name]
                else:
                    layers[name] = value
                    return
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        if "_parameters" in self.__dict__ and name in self.__dict__["_parameters"]:
            return self.__dict__["_parameters"][name]
        if "_sub_layers" in self.__dict__ and name in self.__dict__["_sub_layers"]:
            return self.__dict__["_sub_layers"][name]
        if "_buffers" in self.__dict__ and name in self.__dict__["_buffers"]:
            return self.__dict__["_buffers"][name]
        raise AttributeError(f"{type(self).__name__} has no attribute {name!r}")

    def __delattr__(self, name):
        if name in self._parameters:
            del self._parameters[name]
        elif name in self._sub_layers:
            del self._sub_layers[name]
        elif name in self._buffers:
            del self._buffers[name]
        else:
            object.__delattr__(self, name)

    # ------------------------------------------------------------- registry
    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor],
                        persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        from ...tensor.creation import create_parameter as _cp
        p = _cp(shape, dtype or self._dtype, attr=attr, is_bias=is_bias,
                default_initializer=default_initializer)
        if attr is not None and getattr(attr, "name", None):
            p.name = attr.name
        return p

    # ------------------------------------------------------------ iterators
    def named_parameters(self, prefix: str = "", include_sublayers: bool = True
                         ) -> Iterator[tuple[str, Parameter]]:
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p

    def parameters(self, include_sublayers: bool = True) -> list[Parameter]:
        return [p for _, p in self.named_parameters()]

    def named_sublayers(self, prefix: str = "", include_self: bool = False,
                        layers_set=None) -> Iterator[tuple[str, "Layer"]]:
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from sub.named_sublayers(prefix=sub_prefix, include_self=True,
                                           layers_set=layers_set)

    def sublayers(self, include_self: bool = False) -> list["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self) -> Iterator["Layer"]:
        for _, l in self._sub_layers.items():
            if l is not None:
                yield l

    def named_children(self):
        yield from self._sub_layers.items()

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b

    def buffers(self, include_sublayers: bool = True):
        return [b for _, b in self.named_buffers()]

    def apply(self, fn: Callable):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # ----------------------------------------------------------------- mode
    def train(self):
        self.training = True
        for l in self.children():
            l.train()
        return self

    def eval(self):
        self.training = False
        for l in self.children():
            l.eval()
        return self

    # ---------------------------------------------------------------- hooks
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # ----------------------------------------------------------------- call
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    # ------------------------------------------------------------ state-dict
    def state_dict(self, destination=None, include_sublayers: bool = True,
                   structured_name_prefix: str = "", use_hook: bool = True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip(".")):
            dest[name] = p
        for name, layer in self.named_sublayers(include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                key = f"{name}.{bname}" if name else bname
                dest[key] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name: bool = True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            target = own[k]
            arr = v._data if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
            target.set_value(arr)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # -------------------------------------------------------------- casting
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = convert_dtype(dtype)
            self._cast_all(dt)
        return self

    def astype(self, dtype):
        self._cast_all(convert_dtype(dtype))
        return self

    def _cast_all(self, dt, floating_only: bool = True):
        for _, p in self.named_parameters():
            if not floating_only or jnp.issubdtype(p.dtype, jnp.floating):
                p._data = p._data.astype(dt)
        for _, b in self.named_buffers():
            if not floating_only or jnp.issubdtype(b.dtype, jnp.floating):
                b._data = b._data.astype(dt)

    def float(self):
        self._cast_all(jnp.float32)
        return self

    def bfloat16(self):
        self._cast_all(jnp.bfloat16)
        return self

    def float16(self):
        self._cast_all(jnp.float16)
        return self

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def full_name(self):
        return self._name_scope

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = [f"{type(self).__name__}({extra}" if extra else f"{type(self).__name__}("]
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            lines.append(f"  ({name}): " + "\n  ".join(sub_repr))
        lines.append(")")
        return "\n".join(lines) if len(lines) > 2 else lines[0] + ")"


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and \
                layers[0] and isinstance(layers[0][0], tuple):
            for name, layer in layers[0]:
                self.add_sublayer(name, layer)
        else:
            for i, layer in enumerate(layers):
                if isinstance(layer, tuple):
                    self.add_sublayer(layer[0], layer[1])
                else:
                    self.add_sublayer(str(i), layer)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self

    def forward(self, *a, **k):
        raise NotImplementedError("LayerList is a container")


class LayerDict(Layer):
    """Ordered dict of sublayers (reference: nn.LayerDict)."""

    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(str(key), layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def clear(self):
        self._sub_layers.clear()

    def pop(self, key):
        layer = self._sub_layers[key]
        del self._sub_layers[key]
        return layer

    def keys(self):
        return self._sub_layers.keys()

    def values(self):
        return self._sub_layers.values()

    def items(self):
        return self._sub_layers.items()

    def update(self, sublayers):
        if isinstance(sublayers, dict):
            sublayers = sublayers.items()
        for k, v in sublayers:
            self.add_sublayer(str(k), v)
        return self


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        return list(self._parameters.values())[idx]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self


import contextlib


@contextlib.contextmanager
def substitute_param_arrays(params, arrays):
    """Temporarily swap each Parameter's backing array (functionalization
    helper: lets jit/grad trace a Layer forward with the params supplied as
    function arguments instead of captured constants). Restores the
    originals on exit."""
    old = [p._data for p in params]
    for p, a in zip(params, arrays):
        p._data = a
    try:
        yield
    finally:
        for p, a in zip(params, old):
            p._data = a
