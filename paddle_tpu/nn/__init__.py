"""paddle.nn namespace. Parity: python/paddle/nn/__init__.py."""
from . import functional
from . import utils
from . import initializer
from .layer.layers import (Layer, LayerDict, LayerList, ParameterList,
                           Sequential)
from .layer.common import *  # noqa: F401,F403
from .layer.norm import *  # noqa: F401,F403
from .layer.conv import *  # noqa: F401,F403
from .layer.activation import *  # noqa: F401,F403
from .layer.loss import *  # noqa: F401,F403
from .layer.pooling import *  # noqa: F401,F403
from .layer.transformer import *  # noqa: F401,F403
from .layer.rnn import *  # noqa: F401,F403
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue
from .utils_ import ParamAttr
