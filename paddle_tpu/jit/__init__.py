"""paddle.jit — to_static / save / load.

Parity: python/paddle/jit/ (dy2static program_translator, jit.save). The
reference AST-transforms Python into a static ProgramDesc; here XLA already is
the static graph, so ``to_static`` compiles the *same eager code* by tracing:

  1. snapshot every persistent tensor (Parameters, optimizer slots, RNG key),
  2. build a pure function (state_in, args) -> (out, state_out) that binds
     tracers into those tensors and runs the user fn — the eager tape,
     ``backward()`` and ``optimizer.step()`` all work under tracing,
  3. jax.jit it with donated state (in-place buffer reuse on TPU),
  4. write the updated state back after each call.

This turns a dygraph train step into ONE fused XLA program: the per-op
dispatch the reference pays per Python call disappears, and AdamW over the
whole pytree becomes the fused multi-tensor form for free.
"""
from __future__ import annotations

import functools
import os
import pickle
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor.tensor import (Tensor, persistent_tensors, _tape)

__all__ = ["to_static", "not_to_static", "ignore_module", "save", "load",
           "TranslatedLayer", "enable_to_static"]

_to_static_enabled = [True]


def enable_to_static(flag: bool):
    _to_static_enabled[0] = bool(flag)


class _TensorRef:
    """Placeholder for a Tensor leaf inside a flattened arg/out spec."""

    __slots__ = ("idx", "stop_gradient")

    def __init__(self, idx, stop_gradient):
        self.idx = idx
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"_TensorRef({self.idx})"


def _tree_flatten_args(args, kwargs):
    leaves = []

    def walk(x):
        if isinstance(x, Tensor):
            leaves.append(x)
            return _TensorRef(len(leaves) - 1, x.stop_gradient)
        if isinstance(x, (list, tuple)):
            return type(x)(walk(i) for i in x)
        if isinstance(x, dict):
            return {k: walk(v) for k, v in x.items()}
        return x
    spec = walk((args, kwargs))
    return leaves, spec


def _tree_unflatten_args(spec, arrays):
    def walk(x):
        if isinstance(x, _TensorRef):
            return Tensor(arrays[x.idx], stop_gradient=x.stop_gradient)
        if isinstance(x, (list, tuple)):
            return type(x)(walk(i) for i in x)
        if isinstance(x, dict):
            return {k: walk(v) for k, v in x.items()}
        return x
    args, kwargs = walk(spec)
    return args, kwargs


def _flatten_out(out):
    arrays = []

    def walk(x):
        if isinstance(x, Tensor):
            arrays.append(x._data)
            return _TensorRef(len(arrays) - 1, x.stop_gradient)
        if isinstance(x, (list, tuple)):
            return type(x)(walk(i) for i in x)
        if isinstance(x, dict):
            return {k: walk(v) for k, v in x.items()}
        return x
    spec = walk(out)
    return arrays, spec


def _unflatten_out(spec, arrays):
    def walk(x):
        if isinstance(x, _TensorRef):
            return Tensor(arrays[x.idx], stop_gradient=x.stop_gradient)
        if isinstance(x, (list, tuple)):
            return type(x)(walk(i) for i in x)
        if isinstance(x, dict):
            return {k: walk(v) for k, v in x.items()}
        return x
    return walk(spec)


def _constrain_to_spec(t, arr):
    """Pin a persistent tensor's post-step placement to its annotated
    PartitionSpec (replicated when unannotated) on the active hybrid mesh.

    Without this, GSPMD's propagation is free to re-shard state outputs —
    e.g. ZeRO-1 annotates only optimizer moments, but params touching
    sharded moments could come back sharded too, silently changing the
    sharding level's semantics. A no-op for already-conforming layouts and
    off-mesh runs."""
    try:
        from ..parallel import current_mesh, _valid_spec
        mesh = current_mesh()
        if mesh is None or not hasattr(arr, "ndim"):
            return arr
        from jax.sharding import NamedSharding, PartitionSpec as P
        spec = getattr(t, "sharding_spec", None)
        pspec = P(*spec) if (spec is not None and
                             _valid_spec(arr, spec, mesh)) else P()
        return jax.lax.with_sharding_constraint(
            arr, NamedSharding(mesh, pspec))
    except Exception:
        return arr


class StaticFunction:
    """Compiled wrapper around an eager function (dygraph → XLA program)."""

    def __init__(self, fn: Callable, input_spec=None, build_strategy=None,
                 backend=None, donate_state: bool = None, static_argnames=None):
        if donate_state is None:
            # default off until the buffer-donation path is re-verified on
            # the tunnel TPU backend; opt in per-function or via env
            import os
            donate_state = os.environ.get("PADDLE_TPU_DONATE") == "1"
        functools.update_wrapper(self, fn)
        self._fn = fn
        self._input_spec = input_spec
        self._donate_state = donate_state
        self._cache: dict = {}
        self._bound_instance = None

    def __get__(self, instance, owner):
        if instance is None:
            return self
        bound = StaticFunction(self._fn.__get__(instance, owner),
                               self._input_spec,
                               donate_state=self._donate_state)
        setattr(instance, self._fn.__name__, bound)
        return bound

    @property
    def dygraph_function(self):
        return self._fn

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled[0]:
            return self._fn(*args, **kwargs)

        arg_tensors, spec = _tree_flatten_args(args, kwargs)
        arg_arrays = [t._data for t in arg_tensors]
        state = persistent_tensors()

        key = (
            tuple((tuple(a.shape), str(a.dtype)) for a in arg_arrays),
            tuple(id(t) for t in state),
            _spec_key(spec),
        )
        entry = self._cache.get(key)
        fresh = entry is None
        if fresh:
            entry = self._build(state, spec, key)
        out_arrays, state_after, new_state = self._execute(
            entry, state, arg_arrays, scan=False, entry_key=key,
            fresh_entry=fresh)
        # state_after may be a superset of state: persistent tensors created
        # during tracing (e.g. lazily-built optimizer slots) are captured as
        # extra outputs; the next call's key sees the superset and recompiles
        # once into the steady signature.
        for t, arr in zip(state_after, new_state):
            t._data = arr
        return _unflatten_out(entry[1][0], out_arrays)

    def _make_pure(self, state, spec, out_spec_box, state_after_box):
        """(state_arrays, arg_arrays) -> (out_arrays, new_state): bind the
        arrays into the persistent tensors, run the eager fn under trace,
        capture outputs + post-step state, restore bindings."""
        fn = self._fn

        def pure(state_arrays, arg_arrays):
            old = [t._data for t in state]
            for t, a in zip(state, state_arrays):
                t._data = a
            _tape.nodes.clear()
            args, kwargs = _tree_unflatten_args(spec, arg_arrays)
            out = fn(*args, **kwargs)
            out_arrays, out_spec = _flatten_out(out)
            out_spec_box[0] = out_spec
            state_after = persistent_tensors()
            state_after_box[0] = state_after
            new_state = [_constrain_to_spec(t, t._data)
                         for t in state_after]
            for t, a in zip(state, old):
                t._data = a
            for t in state_after:
                t.grad = None
            _tape.nodes.clear()
            return out_arrays, new_state
        return pure

    def _execute(self, entry, state, call_arrays, scan, entry_key=None,
                 fresh_entry=True):
        """Run a compiled entry with tape/grad save-restore and the
        donation-aware error contract shared by __call__ and run_steps."""
        jitted, out_spec_box, state_after_box = entry
        state_arrays = [t._data for t in state]
        saved_nodes = _tape.nodes[:]
        saved_grads = [(t, t.grad) for t in state]
        pre_existing = {id(t) for t in state}
        try:
            out_arrays, new_state = jitted(state_arrays, call_arrays)
        except Exception as e:
            _tape.nodes[:] = saved_nodes
            for t, arr in zip(state, state_arrays):
                t._data = arr
            for t, g in saved_grads:
                t.grad = g
            # Persistent tensors CREATED during the failed trace/compile
            # (lazily-built optimizer slots, master weights) hold escaped
            # tracers; left registered they poison every later to_static
            # call in the process with UnexpectedTracerError. Their true
            # values never existed, so roll them back hard: drop from the
            # registry and mark dead (_data=None) — owners that cache them
            # (Optimizer._acc/_seed_master) recreate dead slots on reuse.
            from ..tensor.tensor import (persistent_tensors,
                                         unregister_persistent_many)
            killed = [t for t in persistent_tensors()
                      if id(t) not in pre_existing]
            unregister_persistent_many(killed)
            for t in killed:
                t._data = None
            if killed or fresh_entry:
                # only evict when this call's trace may be inconsistent —
                # a transient EXECUTE failure of a long-good compiled entry
                # must not force a retrace (remote compiles cost minutes)
                state_after_box[0] = None
                self._cache.pop(entry_key, None)
            if scan and "carry" in str(e):
                raise RuntimeError(
                    "run_steps traced new persistent state (e.g. "
                    "lazily-built optimizer slots) inside the scan body; "
                    "call the step function once normally before run_steps "
                    "so state is steady.") from e
            if self._donate_state:
                # execution-time failure after donation: the restored arrays
                # may already be deleted — say so instead of surfacing a
                # bare "Array has been deleted" later
                raise RuntimeError(
                    "to_static step failed after state buffers were donated; "
                    "persistent state may be invalid. Re-create the model/"
                    "optimizer or use to_static(donate_state=False) for "
                    "rollback-on-error semantics.") from e
            raise
        finally:
            _tape.nodes[:] = saved_nodes
            for t, arr in zip(state, state_arrays):
                t._data = arr  # undo any tracer leakage before writeback
            for t, g in saved_grads:
                t.grad = g
        return out_arrays, (state_after_box[0] or state), new_state

    def _build(self, state, spec, key):
        out_spec_box = [None]
        state_after_box = [None]
        pure = self._make_pure(state, spec, out_spec_box, state_after_box)

        # donate the state buffers: params/optimizer slots update in place
        # (XLA aliases input->output), halving steady-state HBM traffic for
        # the weight update; callers never read the pre-step arrays again
        # (writeback below replaces every tensor's _data with the outputs).
        # Opt out with to_static(donate_state=False) to keep pre-step arrays
        # valid (e.g. external references, or rollback-on-error semantics).
        donate = (0,) if self._donate_state else ()
        jitted = jax.jit(pure, donate_argnums=donate)
        entry = (jitted, out_spec_box, state_after_box)
        self._cache[key] = entry
        return entry

    def concrete_program(self, *args, **kwargs):
        return None

    def run_steps(self, k: int, *args, **kwargs):
        """Run k steps of this function in ONE device program (lax.scan over
        the compiled step, persistent state threaded as the carry).

        Every Tensor argument must be stacked to a [k, ...] leading axis —
        step i consumes slice [i]. Returns the per-step outputs stacked the
        same way. This is the TPU analogue of the reference's CUDA-Graph
        whole-iteration capture (paddle/fluid/platform/cuda_graph*, SURVEY
        §2.3 row 29) taken one level further: the host dispatches once per k
        steps, so per-call dispatch/RPC latency amortizes to nothing —
        measurable on remote-tunnel backends where every call is a
        round-trip.

        Call the function once normally first (a warmup step): lazily
        created persistent state (optimizer slots) must exist before the
        scan fixes the carry structure.
        """
        if not _to_static_enabled[0]:
            # eager fallback: python loop over the k slices; outputs are
            # stacked to match the compiled path's [k, ...] convention
            leaves, spec_ = _tree_flatten_args(args, kwargs)
            _check_stacked(leaves, k)
            step_outs = []
            for i in range(k):
                a_i, kw_i = _tree_unflatten_args(
                    spec_, [t._data[i] for t in leaves])
                step_outs.append(self._fn(*a_i, **kw_i))
            flat = [_flatten_out(o) for o in step_outs]
            stacked_arrays = [jnp.stack([f[0][j] for f in flat])
                              for j in range(len(flat[0][0]))]
            return _unflatten_out(flat[0][1], stacked_arrays)

        arg_tensors, spec = _tree_flatten_args(args, kwargs)
        _check_stacked(arg_tensors, k)
        stacked = [t._data for t in arg_tensors]
        state = persistent_tensors()

        key = ("scan", k,
               tuple((tuple(a.shape), str(a.dtype)) for a in stacked),
               tuple(id(t) for t in state), _spec_key(spec))
        entry = self._cache.get(key)
        fresh = entry is None
        if fresh:
            entry = self._build_scan(k, state, spec, key)
        out_arrays, state_after, new_state = self._execute(
            entry, state, stacked, scan=True, entry_key=key,
            fresh_entry=fresh)
        for t, arr in zip(state_after, new_state):
            t._data = arr
        return _unflatten_out(entry[1][0], out_arrays)

    def _build_scan(self, k, state, spec, key):
        out_spec_box = [None]
        state_after_box = [None]
        pure = self._make_pure(state, spec, out_spec_box, state_after_box)

        def scanned(state_arrays, stacked):
            def body(carry, xs):
                out_arrays, new_state = pure(carry, list(xs))
                return new_state, out_arrays
            final_state, outs = jax.lax.scan(body, state_arrays,
                                             tuple(stacked), length=k)
            return outs, final_state

        donate = (0,) if self._donate_state else ()
        jitted = jax.jit(scanned, donate_argnums=donate)
        entry = (jitted, out_spec_box, state_after_box)
        self._cache[key] = entry
        return entry


def _check_stacked(tensors, k):
    for t in tensors:
        if len(t.shape) == 0 or t.shape[0] != k:
            raise ValueError(
                f"run_steps({k}): every Tensor arg needs a [k, ...] leading "
                f"axis (scalars included — stack per-step values), got "
                f"shape {list(t.shape)}")


def _spec_key(spec):
    def walk(x):
        if isinstance(x, (list, tuple)):
            return tuple(walk(i) for i in x)
        if isinstance(x, dict):
            return tuple(sorted((k, walk(v)) for k, v in x.items()))
        if isinstance(x, (int, float, str, bool, type(None))):
            return x
        return str(x)
    return walk(spec)


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """Decorator/wrapper compiling an eager function into one XLA program."""
    donate = kwargs.get("donate_state", None)

    def decorate(fn):
        if isinstance(fn, StaticFunction):
            return fn
        from ..nn.layer.layers import Layer
        if isinstance(fn, Layer):
            layer = fn
            layer.forward = StaticFunction(layer.forward, input_spec,
                                           donate_state=donate)
            return layer
        return StaticFunction(fn, input_spec, donate_state=donate)
    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    pass


class TranslatedLayer:
    """Loaded inference bundle (jit.save counterpart)."""

    def __init__(self, state_dict, forward_fn=None, meta=None):
        self._state = state_dict
        self._meta = meta or {}

    def state_dict(self):
        return self._state


def save(layer, path, input_spec=None, **configs):
    """jit.save parity: persist params (+ structure note) for inference.

    Reference exports a ProgramDesc; the TPU-native equivalent persists the
    state_dict and (optionally) an input spec — reload with jit.load, rebind
    to the model class, and jax.jit recompiles on first call (XLA is the
    portable program format here, recompiled per topology).
    """
    from ..framework.io import save as fsave
    from ..nn.layer.layers import Layer
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if isinstance(layer, Layer):
        sd = layer.state_dict()
    else:
        sd = layer
    fsave(sd, path + ".pdparams")
    meta = {"input_spec": repr(input_spec), "class": type(layer).__name__}
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump(meta, f)


def load(path, **configs):
    from ..framework.io import load as fload
    sd = fload(path + ".pdparams")
    meta = {}
    if os.path.exists(path + ".pdmodel"):
        with open(path + ".pdmodel", "rb") as f:
            meta = pickle.load(f)
    return TranslatedLayer(sd, meta=meta)
