"""Paged KV cache: ONE block pool + per-slot block tables.

Capability parity: vLLM's PagedAttention memory architecture, realized
against this repo's stacked fixed-shape serving stack. PRs 2-5 stored
KV three different ways — the dense per-slot ring [L, 2, B, H, Smax, D]
(generation.py), the prefix block pool [L, 2, NB, H, Bt, D]
(prefix_cache.py), and spec-verify's write-masked scatters — stitched
together by compiled gather-copies. Here they collapse into ONE paged
layout:

  * ``BlockPool`` — the single device pool [L, 2, NBtotal, H, Bt, D]
    (+ mirrored int8 scales [L, 2, NBtotal, H, 1, Bt]) plus a host
    free-list allocator with per-block refcounts. A block is storage
    for Bt consecutive token positions of ONE sequence; who uses it is
    pure host bookkeeping (refcounts), so prefix sharing and
    copy-on-write forking are index operations, not data movement.
  * per-slot ``block_tables`` [B, Smax/Bt] int32 live in the engine as
    pure data: position ``s`` of slot ``b`` resolves to
    ``pool[l, kv, tables[b, s // Bt], h, s % Bt, :]``. Unmapped entries
    hold the sentinel ``num_blocks`` — a write through a sentinel (or a
    masked row sent to position Smax) lands out of bounds and is
    DROPPED (``mode="drop"``), the same write-mask discipline as the
    dense path, and the FIFTH client of the decode_attention
    ``cache_lens < Smax`` clamp inventory.
  * ``PagedPrefixStore`` / ``PagedPrefixCache`` — the radix-store
    machinery of prefix_cache.py re-pointed at the shared pool: adopt
    = writing the matched chain's pool indices into the slot's table
    (+refcount; ZERO device copies), publish = taking a store
    reference on the slot's own prompt blocks (zero-copy commit).
    Store eviction merely drops the store's reference; the block
    physically frees when its last user (slot table or store) lets go.
  * copy-on-write: a slot about to write into a block with
    refcount > 1 first allocates a private block and copies just that
    block (ONE fixed-shape compiled dispatch, src/dst as data). In the
    steady serving flow writes never land in shared blocks (adoption
    and publication are block-aligned and strictly below every write
    position), so COW exists as the invariant guard — and as the
    primitive that makes ``ServingEngine.fork_slot`` (parallel
    sampling / N-best) nearly free.

Memory math: the dense layout reserves ``B x Smax`` positions whether
used or not; the pool holds ``NBtotal x Bt`` positions shared by
everything (slots, prefixes, forks — refcounted blocks counted once),
so slot capacity is bounded by actual token residency, not slot count.
"""
from __future__ import annotations

import os

import numpy as np

from .prefix_cache import PrefixNode, PrefixStore

__all__ = ["BlockPool", "PagedPrefixStore", "PagedPrefixCache",
           "counted_jit", "flat_gather_view"]


def counted_jit(jit_cache, key, build, bump, donate=()):
    """ONE owner for the retrace-spy jit wrapper the serving stack's
    zero-retrace contracts are asserted against: ``bump()`` runs at
    TRACE time only (python side effects execute only while tracing),
    so the counter counts executable builds, not calls. Donation is
    suppressed through the axon tunnel, where donated buffers are
    observed to hang (BASELINE.md r2) — keeping that condition in one
    place means the engine's and the pool's spies cannot drift."""
    import jax
    fn = jit_cache.get(key)
    if fn is None:
        inner = build()

        def spied(*args):
            bump()
            return inner(*args)
        tunneled = bool(os.environ.get("PALLAS_AXON_POOL_IPS"))
        fn = jax.jit(spied, donate_argnums=() if tunneled else donate)
        jit_cache[key] = fn
    return fn


def _pool_sharding():
    """The pool's head-sharded layout under an active mp mesh
    (NamedSharding over P(None, None, None, 'mp', None, None) — axis 3
    is the head axis of both the kv blocks and the int8 scales), else
    None. The pool executables below constrain their kv/sc outputs
    with it so every donation round-trip hands back a buffer in the
    SAME layout it consumed — no silent resharding between a COW copy
    / migration write and the next engine step. All the block-index
    slices run on the (replicated) NB axis, so none of these dispatches
    needs a collective."""
    from ..parallel import current_mesh
    mesh = current_mesh()
    if mesh is None or dict(mesh.shape).get("mp", 1) < 2:
        return None
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P(None, None, None, "mp", None, None))


class BlockPool:
    """Host allocator for the ONE paged KV pool.

    Owns the free list and per-block refcounts; the device arrays
    themselves are built by ``FusedDecoder.init_paged_cache`` and ride
    the engine's compiled steps as donated buffers (the pool object
    must stay pure host state so it can be shared/inspected without
    touching the device)."""

    def __init__(self, num_blocks, block_tokens, max_seq_len):
        self.num_blocks = int(num_blocks)
        self.block_tokens = int(block_tokens)
        self.smax = int(max_seq_len)
        if self.num_blocks < 1:
            raise ValueError("BlockPool needs num_blocks >= 1")
        bt = self.block_tokens
        if bt < 1 or bt & (bt - 1):
            raise ValueError(
                f"BlockPool block_tokens must be a power of two >= 1, "
                f"got {bt} (it is the serving engine's prefill_cap — "
                "ONE knob for the prefill ladder, the prefix blocks, "
                "and the pool block size)")
        if self.smax % bt:
            # fail HERE with a clear message instead of a downstream
            # gather OOB: a non-aligned table would leave a ragged last
            # block whose positions index past Bt
            raise ValueError(
                f"BlockPool: max_seq_len {self.smax} must be a multiple "
                f"of block_tokens {bt} — the per-slot block table has "
                f"Smax/Bt entries and position s resolves to "
                "(table[s // Bt], s % Bt); a ragged tail block would "
                "gather out of bounds")
        self.refcounts = np.zeros(self.num_blocks, np.int32)
        # pop() from the end: low ids hand out first (stable tests)
        self._free = list(range(self.num_blocks - 1, -1, -1))
        self._jit_cache = {}
        self.trace_count = 0             # COW copy-path retrace spy
        self.used_peak = 0               # residency high-water mark

    # ---------------------------------------------------------- allocator
    @property
    def free_count(self):
        return len(self._free)

    @property
    def used(self):
        return self.num_blocks - len(self._free)

    def alloc(self, n=1):
        """Take ``n`` blocks (refcount 1 each); None if the free list is
        short — all-or-nothing, the caller reclaims/backs off."""
        if len(self._free) < int(n):
            return None
        ids = [self._free.pop() for _ in range(int(n))]
        self.refcounts[ids] = 1
        if self.used > self.used_peak:
            self.used_peak = self.used
        return ids

    def ref(self, blocks):
        for b in blocks:
            if self.refcounts[b] < 1:
                raise RuntimeError(
                    f"BlockPool.ref on free block {int(b)} — a table or "
                    "store entry outlived its allocation")
            self.refcounts[b] += 1

    def deref(self, blocks):
        for b in blocks:
            if self.refcounts[b] < 1:
                raise RuntimeError(
                    f"BlockPool refcount underflow on block {int(b)}")
            self.refcounts[b] -= 1
            if self.refcounts[b] == 0:
                self._free.append(int(b))

    def stats(self):
        return {"blocks_total": self.num_blocks, "blocks_used": self.used,
                "blocks_free": self.free_count}

    def gauges(self):
        """Prometheus-ready pool gauges (telemetry.render_prometheus and
        telemetry.snapshot consume these): residency now + the lifetime
        high-water mark — the number an operator sizes
        ``PADDLE_SERVING_KV_BLOCKS`` against."""
        return {"kv_blocks_total": self.num_blocks,
                "kv_blocks_used": self.used,
                "kv_blocks_free": self.free_count,
                "kv_blocks_used_peak": self.used_peak}

    # -------------------------------------------------------- the COW copy
    def _bump_traces(self):
        self.trace_count += 1

    @staticmethod
    def _pin(out, sh):
        """Constrain the pool arrays of ``out`` to the head-sharded
        layout ``sh`` (no-op when unsharded) — see _pool_sharding."""
        if sh is None:
            return out
        import jax
        out = dict(out, kv=jax.lax.with_sharding_constraint(
            out["kv"], sh))
        if "sc" in out:
            out["sc"] = jax.lax.with_sharding_constraint(out["sc"], sh)
        return out

    def _build_copy(self):
        import jax
        sh = _pool_sharding()

        def copy(caches, src, dst):
            kv = caches["kv"]
            L, _, _, H, Bt, D = kv.shape
            blk = jax.lax.dynamic_slice(kv, (0, 0, src, 0, 0, 0),
                                        (L, 2, 1, H, Bt, D))
            out = dict(caches, kv=jax.lax.dynamic_update_slice(
                kv, blk, (0, 0, dst, 0, 0, 0)))
            if "sc" in caches:
                sc = caches["sc"]
                sb = jax.lax.dynamic_slice(sc, (0, 0, src, 0, 0, 0),
                                           (L, 2, 1, H, 1, Bt))
                out["sc"] = jax.lax.dynamic_update_slice(
                    sc, sb, (0, 0, dst, 0, 0, 0))
            return self._pin(out, sh)
        return copy

    def copy_block(self, caches, src, dst):
        """Device-copy pool block ``src`` -> ``dst`` (kv + int8 scales)
        in ONE fixed-shape dispatch; src/dst are data. The caches dict
        (WITHOUT the table — pure pool arrays) is donated and the
        updated dict returned. This is the entire cost of a COW fault:
        one block, not a row, not the pool."""
        import jax.numpy as jnp
        fn = counted_jit(self._jit_cache, ("copy",), self._build_copy,
                         self._bump_traces, donate=(0,))
        return fn(caches, jnp.asarray(src, jnp.int32),
                  jnp.asarray(dst, jnp.int32))

    # ------------------------------------------- block transfer (migration)
    # The live-migration primitive (and the groundwork for cross-replica
    # prefix shipping): ONE pool block moves device <-> host per
    # fixed-shape dispatch with the block index as DATA, so exporting a
    # whole slot is n_blocks reuses of one executable each way — zero
    # retraces across any sequence length, same discipline as copy_block.
    def _build_read(self):
        import jax
        # the exported block leaves as FULLY REPLICATED data (P() on
        # every axis): read_block hands it to np.asarray for the host
        # migration payload, and a replicated output makes that one
        # device-local copy instead of a cross-device assembly
        from ..parallel import current_mesh
        mesh = current_mesh()
        rep = None
        if mesh is not None and dict(mesh.shape).get("mp", 1) >= 2:
            from jax.sharding import NamedSharding, PartitionSpec as P
            rep = NamedSharding(mesh, P())

        def read(caches, src):
            kv = caches["kv"]
            L, _, _, H, Bt, D = kv.shape
            out = {"kv": jax.lax.dynamic_slice(kv, (0, 0, src, 0, 0, 0),
                                               (L, 2, 1, H, Bt, D))}
            if "sc" in caches:
                out["sc"] = jax.lax.dynamic_slice(
                    caches["sc"], (0, 0, src, 0, 0, 0),
                    (L, 2, 1, H, 1, Bt))
            if rep is not None:
                out = {k: jax.lax.with_sharding_constraint(v, rep)
                       for k, v in out.items()}
            return out
        return read

    def read_block(self, caches, src):
        """Gather ONE pool block to host arrays ``{"kv"[, "sc"]}`` —
        the migration-export half of the transfer primitive. The caches
        are NOT donated (the pool keeps serving while a slot exports)."""
        import jax.numpy as jnp
        fn = counted_jit(self._jit_cache, ("read",), self._build_read,
                         self._bump_traces)
        out = fn(caches, jnp.asarray(src, jnp.int32))
        return {k: np.asarray(v) for k, v in out.items()}

    def _build_write(self):
        import jax
        sh = _pool_sharding()

        def write(caches, blk, dst):
            kv = caches["kv"]
            out = dict(caches, kv=jax.lax.dynamic_update_slice(
                kv, blk["kv"].astype(kv.dtype), (0, 0, dst, 0, 0, 0)))
            if "sc" in caches:
                sc = caches["sc"]
                out["sc"] = jax.lax.dynamic_update_slice(
                    sc, blk["sc"].astype(sc.dtype), (0, 0, dst, 0, 0, 0))
            return self._pin(out, sh)
        return write

    def write_block(self, caches, block, dst):
        """Scatter one exported host block into pool block ``dst`` —
        the migration-import half. The caches dict is donated like every
        other pool-mutating dispatch; returns the updated dict. The
        block must match this pool's layout exactly (the engine-level
        import validates shapes with a readable error first)."""
        import jax.numpy as jnp
        fn = counted_jit(self._jit_cache, ("write",), self._build_write,
                         self._bump_traces, donate=(0,))
        blk = {k: jnp.asarray(v) for k, v in block.items()
               if k in ("kv", "sc")}
        return fn(caches, blk, jnp.asarray(dst, jnp.int32))


class PagedPrefixStore(PrefixStore):
    """The radix store of prefix_cache.py, re-pointed at the SHARED
    BlockPool: a node's ``block`` is a pool id the store holds one
    refcount on. ``num_blocks`` becomes the store's PIN BUDGET (how
    many pool blocks the prefix cache may keep alive), not a private
    free list — there is exactly one physical pool.

    Publication is zero-copy (``publish`` takes a reference on the
    slot's own block), and eviction merely drops the store's
    reference: a block shared with a live slot table stays resident
    until that slot finishes. ``reclaim`` is the memory-pressure hook
    the engine calls when the pool's free list runs short — prefix
    blocks are cache, droppable by LRU, never load-bearing."""

    def __init__(self, num_blocks, block_tokens, pool):
        super().__init__(num_blocks, block_tokens)
        if pool.block_tokens != int(block_tokens):
            raise ValueError(
                f"PagedPrefixStore block_tokens={int(block_tokens)} but "
                f"the shared BlockPool has block_tokens="
                f"{pool.block_tokens} — the prefix blocks ARE pool "
                "blocks, the sizes must be ONE value")
        self.pool = pool
        self._free = []                  # no private ids in paged mode
        self._pinned = 0

    def insert(self, tokens):
        raise NotImplementedError(
            "PagedPrefixStore has no private blocks to allocate — "
            "publication is zero-copy; use publish(tokens, block_ids) "
            "with the owning slot's pool block ids")

    def publish(self, tokens, block_ids):
        """Paged commit: walk/extend the radix chain over ``tokens``'
        full blocks, taking a store reference on ``block_ids[i]`` (the
        owning slot's pool block) for every node that does not exist
        yet. Returns ``[(node, is_new), ...]`` root-first — no device
        copy ever happens; dedup hits simply resolve to the already-
        published block. Stops early when the pin budget is exhausted
        and nothing is evictable (partial chains are valid, as in the
        dense store)."""
        out = []
        node = self._root
        keys = self._blocks_of(tokens)
        try:
            for i, key in enumerate(keys):
                if i >= len(block_ids):
                    break
                child = node.children.get(key)
                if child is None:
                    if self._pinned >= self.num_blocks:
                        victim = self._lru_evictable_leaf()
                        if victim is None:
                            break        # budget full, nothing cold
                        self._evict(victim)
                    blk = int(block_ids[i])
                    self.pool.ref([blk])
                    self._pinned += 1
                    child = PrefixNode(key, node, blk)
                    node.children[key] = child
                    self._update_evictable(node)
                    self.committed_blocks += 1
                    out.append((child, True))
                else:
                    out.append((child, False))
                self._touch(child)
                # pin the chain under construction (same rationale as
                # the dense insert: a long chain must not evict its own
                # fresh tail to pin the next block)
                self.acquire((child,))
                node = child
        finally:
            self.release(n for n, _ in out)
        return out

    def _evict(self, node):
        blk = super()._evict(node)
        self._pinned -= 1
        # drop the STORE's reference only: a slot still mapping this
        # block keeps it resident; it frees when the last user derefs
        self.pool.deref([blk])
        return blk

    def reclaim(self, n_free):
        """Evict LRU refcount-0 leaves until the POOL free list grew by
        ``n_free`` blocks (or nothing evictable remains). Prefers
        store-only blocks (pool refcount 1 — evicting them actually
        frees memory); falls back to shared nodes to unlock the
        eviction cascade (a parent becomes a leaf only once its
        children are gone). Returns how many blocks were freed."""
        start = self.pool.free_count
        while self.pool.free_count - start < int(n_free):
            singles = [x for x in self._evictable
                       if self.pool.refcounts[x.block] == 1]
            pickable = singles or self._evictable
            if not pickable:
                break
            self._evict(min(pickable, key=lambda x: x.last_use))
        return self.pool.free_count - start

    def stats(self):
        s = super().stats()
        # budget headroom, not a private free list (the POOL owns the
        # physical free list; leak visibility lives in the engine's
        # kv_blocks_used + kv_blocks_free == NBtotal reconciliation)
        s["blocks_free"] = self.num_blocks - s["blocks_used"]
        return s


class PagedPrefixCache:
    """The paged twin of prefix_cache.PrefixCache: same engine-facing
    surface (lookup / hit counters / ``store`` / ``block_tokens`` /
    ``trace_count``), but adopt and publish are INDEX operations on the
    slot's block table — zero device dispatches, zero copies. One
    PagedPrefixCache belongs to one engine (the tables do); the dense
    PrefixCache remains the cross-engine-shareable flavor."""

    def __init__(self, num_blocks, block_tokens, pool):
        self.store = PagedPrefixStore(num_blocks, block_tokens, pool)
        self.pool = pool
        self.num_blocks = int(num_blocks)
        self.block_tokens = int(block_tokens)
        self.trace_count = 0             # index writes never trace

    def lookup(self, tokens):
        """Longest ADOPTABLE chain — prefix_cache.lookup_adoptable is
        the ONE owner of the cap + counter rules, so the dense and
        paged hit semantics cannot drift."""
        from .prefix_cache import lookup_adoptable
        return lookup_adoptable(self.store, self.block_tokens, tokens)

    def adopt_into(self, tables, slot, nodes):
        """THE zero-copy prefix hit: write the chain's pool indices
        into the slot's table row and take a per-slot reference on each
        block. Returns the adopted token count. (The dense path's
        compiled gather-splat is an index write here — a hit costs
        nanoseconds of host bookkeeping, not an HBM block copy.)"""
        ids = [nd.block for nd in nodes]
        self.pool.ref(ids)
        tables[slot, :len(ids)] = ids
        return len(ids) * self.block_tokens

    def publish_from(self, tables, slot, tokens):
        """Zero-copy commit-on-prefill: publish every full block of
        ``tokens`` by referencing the slot's OWN pool blocks. Dedup
        hits against an already-published twin switch the slot's table
        onto the shared block and free the private copy (storage
        dedup — the intra-admission gang case). Returns #new blocks."""
        t = np.asarray(tokens).reshape(-1)
        nfull = t.size // self.block_tokens
        ids = [int(tables[slot, i]) for i in range(nfull)]
        if any(i >= self.pool.num_blocks for i in ids):
            raise RuntimeError(
                "publish_from before the slot's prompt blocks were "
                "mapped — prefill must land before publication")
        plan = self.store.publish(t, ids)
        new = 0
        for i, (node, is_new) in enumerate(plan):
            if is_new:
                new += 1
            elif ids[i] != node.block:
                # the slot computed a private copy of content someone
                # already published: point at the shared block, drop
                # the duplicate (decode never writes below plen, so
                # sharing a full prompt block is always safe)
                self.pool.ref([node.block])
                self.pool.deref([ids[i]])
                tables[slot, i] = node.block
        return new


def flat_gather_view(pool_l, tbl, tslot, smax, sc_l=None):
    """Per-TOKEN gather-through-table view for the flat budget core's
    dense-fallback attention (generation._build_flat_budget_core):
    resolve each flat-stream token's slot through the block tables and
    materialize its full [Smax]-position K/V row.

    pool_l: [2, NB, Hk, Bt, D] (ONE layer's pool slice); tbl:
    [B, Smax/Bt] int32 per-slot tables (sentinel NB for unmapped);
    tslot: [T] int32 per-token slot ids ALREADY CLAMPED in-bounds
    (pad tokens point at any valid slot — their positions are masked
    by the caller); sc_l: optional [2, NB, Hk, 1, Bt] int8 dequant
    scales (the int8 pool flavor — quantized pools come through here
    whenever decode_attention.paged_flat_i8_is_supported refuses the
    shape, e.g. Bt below the int8 sublane minimum of 32; this view is
    the parity ORACLE the flat i8 Pallas kernel is tested against).
    Returns [2, T, Hk, Smax, D] float32 (dequantized when sc_l given).

    Sentinel/unmapped table entries clamp to an arbitrary block —
    their positions are >= the row's lens and masked by the caller's
    block-causal mask, exactly like the row-aligned gather fallback."""
    import jax.numpy as jnp
    nb = pool_l.shape[1]
    hk, bt, d = pool_l.shape[2], pool_l.shape[3], pool_l.shape[4]
    rows = jnp.take(tbl, tslot, axis=0)               # [T, Smax/Bt]
    tc = jnp.minimum(rows, nb - 1)
    kvg = jnp.take(pool_l, tc, axis=1)          # [2, T, Nblk, Hk, Bt, D]
    kvg = jnp.transpose(kvg, (0, 1, 3, 2, 4, 5)).reshape(
        2, tslot.shape[0], hk, smax, d)
    if sc_l is None:
        return kvg.astype(jnp.float32)
    scg = jnp.take(sc_l, tc, axis=1)            # [2, T, Nblk, Hk, 1, Bt]
    scg = jnp.transpose(scg, (0, 1, 3, 4, 2, 5)).reshape(
        2, tslot.shape[0], hk, 1, smax)
    return kvg.astype(jnp.float32) * jnp.swapaxes(scg, -1, -2)
